"""Elementwise / normalization / positional ops.

These deliberately stay as plain jnp expressions: XLA fuses them into the
surrounding matmuls, which is the right call on TPU (HBM-bandwidth-bound
elementwise work should never round-trip). Pallas is reserved for ops XLA
can't fuse well (attention, see ops/flash_attention.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm in float32 accumulation regardless of input dtype."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * scale.astype(jnp.float32)).astype(dtype)


def rope_freqs(head_dim: int, theta: float = 10000.0) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2] (float32)."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def rope(
    x: jnp.ndarray, positions: jnp.ndarray, theta: float = 10000.0
) -> jnp.ndarray:
    """Rotary position embedding, HF-Llama "rotate_half" convention.

    x: [..., seq, heads, head_dim]; positions: broadcastable to [..., seq].
    The two rotated halves are x[..., :d/2] and x[..., d/2:] (NOT interleaved
    pairs), matching transformers' LlamaRotaryEmbedding so HF checkpoints load
    without permutation.
    """
    head_dim = x.shape[-1]
    freqs = rope_freqs(head_dim, theta)  # [d/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., seq, d/2]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate((x1 * cos - x2 * sin, x2 * cos + x1 * sin), axis=-1)
    return out.astype(x.dtype)


def swiglu(gate: jnp.ndarray, up: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.silu(gate) * up


def layer_norm(
    x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5
) -> jnp.ndarray:
    """Classic LayerNorm (mean-centered, affine) in float32 accumulation —
    the GPT/OPT-family normalizer (Llama uses rms_norm)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    normed = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (
        normed * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    ).astype(dtype)


def lora_delta(h, adapter, scale, out_einsum: str):
    """LoRA low-rank update h @ A @ B * scale; shared by every model family
    (adapter trees come from train/lora.py)."""
    down = jnp.einsum("bsd,dr->bsr", h, adapter["a"])
    return jnp.einsum(out_einsum, down, adapter["b"]) * scale


def batched_lora_einsum(out_einsum: str) -> str:
    """The per-row form of a lora_delta output einsum: the second
    operand (the gathered B matrices) grows a leading batch axis, e.g.
    'bsr,rhk->bshk' -> 'bsr,brhk->bshk'."""
    lhs, _, out = out_einsum.partition("->")
    first, _, second = lhs.partition(",")
    return f"{first},b{second}->{out}"


def lora_delta_indexed(h, adapter, scale, out_einsum: str, adapter_ids):
    """Per-batch-row LoRA update for multi-tenant serving
    (serve/adapters.py): the adapter leaves carry a leading adapter-slot
    axis (`a: [A, in, r]`, `b: [A, r, ...out]`) and `adapter_ids` [B]
    gathers each row's pair, so one einsum applies every tenant's delta
    in the same dispatch. Slot 0 is the all-zero identity adapter —
    rows without a tenant gather zeros and stay exactly the base model."""
    a = jnp.take(adapter["a"], adapter_ids, axis=0)  # [B, in, r]
    b = jnp.take(adapter["b"], adapter_ids, axis=0)  # [B, r, ...out]
    down = jnp.einsum("bsd,bdr->bsr", h, a)
    return jnp.einsum(batched_lora_einsum(out_einsum), down, b) * scale
