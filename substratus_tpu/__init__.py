"""substratus_tpu — a TPU-native ML orchestration + runtime framework.

Re-implements the capability surface of substratusai/substratus (a Go
Kubernetes operator, reference at /root/reference) TPU-first, and brings the
ML runtime that the reference delegated to external container images
(model-loader/trainer/server, SURVEY.md §2.2) in-repo as JAX/XLA/Pallas code.

Layout (top of SURVEY.md §7):
  api/         CR types: Dataset, Model, Notebook, Server (reference: api/v1)
  controller/  reconcilers + controller runtime (reference: internal/controller)
  kube/        minimal K8s REST client + in-memory fake apiserver (envtest)
  cloud/       cloud abstraction: gcp, local (reference: internal/cloud)
  sci/         storage/identity gRPC service (reference: internal/sci)
  resources/   CR resources -> pod specs, TPU topology (internal/resources)
  models/      JAX model zoo: llama family flagship
  ops/         attention (XLA + Pallas flash/ring), quant, sampling
  parallel/    mesh building, named shardings, collectives, distributed init
  train/       pjit trainer: FSDP/TP, LoRA, orbax checkpointing
  serve/       continuous-batching inference engine + OpenAI-compatible HTTP
  load/        HF safetensors -> sharded jax params -> artifacts
  cli/         `sub` CLI (reference: internal/cli)
  tools/       container contract tools: nbwatch (reference: containertools)
"""

__version__ = "0.13.0"
