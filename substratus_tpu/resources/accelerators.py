"""TPU accelerator catalog: generations, topologies, GKE labels.

TPU-first replacement for the reference's GPU table (gpu_info.go:14-48 maps
a100/t4/l4 -> GKE accelerator nodeSelectors). TPUs add two notions GPUs don't
have: a *topology* (the physical slice shape, e.g. 4x4) and *multi-host*
slices (chips beyond one VM => a JobSet of pods that must gang-schedule).

Sources for the constants: public GKE TPU docs (machine shapes and
`cloud.google.com/gke-tpu-accelerator` / `gke-tpu-topology` labels).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class TPUInfo:
    generation: str  # "v4" | "v5e" | "v5p" | "v6e"
    gke_accelerator: str  # nodeSelector value
    chips_per_host: int  # chips on one VM (one pod per host)
    hbm_gb_per_chip: int
    # topology name -> total chips
    topologies: Dict[str, int]


CATALOG: Dict[str, TPUInfo] = {
    "v4": TPUInfo(
        "v4", "tpu-v4-podslice", 4, 32,
        {"2x2x1": 4, "2x2x2": 8, "2x2x4": 16, "2x4x4": 32, "4x4x4": 64, "4x4x8": 128},
    ),
    "v5e": TPUInfo(
        "v5e", "tpu-v5-lite-podslice", 4, 16,
        {"1x1": 1, "2x2": 4, "2x4": 8, "4x4": 16, "4x8": 32, "8x8": 64, "8x16": 128, "16x16": 256},
    ),
    "v5p": TPUInfo(
        "v5p", "tpu-v5p-slice", 4, 95,
        {"2x2x1": 4, "2x2x2": 8, "2x2x4": 16, "2x4x4": 32, "4x4x4": 64, "4x4x8": 128},
    ),
    "v6e": TPUInfo(
        "v6e", "tpu-v6e-slice", 4, 32,
        {"1x1": 1, "2x2": 4, "2x4": 8, "4x4": 16, "4x8": 32, "8x8": 64, "8x16": 128, "16x16": 256},
    ),
}


def tpu_info(generation: str) -> TPUInfo:
    gen = generation.lower()
    if gen not in CATALOG:
        raise ValueError(
            f"unknown TPU type {generation!r}; known: {sorted(CATALOG)}"
        )
    return CATALOG[gen]


def derive_topology(generation: str, chips: int) -> str:
    """Smallest catalog topology with >= chips (exact match preferred)."""
    info = tpu_info(generation)
    exact = [t for t, c in info.topologies.items() if c == chips]
    if exact:
        return exact[0]
    bigger = sorted(
        ((c, t) for t, c in info.topologies.items() if c > chips)
    )
    if not bigger:
        raise ValueError(
            f"no {generation} topology holds {chips} chips "
            f"(max {max(info.topologies.values())})"
        )
    return bigger[0][1]


def validate_tpu(generation: str, chips: int, topology: Optional[str]) -> Tuple[str, int, int]:
    """Returns (topology, num_hosts, chips_per_host_pod).

    num_hosts > 1 means a multi-host slice: the workload must run as a JobSet
    of num_hosts pods, each requesting chips_per_host_pod `google.com/tpu`.
    """
    info = tpu_info(generation)
    topo = topology or derive_topology(generation, chips)
    if topo not in info.topologies:
        raise ValueError(
            f"unknown topology {topo!r} for {generation}; known: "
            f"{sorted(info.topologies)}"
        )
    total = info.topologies[topo]
    if chips > total:
        raise ValueError(f"topology {topo} holds {total} chips < requested {chips}")
    if total <= info.chips_per_host:
        return topo, 1, total
    if total % info.chips_per_host:
        raise ValueError(f"topology {topo} not divisible into hosts")
    return topo, total // info.chips_per_host, info.chips_per_host
