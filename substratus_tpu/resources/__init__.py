from substratus_tpu.resources.accelerators import (
    TPUInfo,
    tpu_info,
    validate_tpu,
)
from substratus_tpu.resources.apply import apply_resources

__all__ = ["TPUInfo", "tpu_info", "validate_tpu", "apply_resources"]
