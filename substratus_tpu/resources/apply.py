"""CR Resources -> pod spec translation (reference: internal/resources/
resources.go:13-125).

Reference behavior carried over: cpu/memory/ephemeral requests, spot
toleration for autoscaling, builder pod sizing. TPU-first departure: instead
of `nvidia.com/gpu` + GKE accelerator nodeSelector (resources.go:39-65), TPU
asks emit `google.com/tpu` requests+limits plus the
`cloud.google.com/gke-tpu-accelerator` / `gke-tpu-topology` nodeSelectors;
multi-host slices return host-count metadata the workload builders use to
emit a JobSet instead of a single-pod Job (see controller/workloads.py).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from substratus_tpu.api.common import Resources
from substratus_tpu.resources.accelerators import tpu_info, validate_tpu

# GPU nodeSelector parity table (reference gpu_info.go); retained so mixed
# clusters keep working, though this framework's images are TPU-native.
GPU_NODE_SELECTORS = {
    "a100": "nvidia-tesla-a100",
    "t4": "nvidia-tesla-t4",
    "l4": "nvidia-l4",
}


def apply_resources(
    pod_metadata: Dict[str, Any],
    pod_spec: Dict[str, Any],
    container: Dict[str, Any],
    cloud_name: str,
    resources: Optional[Resources],
) -> Dict[str, Any]:
    """Mutates pod/container dicts in place; returns slice info:
    {"num_hosts": N, "chips_per_host": C, "topology": T, "generation": G}
    (num_hosts == 1 for non-TPU or single-host asks)."""
    info = {"num_hosts": 1, "chips_per_host": 0, "topology": None, "generation": None}
    res = container.setdefault("resources", {})
    requests = res.setdefault("requests", {})
    limits = res.setdefault("limits", {})
    if resources is None:
        return info

    if resources.cpu:
        requests["cpu"] = str(resources.cpu)
    if resources.memory:
        requests["memory"] = f"{resources.memory}Gi"
    if resources.disk:
        requests["ephemeral-storage"] = f"{resources.disk}Gi"

    if resources.tpu:
        t = resources.tpu
        topo, num_hosts, chips_per_host = validate_tpu(
            t.type, t.chips, t.topology
        )
        requests["google.com/tpu"] = str(chips_per_host)
        limits["google.com/tpu"] = str(chips_per_host)
        if cloud_name == "gcp":
            sel = pod_spec.setdefault("nodeSelector", {})
            sel["cloud.google.com/gke-tpu-accelerator"] = tpu_info(
                t.type
            ).gke_accelerator
            sel["cloud.google.com/gke-tpu-topology"] = topo
            # Spot toleration lets node auto-provisioning use preemptible
            # slices (reference resources.go:54-63 did this for GPUs);
            # checkpoint-resume (train/checkpoints.py) makes this safe.
            pod_spec.setdefault("tolerations", []).append(
                {
                    "key": "cloud.google.com/gke-spot",
                    "operator": "Equal",
                    "value": "true",
                    "effect": "NoSchedule",
                }
            )
        info.update(
            num_hosts=num_hosts,
            chips_per_host=chips_per_host,
            topology=topo,
            generation=t.type,
        )
    elif resources.gpu and resources.gpu.count:
        g = resources.gpu
        requests["nvidia.com/gpu"] = str(g.count)
        limits["nvidia.com/gpu"] = str(g.count)
        if cloud_name == "gcp" and g.type in GPU_NODE_SELECTORS:
            sel = pod_spec.setdefault("nodeSelector", {})
            sel["cloud.google.com/gke-accelerator"] = GPU_NODE_SELECTORS[g.type]
            pod_spec.setdefault("tolerations", []).append(
                {
                    "key": "cloud.google.com/gke-spot",
                    "operator": "Equal",
                    "value": "true",
                    "effect": "NoSchedule",
                }
            )
    return info


def builder_resources() -> Dict[str, Any]:
    """Image-builder pod sizing (reference resources.go:74-91)."""
    return {
        "requests": {
            "cpu": "2",
            "memory": "12Gi",
            "ephemeral-storage": "100Gi",
        }
    }
