"""Admission control primitives: per-key token buckets and request
deadlines.

Token buckets are per API key (the `Authorization: Bearer` token or
`x-api-key` header; anonymous traffic shares one bucket) so a single
misbehaving client saturates its own budget, not the cluster. Refill is
lazy — computed from elapsed time at each `allow` — so an idle gateway
spends nothing, and idle buckets are pruned.

Deadlines ride the `x-request-deadline` header as ABSOLUTE unix epoch
seconds (float). Absolute beats relative across hops: a relative
timeout would need re-decrementing at every tier and silently resets on
retries, while an absolute deadline shrinks monotonically no matter how
many replicas a hedged request visits. Clients that prefer relative
send `x-request-timeout: <seconds>`; the gateway converts once at the
edge. (Clock skew caveat documented in docs/serving.md — within one
cluster NTP keeps this well under typical deadlines.)
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

DEADLINE_HEADER = "x-request-deadline"
TIMEOUT_HEADER = "x-request-timeout"


class TokenBucket:
    """Classic token bucket: `rate` tokens/second, `burst` capacity."""

    def __init__(self, rate: float, burst: float,
                 now: Optional[float] = None):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.updated = time.monotonic() if now is None else now

    def allow(self, now: Optional[float] = None,
              cost: float = 1.0) -> Tuple[bool, float]:
        """(allowed, retry_after_seconds). retry_after is how long until
        `cost` tokens will have refilled — the Retry-After a 429 sends."""
        now = time.monotonic() if now is None else now
        self.tokens = min(
            self.burst,
            self.tokens + max(0.0, now - self.updated) * self.rate,
        )
        self.updated = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True, 0.0
        needed = cost - self.tokens
        return False, needed / self.rate if self.rate > 0 else 60.0


class KeyedLimiter:
    """Per-key token buckets with idle pruning. rate <= 0 disables the
    limiter entirely (allow always passes) — the local-dev default."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 max_keys: int = 4096):
        self.rate = rate
        self.burst = burst if burst is not None else max(1.0, rate)
        self.max_keys = max_keys
        self.buckets: Dict[str, TokenBucket] = {}

    @property
    def enabled(self) -> bool:
        return self.rate > 0

    def allow(self, key: str,
              now: Optional[float] = None) -> Tuple[bool, float]:
        if not self.enabled:
            return True, 0.0
        now = time.monotonic() if now is None else now
        bucket = self.buckets.get(key)
        if bucket is None:
            if len(self.buckets) >= self.max_keys:
                self._prune(now)
            bucket = self.buckets[key] = TokenBucket(
                self.rate, self.burst, now=now
            )
        return bucket.allow(now)

    def _prune(self, now: float) -> None:
        """Drop buckets idle long enough to be full again (they carry no
        information a fresh bucket wouldn't)."""
        idle = self.burst / self.rate if self.rate > 0 else 0.0
        for key in [
            k for k, b in self.buckets.items()
            if now - b.updated > idle
        ]:
            del self.buckets[key]
        # Pathological case: every bucket hot. Evict oldest-touched.
        while len(self.buckets) >= self.max_keys:
            oldest = min(self.buckets, key=lambda k: self.buckets[k].updated)
            del self.buckets[oldest]


def api_key_of(headers) -> str:
    """The rate-limit key for a request: bearer token, x-api-key, or the
    shared anonymous bucket."""
    auth = headers.get("Authorization", "")
    if auth.lower().startswith("bearer "):
        return auth[7:].strip() or "anonymous"
    return headers.get("x-api-key") or "anonymous"


def parse_deadline(headers,
                   default_timeout: float = 0.0) -> Optional[float]:
    """Absolute unix-seconds deadline for a request, or None.

    Precedence: explicit x-request-deadline, then x-request-timeout
    (relative, converted here), then the configured default timeout
    (0 = no deadline)."""
    raw = headers.get(DEADLINE_HEADER)
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass  # malformed header: fall through, don't reject
    raw = headers.get(TIMEOUT_HEADER)
    if raw:
        try:
            return time.time() + max(0.0, float(raw))
        except ValueError:
            pass
    if default_timeout > 0:
        return time.time() + default_timeout
    return None


def deadline_remaining(deadline: Optional[float]) -> Optional[float]:
    """Seconds left (may be <= 0: already expired); None = no deadline."""
    if deadline is None:
        return None
    return deadline - time.time()
