"""Serving gateway: replica-aware routing, admission control, load
shedding (docs/serving.md).

Fronts N engine replicas with least-loaded power-of-two-choices
routing fed by the engine load-report protocol, per-key token-bucket
admission, bounded per-replica in-flight windows, deadline
propagation, circuit-breaker ejection with exponential backoff, and
hedged retries for requests that lose their replica before any byte
reaches the client. jax-free by design.
"""
from substratus_tpu.gateway.balancer import Balancer, Replica
from substratus_tpu.gateway.health import CircuitBreaker
from substratus_tpu.gateway.limiter import KeyedLimiter, TokenBucket
from substratus_tpu.gateway.loadreport import LoadReport
from substratus_tpu.gateway.router import (
    Gateway,
    GatewayConfig,
    build_gateway_app,
)

__all__ = [
    "Balancer",
    "CircuitBreaker",
    "Gateway",
    "GatewayConfig",
    "KeyedLimiter",
    "LoadReport",
    "Replica",
    "TokenBucket",
    "build_gateway_app",
]
