"""The routing tier: an async HTTP proxy fronting N engine replicas.

Request path (docs/serving.md "Serving gateway"):

  1. **admission** — per-key token bucket (limiter.py): over budget is
     429 + Retry-After, before any replica work. Expired deadlines are
     shed as 504 (the client already gave up; serving it wastes a slot).
  2. **routing** — least-loaded power-of-two-choices over replicas that
     are circuit-closed and under their in-flight window (balancer.py);
     no eligible replica is 503 + Retry-After, never an unbounded queue.
  3. **proxying** — the request is forwarded with the W3C traceparent of
     the `gateway.route` span and the absolute `x-request-deadline`, so
     the replica's spans join the trace and its own admission can honor
     the same deadline.
  4. **hedged retries** — a request that loses its replica (connect
     refused, reset, timeout) before any byte reached the client is
     replayed on another replica; the failed replica is ejected with
     exponential backoff (health.py). A replica answering 429/503 is
     NOT ejected (it is shedding by contract) but the request does try
     the others. Once bytes have streamed, a dead upstream ends the SSE
     with a well-formed error event + [DONE] instead of a hang.
  5. **learning** — every replica response carries `x-substratus-load`
     (loadreport.py); the router feeds it to the balancer, and a
     background poller hits `/loadz` so idle or recovering replicas
     stay visible.

Everything runs on one event loop; replica engines live in other
processes (or in-process test servers) behind plain HTTP.
"""
from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional

import aiohttp
from aiohttp import web

from substratus_tpu.gateway.balancer import Balancer, Replica
from substratus_tpu.gateway.fleet import FleetAggregator
from substratus_tpu.gateway.limiter import (
    DEADLINE_HEADER,
    KeyedLimiter,
    api_key_of,
    deadline_remaining,
    parse_deadline,
)
from substratus_tpu.gateway.loadreport import HEADER as LOAD_HEADER
from substratus_tpu.gateway.loadreport import LoadReport
from substratus_tpu.observability.httpstats import count_http_response
from substratus_tpu.observability.journey import (
    JourneyLog,
    RequestJourney,
    chrome_trace,
    waterfall,
)
from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.observability.propagation import (
    format_traceparent,
    parse_traceparent,
)
from substratus_tpu.observability.tracing import tracer

log = logging.getLogger("substratus.gateway")

# Gateway metric catalog (docs/observability.md "Gateway"). The
# requests_total family is shared with serve/server.py through
# observability/httpstats.py — one name, one scrape query for shed
# rate across both tiers.
METRICS.describe(
    "substratus_gateway_inflight",
    "Requests this gateway currently has outstanding on a replica.",
    type="gauge",
)
METRICS.describe(
    "substratus_gateway_ejections_total",
    "Circuit-breaker ejections after transport failures, by replica.",
    type="counter",
)
METRICS.describe(
    "substratus_gateway_sheds_total",
    "Requests shed instead of queued, by reason (ratelimit, "
    "adapter_quota, deadline, no_replica, saturated, cold_start).",
    type="counter",
)
METRICS.describe(
    "substratus_gateway_hedges_total",
    "Requests replayed on another replica after losing theirs.",
    type="counter",
)
METRICS.histogram(
    "substratus_gateway_upstream_seconds",
    "Wall time of one upstream attempt (connect to last byte), "
    "successful attempts only.",
)

# Transport-level failures that mean "the replica is gone", as opposed
# to it answering with an error status.
_TRANSPORT_ERRORS = (
    aiohttp.ClientConnectionError,  # covers refused/reset/disconnected
    aiohttp.ClientPayloadError,
    asyncio.TimeoutError,
    ConnectionResetError,
)


class _ClientGone(Exception):
    """The CLIENT disconnected mid-relay. Routine (ctrl-C, timeouts on
    the caller's side) and says nothing about the replica — it must
    never eject or hedge, only end the relay quietly."""


@web.middleware
async def counting_middleware(request: web.Request, handler):
    """substratus_http_requests_total on EVERY gateway response — the
    shed-rate denominator (docs/observability.md)."""
    try:
        resp = await handler(request)
    except web.HTTPException as e:
        count_http_response(request.path, e.status)
        raise
    except Exception:
        count_http_response(request.path, 500)
        raise
    count_http_response(request.path, resp.status)
    return resp


class GatewayConfig:
    def __init__(
        self,
        max_inflight: int = 32,  # per-replica in-flight window
        rate: float = 0.0,  # per-key requests/sec (0 = limiter off)
        burst: Optional[float] = None,
        adapter_rate: float = 0.0,  # per-adapter requests/sec (0 = off)
        adapter_burst: Optional[float] = None,
        default_timeout: float = 0.0,  # default deadline (0 = none)
        connect_timeout: float = 2.0,
        backoff_base: float = 0.5,
        backoff_cap: float = 30.0,
        poll_interval: float = 2.0,  # /loadz poll (0 = off)
        max_hedges: int = 3,  # replays per request on replica loss
        shed_retry_after: float = 1.0,  # Retry-After when saturated
    ):
        self.max_inflight = max_inflight
        self.rate = rate
        self.burst = burst
        self.adapter_rate = adapter_rate
        self.adapter_burst = adapter_burst
        self.default_timeout = default_timeout
        self.connect_timeout = connect_timeout
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.poll_interval = poll_interval
        self.max_hedges = max_hedges
        self.shed_retry_after = shed_retry_after


class Gateway:
    """Router state: balancer + limiter + the shared client session."""

    def __init__(self, urls, cfg: Optional[GatewayConfig] = None,
                 seed: Optional[int] = None, authorizer=None):
        self.cfg = cfg or GatewayConfig()
        self.balancer = Balancer(
            urls, max_inflight=self.cfg.max_inflight,
            backoff_base=self.cfg.backoff_base,
            backoff_cap=self.cfg.backoff_cap, seed=seed,
        )
        # Fleet telemetry (gateway/fleet.py): every accepted load
        # report lands in per-replica ring-buffer time series with
        # EWMA-smoothed sustained signals — /debug/fleetz, the
        # substratus_fleet_* gauges, and FleetSignals (the autoscaler
        # input contract) all read from here.
        self.fleet = FleetAggregator()
        # /debug/* RBAC gate, same contract as the server's
        # (observability/authz.py MetricsAuthorizer); None = open
        # (local dev).
        self.authorizer = authorizer
        self.limiter = KeyedLimiter(self.cfg.rate, self.cfg.burst)
        # Per-adapter quotas (multi-tenant fairness, ISSUE 6 follow-up):
        # keyed by the routed `model`/adapter id, so one tenant's burst
        # exhausts its own budget, not its co-tenants' shared engine.
        self.adapter_limiter = KeyedLimiter(
            self.cfg.adapter_rate, self.cfg.adapter_burst
        )
        self.session: Optional[aiohttp.ClientSession] = None
        self._poll_task: Optional[asyncio.Task] = None
        # Edge-side request journeys keyed by x-trace-id: arrival,
        # shed/hedge/retry decisions, replica choice and why — the
        # gateway's half of the waterfall `sub trace <id>` prints
        # (joined with replica journeys via /debug/journeyz).
        self.journeys = JourneyLog(cap=256)
        # Cold-start hint (scale-to-zero contract, docs/serving.md
        # "Autoscaling"): while a scale-up is in flight and no replica
        # is ready yet, sheds carry Retry-After derived from the plan's
        # ETA instead of a bare 503 — clients back off just long enough.
        self._scale_eta_until: Optional[float] = None

    # -- scale-up hint -----------------------------------------------------

    def set_scale_hint(self, eta_s: float,
                       now: Optional[float] = None) -> None:
        """A scale-up is in flight (autoscaler/controller): expect the
        first replica ready in ~eta_s. Overwrites any earlier hint."""
        now = time.monotonic() if now is None else now
        self._scale_eta_until = now + max(0.0, eta_s)

    def clear_scale_hint(self) -> None:
        self._scale_eta_until = None

    def scale_eta_remaining(
        self, now: Optional[float] = None
    ) -> Optional[float]:
        """Seconds until the hinted scale-up lands; None = no live
        hint (never hinted, or the ETA already passed)."""
        if self._scale_eta_until is None:
            return None
        now = time.monotonic() if now is None else now
        remaining = self._scale_eta_until - now
        if remaining <= 0.0:
            self._scale_eta_until = None
            return None
        return remaining

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self.session = aiohttp.ClientSession()
        if self.cfg.poll_interval > 0:
            self._poll_task = asyncio.get_running_loop().create_task(
                self._poll_loop()
            )

    async def close(self) -> None:
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except asyncio.CancelledError:
                pass
            self._poll_task = None
        if self.session is not None:
            await self.session.close()
            self.session = None

    async def _poll_loop(self) -> None:
        """Background /loadz poll: refreshes reports for replicas the
        traffic isn't touching and notices recoveries without spending a
        live request as the probe."""
        while True:
            await asyncio.sleep(self.cfg.poll_interval)
            for rep in list(self.balancer.replicas.values()):
                await self.poll_replica(rep)

    async def poll_replica(self, rep: Replica) -> bool:
        """One /loadz probe; True = replica answered ready."""
        try:
            timeout = aiohttp.ClientTimeout(
                total=self.cfg.connect_timeout + 1.0,
                sock_connect=self.cfg.connect_timeout,
            )
            async with self.session.get(
                rep.url + "/loadz", timeout=timeout
            ) as resp:
                if resp.status != 200:
                    # Draining/not-ready BY THE REPLICA'S OWN WORD: out
                    # of the eligible set immediately — a drain-based
                    # scale-down stops receiving admissions in one poll
                    # cycle, not after the EWMA/staleness window. Not an
                    # ejection: the replica is healthy, just leaving.
                    self.balancer.observe_ready(rep, False)
                    return False
                snap = await resp.json()
        except _TRANSPORT_ERRORS:
            # The poller observes, it does not punish: ejection windows
            # grow only from real traffic failures, so a dead replica's
            # backoff isn't inflated 2x/poll while it restarts.
            return False
        except (json.JSONDecodeError, aiohttp.ContentTypeError):
            return False
        self.balancer.observe_ready(rep, True)
        report = LoadReport.from_snapshot(snap)
        # The fleet aggregator is the ordering authority (sq=/ts=
        # dedupe): a report it drops as stale/out-of-order must not
        # steer routing either. The full /loadz body rides along — it
        # carries the SLO sketches the header is too small for.
        if self.fleet.record(rep.url, report, snapshot=snap):
            self.balancer.observe_report(rep, report)
        self.balancer.observe_success(rep)
        return True

    # -- per-response bookkeeping -----------------------------------------

    def _learn(self, rep: Replica, headers) -> None:
        raw = headers.get(LOAD_HEADER)
        if raw:
            report = LoadReport.from_header(raw)
            if self.fleet.record(rep.url, report):
                self.balancer.observe_report(rep, report)

    def _fail(self, rep: Replica) -> None:
        window = self.balancer.observe_failure(rep)
        METRICS.inc(
            "substratus_gateway_ejections_total", {"replica": rep.url}
        )
        log.warning(
            "replica %s ejected for %.1fs (%d consecutive failures)",
            rep.url, window, rep.circuit.consecutive_failures,
        )

    def _set_inflight(self, rep: Replica) -> None:
        METRICS.set(
            "substratus_gateway_inflight", rep.inflight,
            {"replica": rep.url},
        )

    def _shed(self, reason: str, retry_after: float,
              status: int = 503, journey=None) -> web.Response:
        if journey is not None:
            journey.record("shed", reason=reason, status=status)
            journey.record("end", reason="shed")
        METRICS.inc("substratus_gateway_sheds_total", {"reason": reason})
        cls = {429: web.HTTPTooManyRequests,
               503: web.HTTPServiceUnavailable,
               504: web.HTTPGatewayTimeout}[status]
        headers = {}
        if status in (429, 503):
            headers["Retry-After"] = str(max(1, int(retry_after + 0.999)))
        return cls(
            text=json.dumps({"error": {
                "message": f"request shed: {reason}", "type": reason,
            }}),
            content_type="application/json", headers=headers,
        )


def build_gateway_app(gw: Gateway) -> web.Application:
    routes = web.RouteTableDef()

    @routes.get("/")
    async def root(request: web.Request) -> web.Response:
        # Ready iff at least one replica is routable right now.
        ok = bool(gw.balancer.eligible())
        return web.Response(status=200 if ok else 503,
                            text="ok" if ok else "no eligible replica")

    @routes.get("/loadz")
    async def loadz(request: web.Request) -> web.Response:
        now = time.monotonic()
        return web.json_response({
            "role": "gateway",
            "replicas": gw.balancer.snapshot(now),
            "eligible": len(gw.balancer.eligible(now)),
        })

    @routes.get("/metrics")
    async def metrics(request: web.Request) -> web.Response:
        for rep in gw.balancer.replicas.values():
            gw._set_inflight(rep)
        # Refresh the fleet rollup gauges (replica counts by role) and
        # run dead-replica eviction so the scrape never reports a
        # scaled-down replica's last load as current.
        gw.fleet.signals()
        return web.Response(
            body=METRICS.render().encode(),
            headers={
                "Content-Type": "text/plain; version=0.0.4; charset=utf-8"
            },
        )

    async def _authorize_debug(request: web.Request) -> None:
        """Gate /debug/* with the same RBAC check as the server's debug
        plane (TokenReview + SubjectAccessReview through gw.authorizer);
        open when no authorizer is configured (local dev)."""
        if gw.authorizer is None:
            return
        loop = asyncio.get_running_loop()
        status, reason = await loop.run_in_executor(
            None, gw.authorizer.allow,
            request.headers.get("Authorization"),
        )
        if status == 200:
            return
        if status == 401:
            raise web.HTTPUnauthorized(
                text=reason, headers={"WWW-Authenticate": "Bearer"}
            )
        if status == 403:
            raise web.HTTPForbidden(text=reason)
        raise web.HTTPInternalServerError(text=reason)

    @routes.get("/debug/fleetz")
    async def fleetz(request: web.Request) -> web.Response:
        """Fleet telemetry (gateway/fleet.py): per-replica ring-buffer
        load series, EWMA sustained signals, SLO percentiles, and the
        fleet rollup — the rendered form of the FleetSignals contract
        the controller autoscaler consumes."""
        await _authorize_debug(request)
        return web.json_response(gw.fleet.snapshot())

    @routes.get("/debug/journeyz")
    async def journeyz(request: web.Request) -> web.Response:
        """The full request waterfall for one trace id: the gateway's
        edge journey joined with every replica's stitched journey
        (fanned out to each replica's /debug/requestz?id=). Without
        ?id= lists the edge ring's ids. `sub trace <id>` renders this
        body as the edge→prefill→transfer→decode→emit timeline."""
        await _authorize_debug(request)
        wanted = request.query.get("id")
        if not wanted:
            return web.json_response({"journeys": gw.journeys.ids()})
        edge = gw.journeys.find(wanted)
        fwd_headers = {}
        if "Authorization" in request.headers:
            fwd_headers["Authorization"] = request.headers["Authorization"]
        replica_journeys = []
        timeout = aiohttp.ClientTimeout(
            total=gw.cfg.connect_timeout + 2.0,
            sock_connect=gw.cfg.connect_timeout,
        )
        for rep in list(gw.balancer.replicas.values()):
            try:
                async with gw.session.get(
                    rep.url + "/debug/requestz", params={"id": wanted},
                    headers=fwd_headers, timeout=timeout,
                ) as resp:
                    if resp.status != 200:
                        continue
                    body = await resp.json()
            except _TRANSPORT_ERRORS:
                continue
            except (json.JSONDecodeError, aiohttp.ContentTypeError):
                continue
            j = body.get("journey")
            if isinstance(j, dict):
                j["replica"] = rep.url
                replica_journeys.append(j)
        if edge is None and not replica_journeys:
            raise web.HTTPNotFound(text=f"no journey for id {wanted!r}")
        merged = dict(edge) if edge is not None else {
            "trace_id": wanted, "rid": None, "origin": "gateway",
            "total": 0, "dropped": 0, "events": [], "marks": {},
            "breaches": [], "segments": [],
        }
        # Flatten each replica journey AND its own stitched segments
        # (the decode half of a disagg handoff) into one segment list,
        # so the waterfall shows every hop on a shared time axis.
        segments = list(merged.get("segments") or [])
        for j in replica_journeys:
            inner = j.pop("segments", None) or []
            segments.append(j)
            segments.extend(s for s in inner if isinstance(s, dict))
        merged["segments"] = segments
        return web.json_response({
            "journey": merged,
            "waterfall": waterfall(merged),
            "chrome_trace": chrome_trace(merged),
        })

    @routes.get("/v1/models")
    async def models(request: web.Request) -> web.Response:
        return await _route(request, b"", streaming=False)

    @routes.post("/v1/completions")
    @routes.post("/v1/chat/completions")
    async def completions(request: web.Request) -> web.StreamResponse:
        body = await request.read()
        streaming = False
        adapter = None
        try:
            parsed = json.loads(body or b"{}")
            streaming = bool(parsed.get("stream"))
            # The OpenAI `model` field doubles as the routing affinity
            # key: replicas report resident adapter ids on
            # x-substratus-load, and the balancer prefers them. A base-
            # model name no replica reports simply never matches.
            model = parsed.get("model")
            adapter = str(model) if model else None
        except (json.JSONDecodeError, AttributeError):
            pass  # replicas reject malformed JSON with a 400; just relay
        # Admission: rate limit, then deadline — an over-budget client
        # is told to slow down even when its deadline is generous.
        ok, retry_after = gw.limiter.allow(api_key_of(request.headers))
        if not ok:
            raise gw._shed(
                "ratelimit", retry_after, status=429,
                journey=_edge_journey_for_shed(request),
            )
        if adapter:
            # Per-adapter quota (token bucket keyed by the routed
            # `model` field): one tenant's burst drains its own budget
            # instead of starving its co-tenants on the shared engine.
            ok, retry_after = gw.adapter_limiter.allow(adapter)
            if not ok:
                raise gw._shed(
                    "adapter_quota", retry_after, status=429,
                    journey=_edge_journey_for_shed(request),
                )
        # Completions are admissions: in a disaggregated deployment
        # they must land on the prefill pool (serve/disagg.py) — the
        # decode tier only takes KV migrations. Monolithic replicas
        # report role "both" and match as before.
        return await _route(request, body, streaming=streaming,
                            adapter=adapter, role="prefill")

    def _edge_journey_for_shed(
        request: web.Request,
    ) -> Optional[RequestJourney]:
        """A journey for a PRE-route shed (rate limit / adapter quota):
        only recorded when the caller sent a traceparent — without one
        there is no id anyone could ever look the journey up by."""
        remote = parse_traceparent(request.headers.get("traceparent"))
        if remote is None:
            return None
        j = RequestJourney(trace_id=remote.trace_id, origin="gateway")
        j.record("arrive", path=request.path)
        gw.journeys.add(j)
        return j

    async def _route(request: web.Request, body: bytes,
                     streaming: bool,
                     adapter: Optional[str] = None,
                     role: Optional[str] = None) -> web.StreamResponse:
        deadline = parse_deadline(
            request.headers, gw.cfg.default_timeout
        )
        remaining = deadline_remaining(deadline)
        if remaining is not None and remaining <= 0:
            raise gw._shed(
                "deadline", 0.0, status=504,
                journey=_edge_journey_for_shed(request),
            )

        remote = parse_traceparent(request.headers.get("traceparent"))
        with tracer.span(
            "gateway.route", parent=remote,
            method=request.method, path=request.path,
            stream=streaming,
        ) as span:
            if adapter:
                span.set_attribute("adapter", adapter)
            # Edge journey keyed by this trace id (== the x-trace-id
            # the client sees): the gateway half of the full waterfall.
            journey = RequestJourney(
                trace_id=span.trace_id, origin="gateway"
            )
            journey.record(
                "arrive", path=request.path, stream=streaming,
                adapter=adapter,
            )
            gw.journeys.add(journey)
            resp = await _attempts(
                request, body, streaming, deadline, span, adapter, role,
                journey,
            )
            span.set_attribute("http_status", resp.status)
            if not journey.ended:
                journey.record("end", status=resp.status)
            return resp

    async def _attempts(request: web.Request, body: bytes,
                        streaming: bool, deadline: Optional[float],
                        span, adapter: Optional[str] = None,
                        role: Optional[str] = None,
                        journey: Optional[RequestJourney] = None
                        ) -> web.StreamResponse:
        """The hedged-retry loop around single-replica attempts."""
        tried: tuple = ()
        # The SSE response toward the client, shared across attempts: a
        # hedge that fires after upstream #1 produced headers (but no
        # body bytes) keeps writing into the already-prepared response.
        stream_state: dict = {"resp": None}
        shed_response: Optional[web.Response] = None  # replica 429/503

        async def give_up(exc: Optional[web.Response]):
            """Terminal shed. If an SSE response is already prepared,
            the only legal ending is in-band: error event + [DONE]."""
            prepared = stream_state["resp"]
            if prepared is not None:
                await _end_stream_with_error(
                    prepared, None, "no replica left to hedge onto"
                )
                return prepared
            if exc is None:
                raise gw._shed("no_replica", gw.cfg.backoff_base)
            return exc

        for attempt in range(1 + gw.cfg.max_hedges):
            rep = gw.balancer.pick(exclude=tried, adapter=adapter, role=role)
            if rep is None:
                if shed_response is not None:
                    # Every other replica is down/full and this one said
                    # "not now" — relay its answer, its Retry-After is
                    # the honest one.
                    METRICS.inc(
                        "substratus_gateway_sheds_total",
                        {"reason": "replica_shed"},
                    )
                    return await give_up(shed_response)
                if stream_state["resp"] is not None:
                    return await give_up(None)
                if gw.balancer.saturated():
                    raise gw._shed(
                        "saturated", gw.cfg.shed_retry_after,
                        journey=journey,
                    )
                # Zero ready replicas with a scale-up in flight: the
                # honest answer is "come back when it lands", not a
                # bare 503 (scale-to-zero cold start).
                eta = gw.scale_eta_remaining()
                if eta is not None:
                    raise gw._shed("cold_start", eta, journey=journey)
                raise gw._shed(
                    "no_replica", gw.cfg.backoff_base, journey=journey
                )
            if attempt > 0:
                METRICS.inc("substratus_gateway_hedges_total")
                span.set_attribute("hedged", True)
                if journey is not None:
                    journey.record("hedge", attempt=attempt + 1)
            span.set_attribute("replica", rep.url)
            span.set_attribute("attempts", attempt + 1)
            if journey is not None:
                # The routing decision AND why: which replica, its
                # current in-flight depth, adapter/role affinity asked.
                journey.record(
                    "replica", url=rep.url, attempt=attempt + 1,
                    inflight=rep.inflight, adapter=adapter, role=role,
                )
            remaining = deadline_remaining(deadline)
            if remaining is not None and remaining <= 0:
                if stream_state["resp"] is not None:
                    return await give_up(None)
                raise gw._shed(
                    "deadline", 0.0, status=504, journey=journey
                )

            gw.balancer.acquire(rep)
            gw._set_inflight(rep)
            try:
                result = await _attempt_one(
                    request, rep, body, streaming, deadline, stream_state
                )
            except _ClientGone:
                # The caller left; the replica served fine. End quietly
                # (closing the upstream context already aborted the
                # replica-side handler, which cancels its engine work).
                log.info("client disconnected mid-relay (%s)", rep.url)
                return stream_state["resp"]
            except _TRANSPORT_ERRORS as e:
                gw._fail(rep)
                tried = tried + (rep.url,)
                log.info("attempt on %s failed: %r", rep.url, e)
                if journey is not None:
                    journey.record(
                        "retry", replica=rep.url, cause="transport"
                    )
                continue  # hedge: nothing reached the client yet
            finally:
                gw.balancer.release(rep)
                gw._set_inflight(rep)
            if isinstance(result, _ReplicaShed):
                tried = tried + (rep.url,)
                shed_response = result.response
                if journey is not None:
                    journey.record(
                        "retry", replica=rep.url, cause="replica_shed"
                    )
                # Sustained shed rate per replica (gateway/fleet.py):
                # overload evidence the autoscaler reads once queue
                # bounds keep queue-depth EWMAs flat.
                gw.fleet.record_shed(rep.url)
                continue
            if isinstance(result, _StreamBroken):
                # Bytes already reached the client: the stream was ended
                # with an SSE error event inside _attempt_one. No hedge.
                gw._fail(rep)
                return result.response
            return result
        # Hedge budget exhausted.
        return await give_up(shed_response)

    async def _attempt_one(request: web.Request, rep: Replica,
                           body: bytes, streaming: bool,
                           deadline: Optional[float],
                           stream_state: dict):
        """One upstream try. Returns a finished response, _ReplicaShed
        (replica said 429/503: try elsewhere), or _StreamBroken (died
        mid-stream, already ended politely). Transport errors raise —
        but only while nothing has streamed to the client; after that
        they are converted to _StreamBroken here."""
        headers = {
            k: v for k, v in request.headers.items()
            if k.lower() not in (
                "host", "content-length", "connection", "traceparent",
            )
        }
        headers["traceparent"] = format_traceparent(
            tracer.current_context()
        )
        remaining = deadline_remaining(deadline)
        if deadline is not None:
            headers[DEADLINE_HEADER] = f"{deadline:.3f}"
        timeout = aiohttp.ClientTimeout(
            total=remaining,  # None = no cap (long SSE decodes)
            sock_connect=gw.cfg.connect_timeout,
        )
        t0 = time.perf_counter()
        async with gw.session.request(
            request.method, rep.url + request.path,
            data=body if request.method == "POST" else None,
            headers=headers, timeout=timeout,
        ) as upstream:
            gw._learn(rep, upstream.headers)
            if upstream.status in (429, 503):
                return _ReplicaShed(await _relay_full(upstream))
            if not streaming or upstream.status != 200:
                resp = await _relay_full(upstream)
                # Which replica served it: debugging aid and the hook
                # chaos tests use to aim their kill.
                resp.headers["x-substratus-replica"] = rep.url
                gw.balancer.observe_success(rep)
                METRICS.observe(
                    "substratus_gateway_upstream_seconds",
                    time.perf_counter() - t0,
                )
                return resp
            # SSE relay. The client response is prepared once, on the
            # first upstream that produced response headers; a hedged
            # second upstream keeps writing into the same prepared
            # response (same status/content type by construction).
            client_resp = stream_state["resp"]
            if client_resp is None:
                client_resp = web.StreamResponse(headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                    "x-substratus-replica": rep.url,
                })
                ctx = tracer.current_context()
                if ctx is not None:
                    client_resp.headers["x-trace-id"] = ctx.trace_id
                await client_resp.prepare(request)
                stream_state["resp"] = client_resp
            streamed = False
            try:
                async for chunk in upstream.content.iter_any():
                    if chunk:
                        try:
                            await client_resp.write(chunk)
                        except (ConnectionResetError, RuntimeError) as e:
                            # The CLIENT hung up, not the replica:
                            # don't let the outer handler blame (and
                            # eject) a healthy upstream.
                            raise _ClientGone() from e
                        streamed = True
            except _TRANSPORT_ERRORS as e:
                if not streamed:
                    raise  # hedgeable: the client saw nothing yet
                await _end_stream_with_error(client_resp, rep, e)
                return _StreamBroken(client_resp)
            gw.balancer.observe_success(rep)
            METRICS.observe(
                "substratus_gateway_upstream_seconds",
                time.perf_counter() - t0,
            )
            await client_resp.write_eof()
            return client_resp

    async def _relay_full(upstream) -> web.Response:
        payload = await upstream.read()
        headers = {}
        for k in ("Content-Type", "Retry-After", "x-trace-id"):
            if k in upstream.headers:
                headers[k] = upstream.headers[k]
        return web.Response(
            body=payload, status=upstream.status, headers=headers
        )

    async def _end_stream_with_error(client_resp: web.StreamResponse,
                                     rep: Replica, err) -> None:
        """A committed SSE stream whose replica died: end with a
        well-formed error event + [DONE] so clients terminate cleanly
        instead of hanging on a half-open socket."""
        ctx = tracer.current_context()
        event = {
            "error": {
                "message": "replica lost mid-stream; partial output",
                "type": "upstream_error",
            },
            "trace_id": ctx.trace_id if ctx is not None else None,
        }
        try:
            await client_resp.write(
                f"data: {json.dumps(event)}\n\ndata: [DONE]\n\n".encode()
            )
            await client_resp.write_eof()
        except (ConnectionResetError, RuntimeError):
            pass  # the client went away too; nothing left to tell it
        log.warning(
            "stream on %s broke mid-flight: %r",
            rep.url if rep is not None else "<none>", err,
        )

    app = web.Application(middlewares=[counting_middleware])
    app.add_routes(routes)

    async def _lifecycle(app):
        await gw.start()
        yield
        await gw.close()

    app.cleanup_ctx.append(_lifecycle)
    return app


class _ReplicaShed:
    """Upstream answered 429/503 (shedding, alive)."""

    def __init__(self, response: web.Response):
        self.response = response


class _StreamBroken:
    """Upstream died after bytes reached the client; stream already
    ended with the error event."""

    def __init__(self, response: web.StreamResponse):
        self.response = response
