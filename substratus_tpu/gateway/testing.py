"""In-process test/smoke harness: real engine replicas + gateway on
loopback sockets, one event loop, no containers.

`InProcessReplica` is a full serving stack — tiny llama Engine (its own
scheduler thread) + the real aiohttp server from serve/server.py — bound
to a loopback port. `kill()` closes its listener and aborts live
connections abruptly (what a crashed pod looks like to the gateway:
connection reset / refused), and `restart()` rebinds the SAME port with
a FRESH engine, which is exactly a pod restart. The chaos test
(tests/test_gateway.py) and `make gateway-smoke`
(tools/gateway_smoke.py) drive the same harness, so CI and local smoke
cannot drift.

Imports jax (engine construction) — gateway code itself stays jax-free;
only this harness pays that cost, and only when instantiated.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from aiohttp import web

from substratus_tpu.gateway.router import (
    Gateway,
    GatewayConfig,
    build_gateway_app,
)

# Spare id beyond the forced 258-token vocab: random-weight generations
# never hit it, so greedy decodes run to max_tokens deterministically
# (the same setup tests/test_multihost_serving.py uses).
TINY_EOS = 257


def build_tiny_engine(max_batch: int = 4, max_seq_len: int = 128,
                      max_queue: Optional[int] = None):
    """Random-weight tiny llama engine on CPU, started."""
    import jax
    import jax.numpy as jnp

    from substratus_tpu.models import llama
    from substratus_tpu.serve.engine import Engine, EngineConfig

    cfg = llama.CONFIGS["tiny"].replace(vocab_size=258, dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.key(0))
    engine = Engine(cfg, params, EngineConfig(
        max_batch=max_batch, max_seq_len=max_seq_len,
        eos_token_id=TINY_EOS, max_queue=max_queue,
    ))
    engine.start()
    return engine


class InProcessReplica:
    """One replica: engine + HTTP server on 127.0.0.1:<port>."""

    def __init__(self, name: str = "replica", max_batch: int = 4,
                 max_seq_len: int = 128,
                 max_queue: Optional[int] = None):
        self.name = name
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        self.max_queue = max_queue
        self.port: Optional[int] = None
        self.engine = None
        self.state = None
        self._runner: Optional[web.AppRunner] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    async def start(self, port: int = 0) -> "InProcessReplica":
        from substratus_tpu.serve.server import ServerState, build_app
        from substratus_tpu.serve.tokenizer import ByteTokenizer

        loop = asyncio.get_running_loop()
        # Engine construction compiles nothing but inits params; keep it
        # off the event loop anyway (fixture parallelism).
        self.engine = await loop.run_in_executor(
            None, lambda: build_tiny_engine(
                self.max_batch, self.max_seq_len, self.max_queue
            )
        )
        self.state = ServerState(self.engine, ByteTokenizer(), self.name)
        # Near-zero shutdown grace: kill() must look like a crash, not
        # a drain (the graceful path is tested via server.drain()).
        self._runner = web.AppRunner(
            build_app(self.state), shutdown_timeout=0.05
        )
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def kill(self) -> None:
        """Abrupt death, in crash order: freeze the engine FIRST so
        in-flight streams stall mid-decode (tokens stop arriving), then
        abort the HTTP server — live connections reset without a final
        chunk, new ones get refused. That is exactly what a killed pod
        looks like from the gateway: no drain, no goodbye."""
        eng, self.engine = self.engine, None
        if eng is not None:
            eng.stop()  # scheduler exits; no terminal Nones yet
        if self._runner is not None:
            await self._runner.cleanup()  # 0.05 s grace, then abort
            self._runner = None
        if eng is not None:
            # Now terminate every stranded request: the aborted
            # handlers' executor threads are blocked in req.out.get()
            # and would leak for the life of the test process.
            for req in (
                list(eng.slot_req)
                + list(getattr(eng.queue, "queue", ()))
                + list(eng._resume)
            ):
                if req is not None:
                    req.finish_reason = "error"
                    req.out.put(None)

    async def restart(self) -> None:
        """Pod restart: same address, fresh engine + server."""
        assert self.port, "start() before restart()"
        await self.start(port=self.port)

    async def stop(self) -> None:
        await self.kill()


class GatewayHarness:
    """N in-process replicas behind an in-process gateway."""

    def __init__(self, n_replicas: int = 2,
                 cfg: Optional[GatewayConfig] = None,
                 max_batch: int = 4, max_queue: Optional[int] = None):
        self.replicas = [
            InProcessReplica(f"replica{i}", max_batch=max_batch,
                             max_queue=max_queue)
            for i in range(n_replicas)
        ]
        self.cfg = cfg or GatewayConfig(
            # Fast-twitch settings for tests: short backoff so recovery
            # is observable in seconds, frequent polling, snappy
            # connect timeout on loopback.
            backoff_base=0.2, backoff_cap=2.0, poll_interval=0.2,
            connect_timeout=1.0,
        )
        self.gateway: Optional[Gateway] = None
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def replica_by_url(self, url: str) -> InProcessReplica:
        return next(r for r in self.replicas if r.url == url.rstrip("/"))

    async def start(self) -> "GatewayHarness":
        for r in self.replicas:
            await r.start()
        self.gateway = Gateway([r.url for r in self.replicas], self.cfg)
        self._runner = web.AppRunner(build_gateway_app(self.gateway))
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        for r in self.replicas:
            await r.stop()
