"""In-process test/smoke harness: real engine replicas + gateway on
loopback sockets, one event loop, no containers.

`InProcessReplica` is a full serving stack — tiny llama Engine (its own
scheduler thread) + the real aiohttp server from serve/server.py — bound
to a loopback port. `kill()` closes its listener and aborts live
connections abruptly (what a crashed pod looks like to the gateway:
connection reset / refused), and `restart()` rebinds the SAME port with
a FRESH engine, which is exactly a pod restart. The chaos test
(tests/test_gateway.py) and `make gateway-smoke`
(tools/gateway_smoke.py) drive the same harness, so CI and local smoke
cannot drift.

Imports jax (engine construction) — gateway code itself stays jax-free;
only this harness pays that cost, and only when instantiated.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from aiohttp import web

from substratus_tpu.gateway.router import (
    Gateway,
    GatewayConfig,
    build_gateway_app,
)

# Spare id beyond the forced 258-token vocab: random-weight generations
# never hit it, so greedy decodes run to max_tokens deterministically
# (the same setup tests/test_multihost_serving.py uses).
TINY_EOS = 257


def tiny_params(seed: int = 0):
    """The harness's tiny-llama param tree for an init seed — the same
    shapes every replica serves, so any seed hot-swaps onto any
    replica (seed 0 is the boot weights)."""
    import jax
    import jax.numpy as jnp

    from substratus_tpu.models import llama

    cfg = llama.CONFIGS["tiny"].replace(vocab_size=258, dtype=jnp.float32)
    return llama.init_params(cfg, jax.random.key(int(seed)))


def seed_checkpoint_loader(ref: str):
    """Checkpoint loader for the harness's /swapz: refs are "seed:N"
    (a fresh init of the tiny config with key N) — real checkpoint
    machinery stays out of the loopback fleet."""
    if not ref.startswith("seed:"):
        raise FileNotFoundError(
            f"harness checkpoints are 'seed:N' refs, got {ref!r}"
        )
    return tiny_params(int(ref.split(":", 1)[1]))


def build_tiny_engine(max_batch: int = 4, max_seq_len: int = 128,
                      max_queue: Optional[int] = None):
    """Random-weight tiny llama engine on CPU, started."""
    import jax
    import jax.numpy as jnp

    from substratus_tpu.models import llama
    from substratus_tpu.serve.engine import Engine, EngineConfig

    cfg = llama.CONFIGS["tiny"].replace(vocab_size=258, dtype=jnp.float32)
    params = tiny_params(0)
    engine = Engine(cfg, params, EngineConfig(
        max_batch=max_batch, max_seq_len=max_seq_len,
        eos_token_id=TINY_EOS, max_queue=max_queue,
    ))
    engine.start()
    return engine


class InProcessReplica:
    """One replica: engine + HTTP server on 127.0.0.1:<port>."""

    def __init__(self, name: str = "replica", max_batch: int = 4,
                 max_seq_len: int = 128,
                 max_queue: Optional[int] = None):
        self.name = name
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        self.max_queue = max_queue
        self.port: Optional[int] = None
        self.engine = None
        self.state = None
        self._runner: Optional[web.AppRunner] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    async def start(self, port: int = 0) -> "InProcessReplica":
        from substratus_tpu.serve.server import ServerState, build_app
        from substratus_tpu.serve.tokenizer import ByteTokenizer

        loop = asyncio.get_running_loop()
        # Engine construction compiles nothing but inits params; keep it
        # off the event loop anyway (fixture parallelism).
        self.engine = await loop.run_in_executor(
            None, lambda: build_tiny_engine(
                self.max_batch, self.max_seq_len, self.max_queue
            )
        )
        self.state = ServerState(
            self.engine, ByteTokenizer(), self.name,
            # "seed:N" refs make the replica hot-swappable via POST
            # /swapz (the rollout smoke/chaos paths) with no checkpoint
            # files on disk.
            checkpoint_loader=seed_checkpoint_loader,
        )
        # Near-zero shutdown grace: kill() must look like a crash, not
        # a drain (the graceful path is tested via server.drain()).
        self._runner = web.AppRunner(
            build_app(self.state), shutdown_timeout=0.05
        )
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def kill(self) -> None:
        """Abrupt death, in crash order: freeze the engine FIRST so
        in-flight streams stall mid-decode (tokens stop arriving), then
        abort the HTTP server — live connections reset without a final
        chunk, new ones get refused. That is exactly what a killed pod
        looks like from the gateway: no drain, no goodbye."""
        eng, self.engine = self.engine, None
        if eng is not None:
            eng.stop()  # scheduler exits; no terminal Nones yet
        if self._runner is not None:
            await self._runner.cleanup()  # 0.05 s grace, then abort
            self._runner = None
        if eng is not None:
            # Now terminate every stranded request: the aborted
            # handlers' executor threads are blocked in req.out.get()
            # and would leak for the life of the test process.
            for req in (
                list(eng.slot_req)
                + list(getattr(eng.queue, "queue", ()))
                + list(eng._resume)
            ):
                if req is not None:
                    req.finish_reason = "error"
                    req.out.put(None)

    async def drain(self, grace_s: float = 10.0) -> bool:
        """Graceful removal, in DRAIN order (the opposite of kill():
        docs/serving.md "Graceful drain"): readiness flips first
        (/loadz answers 503 so the gateway's poller stops admitting
        here within one cycle), in-flight requests — including live
        SSE streams — run to completion up to grace_s, and only then
        do the listener and engine go away. Returns True when
        everything finished inside the deadline."""
        from substratus_tpu.serve.server import drain as server_drain

        clean = await server_drain(self.state, grace_s=grace_s,
                                   poll_s=0.02)
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        eng, self.engine = self.engine, None
        if eng is not None:
            # stop() flushes the pipeline and delivers in-flight
            # tokens (PR 10 stop-flush); after a clean drain there are
            # none left.
            eng.stop()
        return clean

    async def restart(self) -> None:
        """Pod restart: same address, fresh engine + server."""
        assert self.port, "start() before restart()"
        await self.start(port=self.port)

    async def stop(self) -> None:
        await self.kill()


class FleetSupervisor:
    """The closed autoscale loop on in-process replicas — the CPU apply
    path for the SAME decision core the controller runs
    (controller/autoscale.py). The chaos test
    (tests/test_autoscale.py) and `make autoscale-smoke`
    (tools/autoscale_smoke.py) drive this class, so CI and local smoke
    cannot drift.

    Each tick: read the gateway's FleetSignals, run the pure planner,
    then reconcile the ACTUAL replica set toward the planned target the
    way a Deployment controller would —

      * scale-up: start fresh InProcessReplicas and add them to the
        balancer (a cold start from zero first arms the gateway's
        Retry-After hint from the plan's ETA);
      * scale-down: drain the plan's victims (readiness drops first via
        the poller's 503 handling, in-flight SSE streams finish), then
        remove them;
      * self-healing: a managed replica that stopped reporting for
        dead_after_s is replaced with a fresh one, not merely routed
        around.
    """

    def __init__(self, harness: "GatewayHarness", policy=None,
                 dead_after_s: float = 1.5,
                 drain_grace_s: float = 10.0):
        from substratus_tpu.controller.autoscale import (
            Autoscaler,
            AutoscalePolicy,
        )

        self.h = harness
        self.core = Autoscaler(policy or AutoscalePolicy(
            # Fast-twitch windows for CPU tests: decisions in seconds.
            sustain_up_s=0.6, sustain_down_s=1.2, up_cooldown_s=1.0,
            down_cooldown_s=2.0, idle_zero_s=3.0, stale_after_s=5.0,
            cold_start_eta_s=10.0,
        ))
        self.target = len(harness.replicas)
        self.dead_after_s = dead_after_s
        self.drain_grace_s = drain_grace_s
        # Short EWMA halflife so a few seconds of synthetic ramp move
        # the sustained signals a test can act on (production keeps the
        # 10 s default).
        if harness.gateway is not None:
            harness.gateway.fleet.halflife_s = 0.5
        self.transitions: list = []  # (kind, detail) audit for asserts
        self.replaced = 0
        self.drains_clean = 0
        self.drains_dirty = 0
        self._started_at: dict = {}
        self._last_sheds = self._gateway_sheds()
        self._next_name = len(harness.replicas)
        now = __import__("time").monotonic()
        for rep in harness.replicas:
            self._started_at[rep.url] = now

    # -- signals the fleet telemetry cannot carry --------------------------

    @staticmethod
    def _gateway_sheds() -> float:
        """no_replica/cold_start sheds: demand that arrived while zero
        replicas were ready — the only scale-from-zero signal."""
        from substratus_tpu.observability.metrics import METRICS

        total = 0.0
        for reason in ("no_replica", "cold_start"):
            total += METRICS.get(
                "substratus_gateway_sheds_total", {"reason": reason}
            ) or 0
        return total

    # -- the loop ----------------------------------------------------------

    async def tick(self):
        """One reconcile pass; returns the ScalePlan for assertions."""
        from substratus_tpu.controller.autoscale import ScaleTargets

        gw = self.h.gateway
        signals = gw.fleet.signals()
        sheds = self._gateway_sheds()
        pending, self._last_sheds = sheds - self._last_sheds, sheds
        plan = self.core.plan(
            signals, ScaleTargets(replicas=self.target), pending=pending
        )
        if plan.outcome == "applied":
            self.transitions.append((plan.reason, plan.targets.replicas))
            if plan.targets.replicas > self.target and self.target == 0:
                # Cold start: tell the gateway how long to ask clients
                # to wait (scale-to-zero contract).
                gw.set_scale_hint(plan.eta_s)
            self.target = plan.targets.replicas
        await self._reconcile(signals, plan.victims)
        # Self-healing and scale-up both count as "replicas live";
        # the hint dies once any replica is routable again.
        if gw.balancer.eligible() and self.target > 0:
            gw.clear_scale_hint()
        return plan

    async def run(self, duration_s: float, interval_s: float = 0.3):
        import asyncio as _asyncio
        import time as _time

        deadline = _time.monotonic() + duration_s
        while _time.monotonic() < deadline:
            await self.tick()
            await _asyncio.sleep(interval_s)

    # -- actual -> target reconciliation -----------------------------------

    def _live(self, signals) -> list:
        """Managed replicas that are alive by the fleet's word: a row
        younger than dead_after_s, or too recently started to have
        reported yet (first poll pending)."""
        import time as _time

        now = _time.monotonic()
        rows = {r.url: r for r in signals.replicas}
        live = []
        for rep in self.h.replicas:
            row = rows.get(rep.url)
            fresh_start = (
                now - self._started_at.get(rep.url, 0.0)
                < self.dead_after_s * 2
            )
            if (row is not None and row.age_s < self.dead_after_s) \
                    or fresh_start:
                live.append(rep)
        return live

    async def _reconcile(self, signals, victims: tuple) -> None:
        import time as _time

        gw = self.h.gateway
        live = self._live(signals)

        # Self-healing: anything managed but not live is dead — remove
        # and replace (the replacement is part of the same pass's
        # scale-up arithmetic below).
        for rep in [r for r in self.h.replicas if r not in live]:
            self.transitions.append(("replace_dead", rep.url))
            self.replaced += 1
            gw.balancer.remove(rep.url)
            gw.fleet.forget(rep.url)
            await rep.kill()  # idempotent; frees any stranded state
            self.h.replicas.remove(rep)

        # Scale down: drain victims (plan's choice first, arbitrary
        # live replicas only if the plan named fewer than the excess),
        # never below the target.
        excess = len(self.h.replicas) - self.target
        if excess > 0:
            chosen = [
                r for r in self.h.replicas if r.url in victims
            ][:excess]
            for rep in self.h.replicas:
                if len(chosen) >= excess:
                    break
                if rep not in chosen:
                    chosen.append(rep)
            for rep in chosen:
                # Belt and braces: the poller flips this on its next
                # cycle anyway (503 from /loadz), but the supervisor
                # knows NOW.
                known = gw.balancer.replicas.get(rep.url)
                if known is not None:
                    gw.balancer.observe_ready(known, False)
                clean = await rep.drain(grace_s=self.drain_grace_s)
                if clean:
                    self.drains_clean += 1
                else:
                    self.drains_dirty += 1
                self.transitions.append(("drain", rep.url))
                gw.balancer.remove(rep.url)
                gw.fleet.forget(rep.url)
                self.h.replicas.remove(rep)

        # Scale up (and dead-replica replacement): fresh replicas on
        # fresh ports.
        while len(self.h.replicas) < self.target:
            name = f"replica{self._next_name}"
            self._next_name += 1
            rep = InProcessReplica(
                name, max_batch=self.h.replicas[0].max_batch
                if self.h.replicas else 4,
            )
            await rep.start()
            self.h.replicas.append(rep)
            self._started_at[rep.url] = _time.monotonic()
            gw.balancer.add(rep.url)
            self.transitions.append(("start", rep.url))


class GatewayHarness:
    """N in-process replicas behind an in-process gateway."""

    def __init__(self, n_replicas: int = 2,
                 cfg: Optional[GatewayConfig] = None,
                 max_batch: int = 4, max_queue: Optional[int] = None):
        self.replicas = [
            InProcessReplica(f"replica{i}", max_batch=max_batch,
                             max_queue=max_queue)
            for i in range(n_replicas)
        ]
        self.cfg = cfg or GatewayConfig(
            # Fast-twitch settings for tests: short backoff so recovery
            # is observable in seconds, frequent polling, snappy
            # connect timeout on loopback.
            backoff_base=0.2, backoff_cap=2.0, poll_interval=0.2,
            connect_timeout=1.0,
        )
        self.gateway: Optional[Gateway] = None
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def replica_by_url(self, url: str) -> InProcessReplica:
        return next(r for r in self.replicas if r.url == url.rstrip("/"))

    async def start(self) -> "GatewayHarness":
        for r in self.replicas:
            await r.start()
        self.gateway = Gateway([r.url for r in self.replicas], self.cfg)
        self._runner = web.AppRunner(build_gateway_app(self.gateway))
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        for r in self.replicas:
            await r.stop()
