"""Fleet telemetry aggregator: per-replica load time series + sustained
signals + the autoscaler's input contract.

Every ``x-substratus-load`` report and ``/loadz`` poll today informs one
routing decision and evaporates. This module retains them: each replica
gets a bounded ring-buffer time series and EWMA-smoothed sustained
signals (queue depth, slot occupancy, free KV fraction, transfer-queue
depth, shed rate), rolled up fleet-wide and published three ways:

  * ``GET /debug/fleetz`` (gateway/router.py, RBAC-gated like the
    server's /debug plane) — per-replica series + EWMAs + merged SLO
    percentiles, the human/debug view;
  * ``substratus_fleet_*`` gauges on the gateway's ``/metrics``;
  * ``FleetAggregator.signals()`` -> ``FleetSignals`` — the TYPED
    contract the controller autoscaler consumes (ROADMAP item 1):
    sustained signals only, no instantaneous noise, no HTTP parsing.

Ordering: reports carry a per-replica monotonic sequence number and a
replica wall-clock timestamp (``sq=``/``ts=`` on the header —
gateway/loadreport.py). A hedged or retried response can deliver an
OLD report after a newer one already arrived; seq catches that
exactly, and the wall clock rejects grossly stale retransmits (the
tolerance is generous — cross-host clock skew must not eat live
reports). Legacy reports (no ``sq=``) are always accepted.

Single-writer contract: the router calls everything from one asyncio
event loop (same as balancer.py) — no locks here, and adding threads
would need them (sublint's concurrency family watches this module).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Tuple

from substratus_tpu.gateway.loadreport import LoadReport
from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.observability.sketch import Sketch

# Fleet metric catalog (docs/observability.md "Fleet telemetry").
# Per-replica gauges are written at record time (event-loop cheap) and
# REMOVED on eviction so a dead replica stops being scraped as live.
for _name, _help in (
    ("substratus_fleet_queue_depth",
     "EWMA-smoothed waiting-queue depth per replica."),
    ("substratus_fleet_occupancy",
     "EWMA-smoothed decode-slot occupancy (active/max) per replica."),
    ("substratus_fleet_kv_free_frac",
     "EWMA-smoothed free KV-pool fraction per replica."),
    ("substratus_fleet_transfer_queue",
     "EWMA-smoothed KV transfer-queue depth (tq=) per replica."),
    ("substratus_fleet_shed_rate",
     "Replica-originated sheds (429/503) per second, windowed."),
    ("substratus_fleet_slo_burn",
     "Latest reported SLO-burn count per replica and slo."),
):
    METRICS.describe(_name, _help, type="gauge")
METRICS.describe(
    "substratus_fleet_replicas",
    "Replicas with live telemetry series, by role.", type="gauge",
)
METRICS.describe(
    "substratus_fleet_reports_total",
    "Load reports accepted into the fleet time series, by replica.",
    type="counter",
)
METRICS.describe(
    "substratus_fleet_reports_dropped_total",
    "Load reports rejected (reason: out_of_order|stale).",
    type="counter",
)

_EWMA_FIELDS = (
    "queue_depth", "occupancy", "kv_free_frac", "transfer_queue",
)
_GAUGE_OF = {
    "queue_depth": "substratus_fleet_queue_depth",
    "occupancy": "substratus_fleet_occupancy",
    "kv_free_frac": "substratus_fleet_kv_free_frac",
    "transfer_queue": "substratus_fleet_transfer_queue",
}


@dataclass(frozen=True)
class ReplicaSignals:
    """One replica's sustained load: EWMA-smoothed, staleness-annotated.
    The per-replica row of the autoscaler contract."""

    url: str
    role: str
    samples: int
    age_s: float  # since the last accepted report
    seq: int  # last accepted sequence number (-1 = legacy reports)
    queue_depth: float
    occupancy: float
    kv_free_frac: float
    transfer_queue: float
    shed_rate: float  # replica-originated sheds per second


@dataclass(frozen=True)
class FleetSignals:
    """The autoscaler's input (ROADMAP item 1): sustained fleet-wide
    rollups plus the per-replica rows they were rolled up from.

    Semantics a reconcile loop can act on directly: ``queue_depth`` and
    ``transfer_queue`` SUM across replicas (total backlog — scale-up
    pressure; transfer queue is the prefill:decode rebalance signal),
    ``occupancy`` is the MEAN (sustained utilization — scale-down
    evidence), ``kv_free_frac`` is the MIN (the tightest replica
    preempts first), ``shed_rate`` SUMS (user-visible overload)."""

    ts: float  # aggregator clock (monotonic) at snapshot
    replicas: Tuple[ReplicaSignals, ...]
    queue_depth: float
    occupancy: float
    kv_free_frac: float
    transfer_queue: float
    shed_rate: float
    roles: Mapping[str, int]


class _ReplicaSeries:
    """Ring-buffer time series + EWMA state for one replica."""

    __slots__ = (
        "url", "role", "last_seq", "last_wall_ts", "last_mono",
        "reports", "ring", "ewma", "sheds", "shed_times", "slo",
    )

    def __init__(self, url: str, capacity: int):
        self.url = url
        self.role = "both"
        self.last_seq = -1
        self.last_wall_ts = 0.0
        self.last_mono: Optional[float] = None
        self.reports = 0
        # (t_mono, queue_depth, occupancy, kv_free_frac, transfer_queue)
        self.ring: deque = deque(maxlen=capacity)
        self.ewma: Dict[str, float] = {}
        self.sheds = 0
        self.shed_times: deque = deque(maxlen=256)
        # {slo: {"threshold_s", "burn", "sketch": Sketch}} — latest
        # replica-cumulative state from /loadz (header reports are too
        # small to carry sketches).
        self.slo: Dict[str, dict] = {}


class FleetAggregator:
    """Per-replica ring-buffer series + EWMA signals + fleet rollups."""

    def __init__(
        self,
        capacity: int = 240,
        halflife_s: float = 10.0,
        stale_s: float = 30.0,
        evict_s: float = 120.0,
        shed_window_s: float = 30.0,
    ):
        if capacity < 1:
            raise ValueError(f"capacity {capacity} invalid")
        self.capacity = capacity
        self.halflife_s = max(1e-3, halflife_s)
        self.stale_s = stale_s
        self.evict_s = evict_s
        self.shed_window_s = max(1e-3, shed_window_s)
        self._series: Dict[str, _ReplicaSeries] = {}
        self._gauged_roles: set = set()

    # -- ingestion ---------------------------------------------------------

    def record(self, url: str, report: LoadReport,
               now: Optional[float] = None,
               snapshot: Optional[Mapping] = None) -> bool:
        """Ingest one load report. Returns False when the report was
        dropped (stale or out-of-order — the caller should not feed it
        to the balancer either). ``snapshot`` is the full /loadz body
        when the report came from a poll; it carries the SLO sketches."""
        now = time.monotonic() if now is None else now
        url = url.rstrip("/")
        sr = self._series.get(url)
        if sr is None:
            sr = self._series[url] = _ReplicaSeries(url, self.capacity)
        if report.seq >= 0 and sr.last_seq >= 0 \
                and report.seq <= sr.last_seq:
            # Sequence went backwards. A RESTARTED replica resets its
            # counter too — but its wall clock keeps moving, so a
            # fresh-process report carries ts strictly newer than the
            # last accepted one; only deliveries that are old on BOTH
            # axes are stale echoes of hedged/retried responses.
            restarted = (
                report.wall_ts > 0.0
                and report.wall_ts > sr.last_wall_ts
            )
            if not restarted:
                METRICS.inc(
                    "substratus_fleet_reports_dropped_total",
                    {"reason": "out_of_order"},
                )
                return False
            sr.last_seq = -1  # new counter epoch
        if report.wall_ts > 0.0 \
                and time.time() - report.wall_ts > self.stale_s:
            METRICS.inc(
                "substratus_fleet_reports_dropped_total",
                {"reason": "stale"},
            )
            return False

        occupancy = report.active_slots / max(1, report.max_slots)
        values = {
            "queue_depth": float(report.queue_depth),
            "occupancy": occupancy,
            "kv_free_frac": float(report.kv_free_frac),
            "transfer_queue": float(report.transfer_queue),
        }
        if sr.last_mono is None or not sr.ewma:
            for k, v in values.items():
                sr.ewma[k] = v
        else:
            # Time-aware EWMA: the smoothing weight decays with the gap
            # since the previous report, so a replica reporting at 100
            # rps and one polled every 2 s smooth over the SAME wall
            # time, not the same sample count.
            dt = max(0.0, now - sr.last_mono)
            w = 0.5 ** (dt / self.halflife_s)
            for k, v in values.items():
                sr.ewma[k] = w * sr.ewma[k] + (1.0 - w) * v
        sr.ring.append((
            round(now, 3), report.queue_depth, round(occupancy, 4),
            round(report.kv_free_frac, 4), report.transfer_queue,
        ))
        sr.role = report.role
        if report.seq >= 0:
            sr.last_seq = report.seq
        if report.wall_ts > 0.0:
            sr.last_wall_ts = report.wall_ts
        sr.last_mono = now
        sr.reports += 1
        if snapshot is not None:
            self._record_slo(sr, snapshot.get("slo"))
        METRICS.inc("substratus_fleet_reports_total", {"replica": url})
        for k, v in sr.ewma.items():
            METRICS.set(_GAUGE_OF[k], round(v, 4), {"replica": url})
        self._evict_dead(now)
        return True

    def _record_slo(self, sr: _ReplicaSeries, slo: object) -> None:
        if not isinstance(slo, Mapping):
            return
        for name, entry in slo.items():
            if not isinstance(entry, Mapping):
                continue
            try:
                sketch = Sketch.from_dict(entry.get("sketch") or {})
            except ValueError:
                continue  # garbled sketch must not poison the merge
            sr.slo[str(name)] = {
                "threshold_s": float(entry.get("threshold_s", 0.0)),
                "burn": int(entry.get("burn", 0)),
                "sketch": sketch,
            }
            METRICS.set(
                "substratus_fleet_slo_burn",
                sr.slo[str(name)]["burn"],
                {"replica": sr.url, "slo": str(name)},
            )

    def record_shed(self, url: str, now: Optional[float] = None) -> None:
        """A replica answered 429/503 (shedding by contract): the
        sustained shed rate is overload evidence no queue-depth EWMA
        carries once the queue bound is doing its job."""
        now = time.monotonic() if now is None else now
        url = url.rstrip("/")
        sr = self._series.get(url)
        if sr is None:
            sr = self._series[url] = _ReplicaSeries(url, self.capacity)
        sr.sheds += 1
        sr.shed_times.append(now)
        METRICS.set(
            "substratus_fleet_shed_rate",
            round(self._shed_rate(sr, now), 4), {"replica": url},
        )

    def _shed_rate(self, sr: _ReplicaSeries, now: float) -> float:
        cutoff = now - self.shed_window_s
        recent = sum(1 for t in sr.shed_times if t > cutoff)
        return recent / self.shed_window_s

    def forget(self, url: str) -> None:
        """Drop one replica's series (and its gauges) immediately. The
        autoscale supervisor calls this on drain/replacement — it KNOWS
        the replica is gone, and waiting out evict_s would keep a
        removed replica's last load in the rollups the planner reads."""
        url = url.rstrip("/")
        sr = self._series.pop(url, None)
        if sr is None:
            return
        for gauge in _GAUGE_OF.values():
            METRICS.remove(gauge, {"replica": url})
        METRICS.remove("substratus_fleet_shed_rate", {"replica": url})
        for name in sr.slo:
            METRICS.remove(
                "substratus_fleet_slo_burn",
                {"replica": url, "slo": name},
            )

    def _evict_dead(self, now: float) -> None:
        """Forget replicas with no accepted report for evict_s: a
        scaled-down or crashed replica must drop out of the rollups
        (and /metrics) instead of pinning its last-known load forever."""
        for url in [
            u for u, sr in self._series.items()
            if sr.last_mono is not None
            and now - sr.last_mono > self.evict_s
        ]:
            self.forget(url)

    # -- consumption -------------------------------------------------------

    def replica_signals(self, sr: _ReplicaSeries,
                        now: float) -> ReplicaSignals:
        return ReplicaSignals(
            url=sr.url,
            role=sr.role,
            samples=sr.reports,
            age_s=round(now - sr.last_mono, 3)
            if sr.last_mono is not None else float("inf"),
            seq=sr.last_seq,
            queue_depth=round(sr.ewma.get("queue_depth", 0.0), 4),
            occupancy=round(sr.ewma.get("occupancy", 0.0), 4),
            kv_free_frac=round(sr.ewma.get("kv_free_frac", 1.0), 4),
            transfer_queue=round(sr.ewma.get("transfer_queue", 0.0), 4),
            shed_rate=round(self._shed_rate(sr, now), 4),
        )

    def signals(self, now: Optional[float] = None) -> FleetSignals:
        """The autoscaler contract: sustained per-replica signals +
        fleet rollups. Pure data — consumers never touch HTTP, headers,
        or the aggregator's internals."""
        now = time.monotonic() if now is None else now
        self._evict_dead(now)
        reps = tuple(
            self.replica_signals(sr, now)
            for sr in sorted(self._series.values(), key=lambda s: s.url)
        )
        roles: Dict[str, int] = {}
        for r in reps:
            roles[r.role] = roles.get(r.role, 0) + 1
        for role, n in roles.items():
            METRICS.set("substratus_fleet_replicas", n, {"role": role})
        for role in self._gauged_roles - set(roles):
            METRICS.remove("substratus_fleet_replicas", {"role": role})
        self._gauged_roles = set(roles)
        return FleetSignals(
            ts=now,
            replicas=reps,
            queue_depth=round(sum(r.queue_depth for r in reps), 4),
            occupancy=round(
                sum(r.occupancy for r in reps) / len(reps), 4
            ) if reps else 0.0,
            kv_free_frac=round(
                min((r.kv_free_frac for r in reps), default=1.0), 4
            ),
            transfer_queue=round(sum(r.transfer_queue for r in reps), 4),
            shed_rate=round(sum(r.shed_rate for r in reps), 4),
            roles=roles,
        )

    def merged_slo(self) -> Dict[str, dict]:
        """Fleet-wide SLO view: per-SLO merged sketch percentiles +
        summed burn across replicas (exact — fixed-bucket sketches
        merge by adding counts, observability/sketch.py)."""
        out: Dict[str, dict] = {}
        for sr in self._series.values():
            for name, entry in sr.slo.items():
                agg = out.get(name)
                if agg is None:
                    agg = out[name] = {
                        "threshold_s": entry["threshold_s"],
                        "burn": 0,
                        "sketch": Sketch(entry["sketch"].bounds),
                    }
                try:
                    agg["sketch"].merge(entry["sketch"])
                except ValueError:
                    continue  # mismatched bounds: skip, never corrupt
                agg["burn"] += entry["burn"]
        rendered: Dict[str, dict] = {}
        for name, agg in out.items():
            sk: Sketch = agg["sketch"]
            rendered[name] = {
                "threshold_s": agg["threshold_s"],
                "burn": agg["burn"],
                "count": sk.count,
                "p50_s": sk.quantile(0.5),
                "p90_s": sk.quantile(0.9),
                "p99_s": sk.quantile(0.99),
            }
        return rendered

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The /debug/fleetz payload: per-replica series + EWMAs + SLO
        percentiles, and the fleet rollup (FleetSignals, rendered)."""
        now = time.monotonic() if now is None else now
        sig = self.signals(now)
        replicas = {}
        for sr in self._series.values():
            rs = self.replica_signals(sr, now)
            rep_slo = {}
            for name, entry in sr.slo.items():
                sk: Sketch = entry["sketch"]
                rep_slo[name] = {
                    "threshold_s": entry["threshold_s"],
                    "burn": entry["burn"],
                    "count": sk.count,
                    "p50_s": sk.quantile(0.5),
                    "p99_s": sk.quantile(0.99),
                }
            replicas[sr.url] = {
                "role": sr.role,
                "seq": sr.last_seq,
                "age_s": rs.age_s,
                "reports": sr.reports,
                "sheds": sr.sheds,
                "ewma": {
                    "queue_depth": rs.queue_depth,
                    "occupancy": rs.occupancy,
                    "kv_free_frac": rs.kv_free_frac,
                    "transfer_queue": rs.transfer_queue,
                    "shed_rate": rs.shed_rate,
                },
                # The ring, oldest first: [t_mono, queue_depth,
                # occupancy, kv_free_frac, transfer_queue] rows.
                "series": [list(row) for row in sr.ring],
                "slo": rep_slo,
            }
        return {
            "now_mono": round(now, 3),
            "halflife_s": self.halflife_s,
            "replicas": replicas,
            "fleet": {
                "replicas": len(sig.replicas),
                "roles": dict(sig.roles),
                "queue_depth": sig.queue_depth,
                "occupancy": sig.occupancy,
                "kv_free_frac": sig.kv_free_frac,
                "transfer_queue": sig.transfer_queue,
                "shed_rate": sig.shed_rate,
                "slo": self.merged_slo(),
            },
        }
