"""Replica set + routing policy: least-loaded power-of-two-choices.

Routing combines two signals per replica:

  * the gateway's OWN in-flight count — exact, instant, but blind to
    load arriving through other gateways or direct clients;
  * the replica's last LoadReport (header-piggybacked or polled) —
    global truth, but stale by up to one report interval.

Power-of-two-choices over that combined score gets within a constant
factor of full least-loaded routing while keeping herd behavior out:
when every gateway deterministically picks the globally least-loaded
replica from the same stale snapshot, they all dogpile it; sampling
two and taking the better one provably avoids that (the classic
balls-into-bins result ParvaGPU's cluster tier leans on too).

Admission windows: a replica stops being eligible once the gateway has
`max_inflight` requests outstanding on it — bounded per-replica
in-flight beats unbounded proxy queues, and "no eligible replica"
is the router's load-shedding signal (503 + Retry-After).
"""
from __future__ import annotations

import random
import time
from typing import Dict, List, Optional

from substratus_tpu.gateway.health import CircuitBreaker
from substratus_tpu.gateway.loadreport import LoadReport


class Replica:
    def __init__(self, url: str, max_inflight: int = 32,
                 backoff_base: float = 0.5, backoff_cap: float = 30.0):
        self.url = url.rstrip("/")
        self.max_inflight = max_inflight
        self.inflight = 0
        self.report = LoadReport()
        # Readiness as the replica itself declares it: the router's
        # /loadz poller clears this the moment a replica answers 503
        # (draining or not yet serving), so a scale-down stops
        # receiving new admissions within ONE poll cycle instead of
        # waiting out report staleness. Distinct from the circuit: a
        # draining replica is healthy, it is just leaving.
        self.ready = True
        self.circuit = CircuitBreaker(
            backoff_base=backoff_base, backoff_cap=backoff_cap
        )

    def score(self) -> float:
        """Lower = preferred. Local in-flight is the freshest signal;
        the report adds cross-gateway visibility."""
        return self.inflight + self.report.score()

    def snapshot(self, now: float) -> dict:
        return {
            "url": self.url,
            "inflight": self.inflight,
            "max_inflight": self.max_inflight,
            "ready": self.ready,
            "available": self.circuit.available(now),
            "ejected_for_s": max(
                0.0, round(self.circuit.ejected_until - now, 3)
            ),
            "consecutive_failures": self.circuit.consecutive_failures,
            "ejections": self.circuit.ejections,
            "report": {
                "queue_depth": self.report.queue_depth,
                "active_slots": self.report.active_slots,
                "max_slots": self.report.max_slots,
                "kv_free_frac": round(self.report.kv_free_frac, 3),
                "age_s": round(now - self.report.ts, 3),
            },
        }


class Balancer:
    """The replica table. Single event loop owner: the router calls
    everything from one asyncio loop, so there is no locking — adding
    threads here would need one."""

    def __init__(self, urls: List[str], max_inflight: int = 32,
                 backoff_base: float = 0.5, backoff_cap: float = 30.0,
                 seed: Optional[int] = None):
        self.replicas: Dict[str, Replica] = {}
        self._rng = random.Random(seed)
        self._max_inflight = max_inflight
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap
        for u in urls:
            self.add(u)

    def add(self, url: str) -> Replica:
        url = url.rstrip("/")
        rep = self.replicas.get(url)
        if rep is None:
            rep = self.replicas[url] = Replica(
                url, self._max_inflight,
                backoff_base=self._backoff_base,
                backoff_cap=self._backoff_cap,
            )
        return rep

    def remove(self, url: str) -> None:
        self.replicas.pop(url.rstrip("/"), None)

    # -- routing -----------------------------------------------------------

    def eligible(self, now: Optional[float] = None,
                 exclude: tuple = ()) -> List[Replica]:
        now = time.monotonic() if now is None else now
        return [
            r for r in self.replicas.values()
            if r.url not in exclude
            and r.ready
            and r.circuit.available(now)
            and r.inflight < r.max_inflight
        ]

    def pick(self, now: Optional[float] = None,
             exclude: tuple = (), adapter: Optional[str] = None,
             role: Optional[str] = None) -> Optional[Replica]:
        """Power-of-two-choices among eligible replicas; None = shed.
        `exclude` carries the urls a hedged retry already failed on.

        `role` is the serving phase the request needs (disaggregated
        serving, serve/disagg.py): completions route to the prefill
        pool, so `role="prefill"` keeps replicas reporting that role
        (or "both" — monolithic deployments are unaffected) and always
        drops decode-role replicas, which only accept KV migrations
        from the prefill tier, never client admissions.

        `adapter` is the request's LoRA adapter id (the OpenAI `model`
        field): replicas whose last load report lists it resident are
        preferred — same-tenant traffic concentrates where the weights
        (and that tenant's prefix-cache pages) already live, instead of
        making every replica hot-load every adapter. p2c still runs
        WITHIN the resident subset, so affinity never defeats load
        balancing; with no resident replica it falls back to the full
        candidate set (the chosen replica hot-loads on admission)."""
        cands = self.eligible(now, exclude)
        if role:
            # A decode replica 503s client completions anyway; dropping
            # it here saves the wasted attempt (and the hedge budget).
            cands = [
                r for r in cands
                if r.report.role in (role, "both")
            ]
        if not cands:
            return None
        if adapter:
            resident = [
                r for r in cands if adapter in r.report.adapters
            ]
            if resident:
                cands = resident
        if len(cands) <= 2:
            return min(cands, key=lambda r: r.score())
        a, b = self._rng.sample(cands, 2)
        return a if a.score() <= b.score() else b

    def saturated(self, now: Optional[float] = None) -> bool:
        """Every replica healthy-but-full: the shed should say 'soon'
        (Retry-After ~ a decode wave), not 'back off hard'."""
        now = time.monotonic() if now is None else now
        live = [
            r for r in self.replicas.values() if r.circuit.available(now)
        ]
        return bool(live) and all(
            r.inflight >= r.max_inflight for r in live
        )

    # -- bookkeeping (router calls around each proxied request) ------------

    def acquire(self, rep: Replica) -> None:
        rep.inflight += 1

    def release(self, rep: Replica) -> None:
        rep.inflight = max(0, rep.inflight - 1)

    def observe_report(self, rep: Replica, report: LoadReport) -> None:
        rep.report = report

    def observe_ready(self, rep: Replica, ready: bool) -> None:
        """Poller verdict on the replica's own readiness answer: 200 on
        /loadz = admittable, 503 = draining/not-ready — out of the
        eligible set NOW, before any report ages out."""
        rep.ready = ready

    def observe_success(self, rep: Replica) -> None:
        rep.circuit.record_success()

    def observe_failure(self, rep: Replica,
                        now: Optional[float] = None) -> float:
        return rep.circuit.record_failure(
            time.monotonic() if now is None else now
        )

    def snapshot(self, now: Optional[float] = None) -> List[dict]:
        now = time.monotonic() if now is None else now
        return [
            r.snapshot(now)
            for r in sorted(self.replicas.values(), key=lambda r: r.url)
        ]
