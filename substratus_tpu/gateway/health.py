"""Per-replica health and circuit state.

Failure policy: transport-level failures (connect refused, reset,
timeout) eject the replica for an exponentially growing backoff window
— 0.5 s, 1 s, 2 s, ... capped at 30 s — because a replica that just
dropped a connection is overwhelmingly likely to drop the next one too,
and every request sent there during the outage pays a full connect
timeout. After the window the circuit is HALF-OPEN: the replica is
eligible again, one success closes the circuit (counter resets), one
failure re-ejects with the doubled window. Application-level responses
never eject (a 429/503 is the replica TALKING — shedding by contract,
not dead); they only steer the balancer via the load report.

All times are caller-supplied monotonic seconds so tests drive the
clock; nothing here sleeps or threads.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class CircuitBreaker:
    backoff_base: float = 0.5  # first ejection window (seconds)
    backoff_cap: float = 30.0
    consecutive_failures: int = 0
    ejected_until: float = 0.0  # monotonic deadline; 0 = closed
    ejections: int = 0  # lifetime count (metrics)

    def available(self, now: float) -> bool:
        """Eligible for traffic: circuit closed, or backoff expired
        (half-open trial)."""
        return now >= self.ejected_until

    @property
    def half_open(self) -> bool:
        """A past ejection whose window lapsed without a success yet —
        the next request is the trial."""
        return self.consecutive_failures > 0

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self.ejected_until = 0.0

    def record_failure(self, now: float) -> float:
        """Transport failure: eject with exponential backoff. Returns
        the backoff window just applied (seconds)."""
        self.consecutive_failures += 1
        window = min(
            self.backoff_cap,
            self.backoff_base * (2 ** (self.consecutive_failures - 1)),
        )
        self.ejected_until = now + window
        self.ejections += 1
        return window
