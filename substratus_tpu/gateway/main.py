"""Gateway container entrypoint.

    python -m substratus_tpu.gateway.main --replicas http://a:8080,http://b:8080
    python -m substratus_tpu.gateway.main --discover my-server-replicas:8080

`--discover` takes a DNS name (the controller passes the engine
Deployment's headless Service) and re-resolves it periodically, so
scale-up/down and pod churn flow into the replica table without a
restart; `--replicas` is the static list for local runs and tests.

Deliberately jax-free: the gateway routes bytes, it never touches a
model, so it starts in milliseconds and its Deployment can scale
independently of the engine replicas.
"""
from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
from typing import List, Optional

from aiohttp import web

from substratus_tpu.gateway.router import (
    Gateway,
    GatewayConfig,
    build_gateway_app,
)

log = logging.getLogger("substratus.gateway")


async def resolve_replicas(name: str, port: int) -> List[str]:
    """DNS name -> replica urls (one per A/AAAA record — a headless
    Service resolves to every ready pod)."""
    loop = asyncio.get_running_loop()
    try:
        infos = await loop.getaddrinfo(name, port, type=0)
    except OSError:
        return []
    urls = []
    for _, _, _, _, sockaddr in infos:
        host = sockaddr[0]
        if ":" in host:  # IPv6 literal
            host = f"[{host}]"
        urls.append(f"http://{host}:{port}")
    return sorted(set(urls))


async def discover_loop(gw: Gateway, name: str, port: int,
                        interval: float) -> None:
    """Sync the balancer's replica set with DNS. Known-but-gone replicas
    are removed only when DNS answered (an empty answer on a resolver
    blip must not dump the whole table)."""
    while True:
        urls = await resolve_replicas(name, port)
        if urls:
            for u in urls:
                gw.balancer.add(u)
            for u in list(gw.balancer.replicas):
                if u not in urls:
                    gw.balancer.remove(u)
        await asyncio.sleep(interval)


async def run_gateway(gw: Gateway, host: str, port: int,
                      discover: Optional[str] = None,
                      discover_interval: float = 5.0,
                      ready_event: Optional[asyncio.Event] = None,
                      stop_event: Optional[asyncio.Event] = None) -> None:
    """Serve until SIGTERM/SIGINT (or `stop_event` for embedders)."""
    app = build_gateway_app(gw)
    runner = web.AppRunner(app, handle_signals=False)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()

    tasks = []
    if discover:
        name, _, dport = discover.partition(":")
        tasks.append(asyncio.get_running_loop().create_task(
            discover_loop(
                gw, name, int(dport or 8080), discover_interval
            )
        ))
    stop = stop_event or asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix event loops
            pass
    log.info("gateway on %s:%s (%d replicas)", host, port,
             len(gw.balancer.replicas))
    if ready_event is not None:
        ready_event.set()
    try:
        await stop.wait()
    finally:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        await runner.cleanup()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument(
        "--replicas", default="",
        help="comma-separated replica base urls (static set)",
    )
    ap.add_argument(
        "--discover", default="",
        help="DNS name[:port] re-resolved into the replica set "
             "(headless Service of the engine Deployment)",
    )
    ap.add_argument("--discover-interval", type=float, default=5.0)
    ap.add_argument(
        "--max-inflight", type=int,
        default=int(os.environ.get("GATEWAY_MAX_INFLIGHT", 32)),
        help="per-replica in-flight window; beyond it requests shed",
    )
    ap.add_argument(
        "--rate", type=float,
        default=float(os.environ.get("GATEWAY_RATE", 0)),
        help="per-API-key requests/second (0 = rate limiting off)",
    )
    ap.add_argument("--burst", type=float, default=None)
    ap.add_argument(
        "--adapter-rate", type=float,
        default=float(os.environ.get("GATEWAY_ADAPTER_RATE", 0)),
        help="per-adapter (OpenAI model field) requests/second quota "
             "(0 = off) — multi-tenant fairness on shared engines",
    )
    ap.add_argument("--adapter-burst", type=float, default=None)
    ap.add_argument(
        "--default-timeout", type=float,
        default=float(os.environ.get("GATEWAY_DEFAULT_TIMEOUT", 0)),
        help="deadline stamped on requests that carry none (seconds; "
             "0 = unbounded)",
    )
    ap.add_argument("--poll-interval", type=float, default=2.0)
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    # Join the spawner's trace (controller-stamped TRACEPARENT) the same
    # way serve.main does, and honor the JSONL span export for lint.
    from substratus_tpu.observability.propagation import context_from_env
    from substratus_tpu.observability.tracing import tracer

    with tracer.span("gateway.start", parent=context_from_env()):
        pass
    trace_export = os.environ.get("SUBSTRATUS_TRACE_EXPORT")
    if trace_export:
        import atexit

        atexit.register(tracer.export_jsonl, trace_export)

    urls = [u for u in args.replicas.split(",") if u.strip()]
    if not urls and not args.discover:
        raise SystemExit("gateway: need --replicas or --discover")
    gw = Gateway(urls, GatewayConfig(
        max_inflight=args.max_inflight,
        rate=args.rate,
        burst=args.burst,
        adapter_rate=args.adapter_rate,
        adapter_burst=args.adapter_burst,
        default_timeout=args.default_timeout,
        poll_interval=args.poll_interval,
    ))
    asyncio.run(run_gateway(
        gw, args.host, args.port,
        discover=args.discover or None,
        discover_interval=args.discover_interval,
    ))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
