"""Load-report protocol between engine replicas and the gateway.

A replica's load is four cheap host-side numbers the engine already
tracks (no device read, no lock): waiting-queue depth, occupied decode
slots, the slot ceiling, and the free fraction of the KV pool. The
server exposes the snapshot two ways:

  * `GET /loadz` — pull: the gateway's poller and k8s-style readiness
    checks (a draining server answers 503, which is how the gateway
    learns a replica is leaving BEFORE its streams finish);
  * `x-substratus-load` response header — push: stamped on every
    completion response, so a gateway routing live traffic learns each
    replica's load passively at the rate it talks to it, with zero
    extra round trips.

The header value is a comma-joined `k=v` list (`q=3 a=2 m=8 kvf=0.75`
shaped), chosen over JSON so it never needs quoting inside an HTTP
header and stays greppable in access logs.

Wire-contract note: sublint's `protodrift` family statically checks
that every key `to_header` emits is parsed by `from_header` and vice
versa (docs/development.md#static-analysis-sublint) — add both sides
in the same change or `make lint` fails.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Tuple

HEADER = "x-substratus-load"

# Resident-adapter ids on the header are capped: affinity only needs
# "is my adapter here", and an unbounded tenant list would bloat every
# response by the whole roster.
MAX_HEADER_ADAPTERS = 8


@dataclass
class LoadReport:
    """One replica's load snapshot, as routed on."""

    queue_depth: int = 0  # requests waiting for a decode slot
    active_slots: int = 0  # slots currently generating
    max_slots: int = 1  # configured decode slot ceiling (max_batch)
    kv_free_frac: float = 1.0  # free fraction of the KV pool [0, 1]
    # Resident LoRA adapter ids (serve/adapters.py) — the gateway's
    # adapter-affinity scoring prefers replicas that already hold a
    # request's adapter (balancer.py).
    adapters: Tuple[str, ...] = ()
    # Disaggregated serving (serve/disagg.py): which phase this replica
    # runs ("both" = monolithic, "prefill", "decode") and its transfer
    # backlog (handoffs waiting to ship / migrations waiting to board).
    # Admissions route to the prefill pool; decode replicas never take
    # client completions directly (balancer.pick(role=...)).
    role: str = "both"
    transfer_queue: int = 0
    # Ordering (gateway/fleet.py): a per-replica monotonic report
    # sequence number (`sq=`) and the replica's wall clock at snapshot
    # time (`ts=`). A hedged or retried response can deliver an OLD
    # report after a newer one — the fleet aggregator drops those by
    # seq, and grossly stale retransmits by wall clock. -1 / 0.0 =
    # legacy report (always accepted; pre-telemetry replicas keep
    # working byte-identically).
    seq: int = -1
    wall_ts: float = 0.0
    # Hot weight-swap generation (`wv=`, serve/engine.py swap_params):
    # lets the gateway/rollout tooling see which checkpoint generation
    # each replica serves without an extra poll. 0 = boot weights /
    # pre-swap replica.
    weights_version: int = 0
    # Stamped by the RECEIVER (gateway clock): reports age out rather
    # than mislead — a 30 s old "idle" beats routing storms.
    ts: float = field(default_factory=time.monotonic)

    def score(self) -> float:
        """Routing score: lower = less loaded. Queue depth dominates
        (each queued request is a whole forthcoming batch residency),
        slot occupancy breaks ties, KV pressure nudges away from
        replicas about to preempt."""
        occupancy = self.active_slots / max(1, self.max_slots)
        kv_pressure = 1.0 - self.kv_free_frac
        # Transfer backlog counts like queued work at half weight: a
        # handoff waiting to ship blocks a client stream, but drains
        # faster than a whole batch residency.
        return (
            2.0 * self.queue_depth + occupancy + 0.5 * kv_pressure
            + 0.5 * self.transfer_queue
        )

    def to_header(self) -> str:
        out = (
            f"q={self.queue_depth} a={self.active_slots} "
            f"m={self.max_slots} kvf={self.kv_free_frac:.3f}"
        )
        if self.seq >= 0:
            out += f" sq={self.seq}"
        if self.wall_ts > 0.0:
            out += f" ts={self.wall_ts:.3f}"
        if self.role != "both":
            # One char on the wire; absent = "both" (monolithic replicas
            # and pre-disaggregation gateways stay byte-identical).
            out += f" r={self.role[0]}"
        if self.transfer_queue:
            out += f" tq={self.transfer_queue}"
        if self.weights_version:
            # Absent = 0 (boot weights): pre-swap replicas and gateways
            # stay byte-identical.
            out += f" wv={self.weights_version}"
        if self.adapters:
            # `;`-joined: header values stay comma/space-free so the
            # k=v split survives; ids with either separator are dropped
            # rather than corrupting the whole report.
            ids = [
                a for a in self.adapters[:MAX_HEADER_ADAPTERS]
                if a and not set(a) & {" ", ",", ";", "="}
            ]
            if ids:
                out += f" ad={';'.join(ids)}"
        return out

    @classmethod
    def from_header(cls, value: str) -> "LoadReport":
        """Parse a header value; unknown keys ignored, malformed fields
        fall back to the defaults (a half-parsed report still beats no
        report)."""
        kv = {}
        adapters: Tuple[str, ...] = ()
        role = "both"
        for part in value.replace(",", " ").split():
            if "=" not in part:
                continue
            k, _, v = part.partition("=")
            if k == "ad":
                adapters = tuple(a for a in v.split(";") if a)
                continue
            if k == "r":
                role = {"p": "prefill", "d": "decode"}.get(v, "both")
                continue
            try:
                kv[k] = float(v)
            except ValueError:
                continue
        return cls(
            queue_depth=int(kv.get("q", 0)),
            active_slots=int(kv.get("a", 0)),
            max_slots=max(1, int(kv.get("m", 1))),
            kv_free_frac=min(1.0, max(0.0, kv.get("kvf", 1.0))),
            adapters=adapters,
            role=role,
            transfer_queue=max(0, int(kv.get("tq", 0))),
            seq=int(kv.get("sq", -1)),
            wall_ts=max(0.0, kv.get("ts", 0.0)),
            weights_version=max(0, int(kv.get("wv", 0))),
        )

    @classmethod
    def from_snapshot(cls, snap: dict) -> "LoadReport":
        """From the engine's load_snapshot() dict (the /loadz body)."""
        return cls(
            queue_depth=int(snap.get("queue_depth", 0)),
            active_slots=int(snap.get("active_slots", 0)),
            max_slots=max(1, int(snap.get("max_slots", 1))),
            kv_free_frac=min(
                1.0, max(0.0, float(snap.get("kv_free_frac", 1.0)))
            ),
            adapters=tuple(
                str(a) for a in (snap.get("adapters") or ())
            ),
            role=str(snap.get("role", "both") or "both"),
            transfer_queue=max(0, int(snap.get("transfer_queue_depth", 0))),
            seq=int(snap.get("load_seq", -1)),
            wall_ts=max(0.0, float(snap.get("load_ts", 0.0))),
            weights_version=max(0, int(snap.get("weights_version", 0))),
        )
