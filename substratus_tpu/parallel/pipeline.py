"""GPipe-style pipeline parallelism over the "stage" mesh axis.

Greenfield (SURVEY.md §2.3 — the reference has no parallelism at all). The
transformer's layer stack is split into `n_stages` contiguous groups, one
per device along the "stage" axis; activations flow stage-to-stage via
`jax.lax.ppermute` (XLA lowers to neighbor transfers — ICI within a slice,
DCN across slices, which is why "stage" sits next to "data" in MESH_AXES).

Two schedules:

* `pipeline_forward` — classic GPipe. M microbatches enter stage 0 one step
  apart; step t has stage s working on microbatch t-s; after M + S - 1 steps
  every microbatch has exited the last stage. Backward is jax.grad through
  the same scan (ppermute is differentiable): synchronous fill-drain, so
  activation memory grows O(M) with the microbatch count.

* `pipeline_train_step_1f1b` — one-forward-one-backward with an explicit
  hand-written backward (jax.vjp per stage, inputs stashed and the stage
  recomputed at backward time, Megatron-style remat). Each stage holds at
  most 2S-1 in-flight microbatch inputs, so activation memory is O(S) —
  INDEPENDENT of M. That is 1F1B's point: M can grow to amortize the
  bubble (fraction (2S-2)/(M+2S-2)) without blowing up memory, where GPipe
  under jax.grad cannot. Under XLA's SPMD lockstep all stages execute every
  tick (invalid slots compute on garbage and are masked out), the same
  trade the GPipe path already makes in its warmup/drain steps.

Embedding and the LM head are replicated; the GPipe path applies the head
outside the pipelined region, the 1F1B path folds head+loss into the last
stage's tick (the backward needs dL/d(out) as soon as a microbatch exits).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from substratus_tpu.models import llama
from substratus_tpu.models.llama import LlamaConfig, Params
from substratus_tpu.ops.basics import rms_norm
from substratus_tpu.ops.quant import materialize
from substratus_tpu.utils import jaxcompat

AXIS = "stage"


def stage_params(params: Params, n_stages: int) -> Params:
    """Reshape stacked layers [L, ...] -> [n_stages, L/S, ...]; embed/norm/
    head stay replicated."""
    L = jax.tree.leaves(params["layers"])[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible into {n_stages} stages")
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda x: x.reshape((n_stages, L // n_stages) + x.shape[1:]),
        params["layers"],
    )
    return out


def _stage_fn(local_layers: Params, x: jnp.ndarray, positions, cfg, train):
    """Apply this stage's local layer stack (scan over layers). Returns
    (x_out, summed MoE aux for the stage — 0 for dense models)."""

    def body(carry, lp):
        x_out, _, aux = llama._block(carry, lp, positions, cfg, None, train=train)
        return x_out, aux

    x, auxes = lax.scan(body, x, local_layers)
    return x, auxes.sum()


def pipeline_forward(
    params: Params,  # stage_params() output, "layers" sharded on stage
    tokens: jnp.ndarray,  # [B, S]
    cfg: LlamaConfig,
    n_stages: int,
    n_microbatches: int,
    train: bool = False,
):
    """Pipelined (logits [B, S, vocab], moe_aux scalar). Call inside jit
    with an ambient mesh (jax.set_mesh) that has a "stage" axis of size
    n_stages. For MoE models the router load-balancing aux is accumulated
    across stages and valid microbatches (0.0 for dense models); `train`
    selects the capacity-dispatch expert path like llama.forward."""
    B, S = tokens.shape
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by {n_microbatches} microbatches")
    mb = B // n_microbatches
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (mb, S))

    x = materialize(params["tok_embed"], cfg.dtype)[tokens]
    micro = x.reshape(n_microbatches, mb, S, cfg.dim)

    layers_spec = P(AXIS)  # leading stage dim sharded; rest replicated

    def pipelined(layers_local, micro):
        # layers_local leaves: [1, L/S, ...] (this stage's group).
        local = jax.tree.map(lambda a: a[0], layers_local)
        stage = lax.axis_index(AXIS)
        n = n_stages
        M = n_microbatches
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, t):
            act = carry  # activation arriving from the previous stage
            inject = micro[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(stage == 0, inject, act)
            out, aux = _stage_fn(local, inp, positions, cfg, train)
            # This stage processes microbatch t - stage; aux from warmup/
            # drain steps (garbage inputs) must not count.
            mb_idx = t - stage
            valid = jnp.logical_and(mb_idx >= 0, mb_idx < M)
            aux = jnp.where(valid, aux, 0.0)
            # The last stage's output at step t is microbatch t-(n-1).
            collect = jnp.where(stage == n - 1, out, jnp.zeros_like(out))
            act_next = lax.ppermute(out, AXIS, perm)
            return act_next, (collect, aux)

        init = jnp.zeros((mb, S, cfg.dim), cfg.dtype)
        # Mark the carry as stage-varying: the scan's output (post-ppermute)
        # is device-varying, and scan requires carry types to match.
        init = jaxcompat.pcast(init, (AXIS,), to="varying")
        _, (collected, auxes) = lax.scan(step, init, jnp.arange(M + n - 1))
        # Valid outputs live at steps n-1 .. n-1+M-1; broadcast them off the
        # last stage to every stage (zeros elsewhere -> psum is a select).
        outs = collected[n - 1:]
        outs = lax.psum(outs, AXIS)
        # Mean aux per (layer, microbatch): sum over stages/steps, then
        # normalize like llama.forward's kv["moe_aux"].mean().
        aux_total = lax.psum(auxes.sum(), AXIS) / (cfg.n_layers * M)
        return outs, aux_total  # [M, mb, S, D], scalar

    outs, aux = jaxcompat.shard_map(
        pipelined,
        in_specs=(layers_spec, P()),
        out_specs=(P(), P()),
        axis_names={AXIS},
    )(params["layers"], micro)

    x = outs.reshape(B, S, cfg.dim)
    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum(
            "bsd,vd->bsv", x, materialize(params["tok_embed"], cfg.dtype)
        )
    else:
        logits = jnp.einsum(
            "bsd,dv->bsv", x, materialize(params["lm_head"], cfg.dtype)
        )
    return logits.astype(jnp.float32), aux


def pipeline_train_step_1f1b(
    params: Params,  # stage_params() output
    tokens: jnp.ndarray,  # [B, S] int32 (next-token loss computed inside)
    cfg: LlamaConfig,
    n_stages: int,
    n_microbatches: int,
    weights: Optional[jnp.ndarray] = None,  # [B, S] loss mask
    train: bool = True,
):
    """One 1F1B forward+backward: returns (loss, grads, moe_aux) with grads
    matching the stage_params() tree. Call inside jit with an ambient mesh
    holding a "stage" axis of size n_stages.

    Schedule (full ticks, fwd-then-bwd per tick): stage s forwards
    microbatch f = t - s and backwards b = t - (2S-2-s); the last stage
    computes head+loss and starts a microbatch's backward the same tick its
    forward finishes. A microbatch's input is stashed at forward time and
    the stage recomputed at backward time (jax.vjp), so the stash — a ring
    of 2S-1 inputs — is the only activation state, independent of M.
    """
    if cfg.tie_embeddings:
        raise NotImplementedError("1F1B with tied embeddings")
    B, S = tokens.shape
    if B % n_microbatches:
        raise ValueError(
            f"batch {B} not divisible by {n_microbatches} microbatches"
        )
    M = n_microbatches
    n = n_stages
    mb = B // M
    K = 2 * n - 1  # stash ring size (max in-flight at stage 0)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (mb, S))
    dt = cfg.dtype

    x = materialize(params["tok_embed"], dt)[tokens]
    micro_x = x.reshape(M, mb, S, cfg.dim)
    micro_tok = tokens.reshape(M, mb, S)
    if weights is None:
        weights = jnp.ones((B, S), jnp.float32)
    micro_w = weights.reshape(M, mb, S).astype(jnp.float32)

    layers_spec = P(AXIS)
    aux_ct_unit = (
        cfg.router_aux_weight / (cfg.n_layers * M)
        if cfg.n_experts > 0
        else 0.0
    )

    # The CE normalizer is known up front (it's just the mask sum), so the
    # head loss is computed pre-normalized: gradients then need NO final
    # rescaling — crucial because the MoE router-aux objective shares the
    # same backward and must NOT be divided by the token count.
    denom = jnp.maximum(micro_w[:, :, 1:].sum(), 1.0)

    def head_loss(out, norm_w, head_w, toks, w):
        """Mean next-token CE contribution of one microbatch."""
        h = rms_norm(out, norm_w, cfg.norm_eps)
        logits = jnp.einsum(
            "bsd,dv->bsv", h, materialize(head_w, dt)
        ).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(
            logp, toks[:, 1:, None], axis=-1
        )[..., 0]
        return (nll * w[:, 1:]).sum() / denom

    def pipelined(layers_local, head_w, micro_x, micro_tok, micro_w):
        local = jax.tree.map(lambda a: a[0], layers_local)
        norm_w, lm_head = head_w
        # Replicated params must become stage-VARYING before any grad is
        # taken wrt them: differentiating an unvarying input used in a
        # varying computation transposes the implicit broadcast into a
        # psum over stages — which would silently sum the masked-out
        # garbage gradients from invalid ticks on other stages into the
        # valid one's BEFORE the validity mask can drop them.
        norm_w = jaxcompat.pcast(norm_w, (AXIS,), to="varying")
        lm_head = jaxcompat.pcast(lm_head, (AXIS,), to="varying")
        s = lax.axis_index(AXIS)
        is_last = s == n - 1
        is_first = s == 0
        perm_fwd = [(i, (i + 1) % n) for i in range(n)]
        perm_bwd = [(i, (i - 1) % n) for i in range(n)]

        def stage(p, x):
            return _stage_fn(p, x, positions, cfg, train)

        def loss_of(out, nw, hw, f_idx):
            return head_loss(out, nw, hw, micro_tok[f_idx], micro_w[f_idx])

        def tick(carry, t):
            act, grad_in, stash, g_layers, g_head, g_embed, nll_a, aux_a = carry

            # ---- forward: microbatch f = t - s
            f = t - s
            f_ok = jnp.logical_and(f >= 0, f < M)
            f_c = jnp.clip(f, 0, M - 1)
            inp = jnp.where(is_first, micro_x[f_c], act)
            out, aux_f = stage(local, inp)
            aux_a = aux_a + jnp.where(f_ok, aux_f, 0.0)
            # Stash this input for the backward-time recompute (only when
            # valid — never clobber a live slot with garbage).
            slot = f_c % K
            stash = stash.at[slot].set(
                jnp.where(f_ok, inp, stash[slot])
            )

            # ---- last stage: head + loss for f (== the bwd microbatch b)
            (nll, (g_out, g_norm, g_hw)) = jax.value_and_grad(
                lambda o, nw, hw: loss_of(o, nw, hw, f_c),
                argnums=(0, 1, 2),
            )(out.astype(dt), norm_w, lm_head)
            last_ok = jnp.logical_and(is_last, f_ok)
            nll_a = nll_a + jnp.where(last_ok, nll, 0.0)
            g_head = jax.tree.map(
                lambda a, g: a + jnp.where(last_ok, g, 0).astype(a.dtype),
                g_head, (g_norm, g_hw),
            )

            # ---- backward: microbatch b = t - (2n - 2 - s), recomputed
            b = t - (2 * n - 2 - s)
            b_ok = jnp.logical_and(b >= 0, b < M)
            b_c = jnp.clip(b, 0, M - 1)
            x_b = stash[b_c % K]
            _, vjp = jax.vjp(stage, local, x_b)
            g_up = jnp.where(is_last, g_out.astype(dt), grad_in)
            if aux_ct_unit == 0.0:
                # Dense model: the aux primal is a constant zero and hence
                # UNVARYING over the stage axis; its cotangent must match
                # that type (a stage-dependent where() would be varying).
                aux_ct = jnp.zeros((), jnp.float32)
            else:
                aux_ct = jnp.where(b_ok, aux_ct_unit, 0.0).astype(
                    jnp.float32
                )
            g_local, g_x = vjp((g_up, aux_ct))
            bscale = b_ok.astype(jnp.float32)
            g_layers = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32) * bscale,
                g_layers, g_local,
            )
            g_embed = g_embed + jnp.where(
                jnp.logical_and(is_first, b_ok),
                jnp.zeros_like(g_embed).at[micro_tok[b_c]].add(
                    g_x.astype(jnp.float32)
                ),
                0.0,
            )

            act_next = lax.ppermute(out, AXIS, perm_fwd)
            grad_next = lax.ppermute(g_x, AXIS, perm_bwd)
            return (act_next, grad_next, stash, g_layers, g_head, g_embed,
                    nll_a, aux_a), None

        zeros_act = jnp.zeros((mb, S, cfg.dim), dt)
        init = (
            jaxcompat.pcast(zeros_act, (AXIS,), to="varying"),
            jaxcompat.pcast(zeros_act, (AXIS,), to="varying"),
            jaxcompat.pcast(jnp.zeros((K, mb, S, cfg.dim), dt), (AXIS,), to="varying"),
            jaxcompat.pcast(
                jax.tree.map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), local
                ), (AXIS,), to="varying",
            ),
            jaxcompat.pcast(
                jax.tree.map(
                    lambda a: jnp.zeros(a.shape, jnp.float32),
                    (norm_w, lm_head),
                ), (AXIS,), to="varying",
            ),
            jaxcompat.pcast(
                jnp.zeros((cfg.vocab_size, cfg.dim), jnp.float32),
                (AXIS,), to="varying",
            ),
            jaxcompat.pcast(jnp.zeros((), jnp.float32), (AXIS,), to="varying"),
            jaxcompat.pcast(jnp.zeros((), jnp.float32), (AXIS,), to="varying"),
        )
        T = M + 2 * n - 2
        carry, _ = lax.scan(tick, init, jnp.arange(T))
        (_, _, _, g_layers, g_head, g_embed, nll_a, aux_a) = carry

        # Scalars and replicated-param grads live on one stage each —
        # psum selects + replicates them.
        nll = lax.psum(nll_a, AXIS)
        aux = lax.psum(aux_a, AXIS) / (cfg.n_layers * M)
        g_head = jax.tree.map(lambda g: lax.psum(g, AXIS), g_head)
        g_embed = lax.psum(g_embed, AXIS)
        g_layers = jax.tree.map(lambda g: g[None], g_layers)
        return nll, aux, g_layers, g_head, g_embed

    loss, aux, g_layers, g_head, g_embed = jaxcompat.shard_map(
        pipelined,
        in_specs=(layers_spec, P(), P(), P(), P()),
        out_specs=(P(), P(), layers_spec, P(), P()),
        axis_names={AXIS},
    )(
        params["layers"], (params["out_norm"], params["lm_head"]),
        micro_x, micro_tok, micro_w,
    )

    grads = {
        "tok_embed": g_embed,
        "layers": g_layers,
        "out_norm": g_head[0],
        "lm_head": g_head[1],
    }
    # The MoE router aux already contributed its gradient inside the ticks
    # (aux cotangent); the reported loss mirrors trainer semantics.
    return loss + (cfg.router_aux_weight * aux if cfg.n_experts else 0.0), grads, aux
