"""GPipe-style pipeline parallelism over the "stage" mesh axis.

Greenfield (SURVEY.md §2.3 — the reference has no parallelism at all). The
transformer's layer stack is split into `n_stages` contiguous groups, one
per device along the "stage" axis; activations flow stage-to-stage via
`jax.lax.ppermute` (XLA lowers to neighbor transfers — ICI within a slice,
DCN across slices, which is why "stage" sits next to "data" in MESH_AXES).

Schedule: classic GPipe. M microbatches enter stage 0 one step apart; step t
has stage s working on microbatch t-s; after M + S - 1 steps every
microbatch has exited the last stage. The bubble fraction is (S-1)/(M+S-1) —
callers pick M >= 4*S to amortize. Backward is jax.grad through the same
scan (ppermute is differentiable), i.e. GPipe's synchronous fill-drain, not
1F1B — a later round can swap the schedule without touching callers.

Embedding and the LM head are replicated and run outside the pipelined
region (they are a tiny fraction of FLOPs); only the block stack pipelines.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from substratus_tpu.models import llama
from substratus_tpu.models.llama import LlamaConfig, Params
from substratus_tpu.ops.basics import rms_norm
from substratus_tpu.ops.quant import materialize

AXIS = "stage"


def stage_params(params: Params, n_stages: int) -> Params:
    """Reshape stacked layers [L, ...] -> [n_stages, L/S, ...]; embed/norm/
    head stay replicated."""
    L = jax.tree.leaves(params["layers"])[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible into {n_stages} stages")
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda x: x.reshape((n_stages, L // n_stages) + x.shape[1:]),
        params["layers"],
    )
    return out


def _stage_fn(local_layers: Params, x: jnp.ndarray, positions, cfg, train):
    """Apply this stage's local layer stack (scan over layers). Returns
    (x_out, summed MoE aux for the stage — 0 for dense models)."""

    def body(carry, lp):
        x_out, _, aux = llama._block(carry, lp, positions, cfg, None, train=train)
        return x_out, aux

    x, auxes = lax.scan(body, x, local_layers)
    return x, auxes.sum()


def pipeline_forward(
    params: Params,  # stage_params() output, "layers" sharded on stage
    tokens: jnp.ndarray,  # [B, S]
    cfg: LlamaConfig,
    n_stages: int,
    n_microbatches: int,
    train: bool = False,
):
    """Pipelined (logits [B, S, vocab], moe_aux scalar). Call inside jit
    with an ambient mesh (jax.set_mesh) that has a "stage" axis of size
    n_stages. For MoE models the router load-balancing aux is accumulated
    across stages and valid microbatches (0.0 for dense models); `train`
    selects the capacity-dispatch expert path like llama.forward."""
    B, S = tokens.shape
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by {n_microbatches} microbatches")
    mb = B // n_microbatches
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (mb, S))

    x = materialize(params["tok_embed"], cfg.dtype)[tokens]
    micro = x.reshape(n_microbatches, mb, S, cfg.dim)

    layers_spec = P(AXIS)  # leading stage dim sharded; rest replicated

    def pipelined(layers_local, micro):
        # layers_local leaves: [1, L/S, ...] (this stage's group).
        local = jax.tree.map(lambda a: a[0], layers_local)
        stage = lax.axis_index(AXIS)
        n = n_stages
        M = n_microbatches
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, t):
            act = carry  # activation arriving from the previous stage
            inject = micro[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(stage == 0, inject, act)
            out, aux = _stage_fn(local, inp, positions, cfg, train)
            # This stage processes microbatch t - stage; aux from warmup/
            # drain steps (garbage inputs) must not count.
            mb_idx = t - stage
            valid = jnp.logical_and(mb_idx >= 0, mb_idx < M)
            aux = jnp.where(valid, aux, 0.0)
            # The last stage's output at step t is microbatch t-(n-1).
            collect = jnp.where(stage == n - 1, out, jnp.zeros_like(out))
            act_next = lax.ppermute(out, AXIS, perm)
            return act_next, (collect, aux)

        init = jnp.zeros((mb, S, cfg.dim), cfg.dtype)
        # Mark the carry as stage-varying: the scan's output (post-ppermute)
        # is device-varying, and scan requires carry types to match.
        init = lax.pcast(init, (AXIS,), to="varying")
        _, (collected, auxes) = lax.scan(step, init, jnp.arange(M + n - 1))
        # Valid outputs live at steps n-1 .. n-1+M-1; broadcast them off the
        # last stage to every stage (zeros elsewhere -> psum is a select).
        outs = collected[n - 1:]
        outs = lax.psum(outs, AXIS)
        # Mean aux per (layer, microbatch): sum over stages/steps, then
        # normalize like llama.forward's kv["moe_aux"].mean().
        aux_total = lax.psum(auxes.sum(), AXIS) / (cfg.n_layers * M)
        return outs, aux_total  # [M, mb, S, D], scalar

    outs, aux = jax.shard_map(
        pipelined,
        in_specs=(layers_spec, P()),
        out_specs=(P(), P()),
        axis_names={AXIS},
    )(params["layers"], micro)

    x = outs.reshape(B, S, cfg.dim)
    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum(
            "bsd,vd->bsv", x, materialize(params["tok_embed"], cfg.dtype)
        )
    else:
        logits = jnp.einsum(
            "bsd,dv->bsv", x, materialize(params["lm_head"], cfg.dtype)
        )
    return logits.astype(jnp.float32), aux
