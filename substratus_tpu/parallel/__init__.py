from substratus_tpu.parallel.mesh import MESH_AXES, build_mesh, local_mesh
from substratus_tpu.parallel.sharding import (
    LogicalRules,
    DEFAULT_RULES,
    logical_sharding,
    shard_tree,
    spec_for,
)

__all__ = [
    "MESH_AXES",
    "build_mesh",
    "local_mesh",
    "LogicalRules",
    "DEFAULT_RULES",
    "logical_sharding",
    "shard_tree",
    "spec_for",
]
