"""Multi-host distributed bootstrap (greenfield; SURVEY.md §2.3/§5).

The comms backend is XLA collectives over ICI within a slice and DCN across
slices; what this module adds is the *rendezvous*: turning the env the
operator injects into JobSet pods (controller/workloads.py — TPU_WORKER_ID,
TPU_WORKER_HOSTNAMES, JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES) into a
`jax.distributed.initialize` call, the way the reference ecosystem relied on
NCCL/MPI env bootstraps (MASTER_ADDR/WORLD_SIZE) that the reference operator
itself never provided.

Call `maybe_initialize()` first thing in any entrypoint; it is a no-op for
single-host runs so the same containers work everywhere.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("substratus.distributed")

_initialized = False


def world_info() -> tuple[Optional[str], int, int]:
    """(coordinator_address, num_processes, process_id) from operator env."""
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    n = int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1)
    pid_raw = os.environ.get("TPU_WORKER_ID", "0") or "0"
    try:
        pid = int(pid_raw)
    except ValueError:
        pid = 0
    return coord, n, pid


def maybe_initialize(timeout_seconds: int = 300) -> bool:
    """Initialize jax.distributed when the operator wired a multi-host slice;
    no-op (returns False) on single-host. Idempotent."""
    global _initialized
    if _initialized:
        return True
    coord, n, pid = world_info()
    if n <= 1 or coord is None:
        return False
    import jax

    log.info(
        "jax.distributed.initialize(coordinator=%s, processes=%d, id=%d)",
        coord, n, pid,
    )
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=n,
        process_id=pid,
        initialization_timeout=timeout_seconds,
    )
    _initialized = True
    return True
