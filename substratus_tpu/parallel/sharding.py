"""Logical-axis sharding rules (greenfield; see SURVEY.md §2.3).

Arrays in models/ are annotated with *logical* axis names; a rules table maps
each logical name to zero or more *mesh* axes. This is the standard TPU recipe
(pick a mesh, annotate shardings, let XLA insert collectives) decoupled from
any one model: changing the parallelism strategy means changing the rules
table, not the model code.

Logical axis vocabulary:
  activations: "batch", "seq", "act_embed", "act_heads", "act_kv", "act_mlp"
  params:      "vocab", "embed", "heads", "kv_heads", "head_dim", "mlp",
               "layers" (scan axis, never sharded), "expert", "lora_rank"
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Axes = Tuple[Optional[Union[str, Tuple[str, ...]]], ...]


@dataclass(frozen=True)
class LogicalRules:
    """Mapping from logical axis name -> mesh axis (or tuple of mesh axes)."""

    rules: Tuple[Tuple[str, Union[None, str, Tuple[str, ...]]], ...]

    def mesh_axes(self, logical: Sequence[Optional[str]]) -> P:
        table = dict(self.rules)
        out, used = [], set()
        for name in logical:
            if name is None:
                out.append(None)
                continue
            mapped = table.get(name)
            # A mesh axis may appear only once in a PartitionSpec; later
            # logical axes that map to an already-used mesh axis stay
            # replicated (matches flax.linen logical partitioning semantics).
            if mapped is None:
                out.append(None)
                continue
            axes = (mapped,) if isinstance(mapped, str) else tuple(mapped)
            free = tuple(a for a in axes if a not in used)
            used.update(free)
            if not free:
                out.append(None)
            elif len(free) == 1:
                out.append(free[0])
            else:
                out.append(free)
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def replace(self, **kv) -> "LogicalRules":
        table = dict(self.rules)
        table.update(kv)
        return LogicalRules(tuple(table.items()))


# Training defaults: FSDP shards the param embed dim, tensor shards heads/mlp,
# batch is data-parallel over both data and fsdp axes, sequence parallelism
# shards activation seq.
DEFAULT_RULES = LogicalRules(
    (
        ("batch", ("data", "fsdp")),
        ("seq", "sequence"),
        ("act_embed", None),
        ("act_heads", "tensor"),
        ("act_kv", "tensor"),
        ("act_mlp", "tensor"),
        ("vocab", "tensor"),
        ("embed", "fsdp"),
        ("heads", "tensor"),
        ("kv_heads", "tensor"),
        ("head_dim", None),
        ("mlp", "tensor"),
        ("layers", None),
        ("expert", "expert"),
        ("lora_rank", None),
        ("cache_batch", ("data", "fsdp")),
        ("cache_seq", None),
    )
)

# Serving: no fsdp (weights fit, or are tensor-sharded); batch over data.
SERVE_RULES = DEFAULT_RULES.replace(
    batch="data", embed=None, cache_batch="data"
)


def serve_rules_for(mesh: Optional[Mesh]) -> LogicalRules:
    """SERVE_RULES, with the KV cache's sequence dim sharded over the
    mesh's "sequence" axis when the serving mesh has one (>1): serving-
    side context parallelism. A long-context dense cache then spreads
    over sequence shards — per-chip cache memory drops N×, and XLA's
    partitioner turns the attention softmax over the sharded dim into
    the max/sum collectives (the decode analogue of training's ring
    attention; SURVEY.md §5 long-context)."""
    if (
        mesh is not None
        and "sequence" in mesh.shape
        and mesh.shape["sequence"] > 1
    ):
        return SERVE_RULES.replace(cache_seq="sequence")
    return SERVE_RULES


def spec_for(logical: Sequence[Optional[str]], rules: LogicalRules = DEFAULT_RULES) -> P:
    return rules.mesh_axes(logical)


def logical_sharding(
    mesh: Mesh, logical_tree: Any, rules: LogicalRules = DEFAULT_RULES
) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda ax: NamedSharding(mesh, rules.mesh_axes(ax)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(a is None or isinstance(a, str) for a in x),
    )


def sharding_tree(
    tree: Any,
    mesh: Mesh,
    logical_tree: Any,
    rules: LogicalRules = DEFAULT_RULES,
) -> Any:
    """Same-structure tree of NamedShardings for `tree`.

    Handles int8 QTensor leaves (ops/quant.py): the quantized values take the
    weight's sharding; the per-channel scale takes the same spec with size-1
    (contracting, keepdims) dims left unsharded. The returned tree carries a
    QTensor *of shardings* at those positions so it flattens in lockstep with
    the value tree (usable with device_put, jit shardings, or
    ShapeDtypeStruct pairing).
    """
    from substratus_tpu.ops.quant import QTensor
    from substratus_tpu.ops.quant4 import Q4Tensor

    def fit(shape, spec: P) -> P:
        """Drop spec entries whose mesh-axis size doesn't divide the dim —
        e.g. multi-query attention (1 kv head) with a tensor axis: the kv
        projections replicate instead of erroring."""
        out = []
        for i, entry in enumerate(tuple(spec)):
            if entry is None:
                out.append(None)
                continue
            axes = (entry,) if isinstance(entry, str) else tuple(entry)
            size = 1
            for a in axes:
                size *= mesh.shape[a]
            out.append(entry if shape[i] % size == 0 else None)
        return P(*out)

    def one(leaf, axes):
        spec = fit(leaf.shape, rules.mesh_axes(axes))
        if isinstance(leaf, Q4Tensor):
            # packed halves the pack dim and scale divides it by `block`:
            # re-fit the weight's spec against each child's real shape so a
            # mesh axis that no longer divides the dim replicates instead
            # of erroring (mirrors the QTensor keepdims handling).
            base = tuple(spec) + (None,) * (leaf.packed.ndim - len(tuple(spec)))
            return Q4Tensor(
                packed=NamedSharding(mesh, fit(leaf.packed.shape, P(*base))),
                scale=NamedSharding(mesh, fit(leaf.scale.shape, P(*base))),
                pack_axis=leaf.pack_axis,
                block=leaf.block,
            )
        if isinstance(leaf, QTensor):
            qspec = tuple(spec) + (None,) * (leaf.q.ndim - len(tuple(spec)))
            sspec = P(
                *[
                    a if leaf.scale.shape[i] != 1 else None
                    for i, a in enumerate(qspec)
                ]
            )
            return QTensor(
                q=NamedSharding(mesh, P(*qspec)),
                scale=NamedSharding(mesh, sspec),
            )
        return NamedSharding(mesh, spec)

    return jax.tree.map(
        one,
        tree,
        logical_tree,
        is_leaf=lambda x: isinstance(x, (QTensor, Q4Tensor)),
    )


def shard_tree(
    tree: Any,
    mesh: Mesh,
    logical_tree: Any,
    rules: LogicalRules = DEFAULT_RULES,
) -> Any:
    """Device-put a pytree according to its logical annotations (QTensor
    aware, see sharding_tree)."""
    shardings = sharding_tree(tree, mesh, logical_tree, rules)
    return jax.tree.map(jax.device_put, tree, shardings)
