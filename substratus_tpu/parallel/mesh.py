"""Device mesh construction over ICI and DCN.

The reference framework has no multi-device compute at all (SURVEY.md §2.3:
its only knob is `resources.gpu.count` on one pod, api/v1/common_types.go:102).
Here the mesh is the foundation every parallel form hangs off:

  axis        parallelism
  ----        -----------
  "data"      pure data parallelism (replicated params)
  "fsdp"      ZeRO-3 style data parallelism (params sharded over this axis)
  "sequence"  context/sequence parallelism (ring attention shards seq here)
  "tensor"    megatron-style tensor parallelism (heads / mlp sharded)
  "expert"    expert parallelism for MoE layers

Multi-slice TPU pods: ICI connects chips within a slice, DCN connects slices.
`build_mesh` accepts `dcn_data` (number of slices) and places it as the
outermost axis so that only the data axis crosses DCN — all other collectives
ride ICI, per the scaling-book recipe.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Order matters: outer-to-inner. data/stage outermost so multi-slice DCN
# traffic is confined to data-parallel gradient all-reduce and pipeline
# stage-boundary transfers (both DCN-friendly: large, infrequent).
#
# This tuple is the CANONICAL mesh-axis registry: every mesh this repo
# builds carries exactly these names, every PartitionSpec literal must
# draw from them, and the shard lint (substratus_tpu/analysis/shardlint
# .py, `make lint`) validates the whole package against it by parsing
# this assignment out of the AST — keep it a literal.
MESH_AXES = ("data", "stage", "fsdp", "sequence", "tensor", "expert")

# Membership form of the registry, for runtime validation.
KNOWN_AXES = frozenset(MESH_AXES)


def axis_names(axis) -> tuple:
    """Flatten one PartitionSpec entry — a mesh-axis name, a tuple of
    names, or None — to a tuple of axis names.

    The single shared helper behind every axis-overlap check:
    ops/quant4.py and ops/kernel_partition.py used to carry private
    copies of both this flattening and their axis bookkeeping, and the
    PR 3 tuple-spec overlap bugs came exactly from that drift. One
    definition, one semantics."""
    if axis is None:
        return ()
    if isinstance(axis, (tuple, list)):
        return tuple(axis)
    return (axis,)


def build_mesh(
    data: int = 1,
    fsdp: int = 1,
    sequence: int = 1,
    tensor: int = 1,
    expert: int = 1,
    stage: int = 1,
    *,
    dcn_data: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named Mesh over the given (or all) devices.

    Any axis may be -1 exactly once, meaning "all remaining devices".
    With dcn_data > 1 the devices are assumed grouped by slice (jax.devices()
    returns them in process/slice order) and `data` must be divisible by it;
    jax.experimental.mesh_utils handles hybrid ICI/DCN placement when
    available.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = [data, stage, fsdp, sequence, tensor, expert]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(f"mesh sizes {sizes} != device count {n}")

    if dcn_data > 1:
        from jax.experimental import mesh_utils

        if sizes[0] % dcn_data:
            raise ValueError(
                f"data axis {sizes[0]} not divisible by dcn slices {dcn_data}"
            )
        ici = [sizes[0] // dcn_data] + sizes[1:]
        dcn = [dcn_data] + [1] * (len(sizes) - 1)
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici, dcn, devices=devices
            )
        except ValueError:
            # Virtual/CPU devices carry no slice_index attribute. They are
            # slice-ordered by construction (jax.devices() returns process/
            # slice order), so a plain slice-major reshape yields the same
            # placement: the outermost data axis is the only one crossing
            # slice boundaries.
            dev_array = np.asarray(devices).reshape(sizes)
    else:
        dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


def local_mesh() -> Mesh:
    """Single-chip (or fully data-parallel) trivial mesh; used for bench and
    single-host serving."""
    n = len(jax.devices())
    return build_mesh(data=n)
