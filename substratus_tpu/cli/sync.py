"""Notebook file-sync + port-forward (reference: internal/client/sync.go:
28-135, 184-273 and internal/tui/portforward.go:18-63).

Flow parity: ship the nbwatch binary into the pod, exec it, stream its JSON
event lines, and mirror each changed file back locally (download on
WRITE/CREATE, delete on REMOVE). Transport: the in-library WebSocket
exec/port-forward in kube/real.py + kube/ws.py — no kubectl subprocesses
(the reference links client-go for the same reason; a machine without
kubectl on PATH works).
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import time
from typing import Callable, Optional

NBWATCH_LOCAL = os.path.join(os.path.dirname(__file__), "..", "..", "native")


def ensure_nbwatch_binary() -> str:
    """Locate (or build from native/nbwatch.cc) the nbwatch binary."""
    candidates = [
        shutil.which("nbwatch"),
        os.path.join(NBWATCH_LOCAL, "nbwatch"),
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return c
    src = os.path.join(NBWATCH_LOCAL, "nbwatch.cc")
    out = os.path.join(NBWATCH_LOCAL, "nbwatch")
    subprocess.run(["g++", "-O2", "-o", out, src], check=True)
    return out


def sync_files_from_notebook(
    client,
    namespace: str,
    pod: str,
    local_dir: str,
    container_dir: str = "/content",
    on_event: Optional[Callable[[dict], None]] = None,
    stop: Optional[threading.Event] = None,
) -> None:
    """Stream nbwatch events from the pod and mirror files locally."""
    # The runtime image ships nbwatch at /usr/local/bin (Dockerfile); use it
    # — copying a host-built binary breaks on arch mismatch (e.g. arm64
    # laptop -> amd64 pod). Copy only as a fallback for foreign images.
    in_pod = "/usr/local/bin/nbwatch"
    rc, _, _ = client.pod_exec(namespace, pod, ["test", "-x", in_pod])
    if rc != 0:
        binary = ensure_nbwatch_binary()
        in_pod = "/tmp/nbwatch"
        if not client.cp_to_pod(namespace, pod, binary, in_pod):
            raise RuntimeError(f"failed to copy nbwatch into {pod}")
        rc, _, err = client.pod_exec(namespace, pod, ["chmod", "+x", in_pod])
        if rc != 0:
            raise RuntimeError(
                f"chmod +x {in_pod} failed in {pod}: "
                f"{err.decode(errors='replace').strip()}"
            )

    stream = client.pod_exec_stream(namespace, pod, [in_pod, container_dir])
    try:
        buf = b""
        for channel, data in stream.chunks():
            if stop is not None and stop.is_set():
                break
            if channel != 1:  # stdout only
                continue
            buf += data
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                try:
                    event = json.loads(line)
                except json.JSONDecodeError:
                    continue
                _apply_event(client, namespace, pod, event, container_dir,
                             local_dir)
                if on_event:
                    on_event(event)
    finally:
        stream.close()


def _apply_event(client, namespace, pod, event, container_dir,
                 local_dir) -> None:
    rel = os.path.relpath(event["path"], container_dir)
    local_path = os.path.join(local_dir, rel)
    if event["op"] == "REMOVE":
        if os.path.exists(local_path):
            os.unlink(local_path)
    else:
        client.cp_from_pod(namespace, pod, event["path"], local_path)


def port_forward(
    client,
    namespace: str,
    pod: str,
    local_port: int,
    remote_port: int,
    stop: Optional[threading.Event] = None,
    max_retries: int = 10,
) -> None:
    """In-library port-forward with exponential-backoff restart (reference
    tui/portforward.go:20-61)."""
    delay = 1.0
    retries = 0
    while not (stop is not None and stop.is_set()):
        started = time.monotonic()
        try:
            client.port_forward(
                namespace, pod, local_port, remote_port, stop=stop
            )
            return  # clean stop
        except Exception as e:  # sublint: allow[broad-except]: any forward error is retried with backoff; surfaced via emit below
            if stop is not None and stop.is_set():
                return
            if time.monotonic() - started > 10.0:
                # The forward was healthy for a while; an idle disconnect is
                # not a failure — reset the budget so long sessions never
                # die.
                retries, delay = 0, 1.0
            retries += 1
            if retries > max_retries:
                raise RuntimeError(
                    f"port-forward failed {max_retries} times (last: {e})"
                )
            time.sleep(delay)
            delay = min(delay * 2, 30.0)


def _probe_forward(port: int, timeout: float = 2.0) -> bool:
    """True once the forward round-trips to the pod. A bare TCP connect is
    not enough: the in-library forwarder's local listener accepts the
    instant it binds, before any pod-side stream exists — readiness means
    bytes actually come back from the far end."""
    import socket

    try:
        with socket.create_connection(("localhost", port), timeout) as conn:
            conn.sendall(b"GET /api HTTP/1.0\r\n\r\n")
            conn.settimeout(timeout)
            return bool(conn.recv(1))
    except OSError:
        return False


def notebook_dev_loop(
    client,
    namespace: str,
    pod: str,
    *,
    local_dir: Optional[str] = None,
    port: int = 8888,
    open_browser: bool = True,
    emit: Callable[[str], None] = print,
    stop: Optional[threading.Event] = None,
) -> None:
    """The composed notebook dev loop both `sub notebook` frontends share
    (plain CLI and TUI): background file-sync + port-forward, wait for the
    local port to answer, open the browser, then hold until interrupted —
    setting `stop` on every exit path so both workers wind down."""
    import socket
    import webbrowser

    stop = stop or threading.Event()
    threading.Thread(
        target=sync_files_from_notebook,
        args=(client, namespace, pod, local_dir or os.getcwd()),
        kwargs={
            "stop": stop,
            "on_event": lambda e: emit(f"sync: {e['op']} {e['path']}"),
        },
        daemon=True,
    ).start()
    fwd = threading.Thread(
        target=port_forward, args=(client, namespace, pod, port, port),
        kwargs={"stop": stop}, daemon=True,
    )
    fwd.start()

    url = f"http://localhost:{port}?token=default"
    for _ in range(60):
        if stop.is_set():
            return
        if _probe_forward(port):
            break
        time.sleep(0.5)
    emit(f"forwarding :{port} — {url} (ctrl-c to stop)")
    if open_browser:
        webbrowser.open(url)
    try:
        while fwd.is_alive() and not stop.is_set():
            fwd.join(timeout=1.0)
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
