"""Notebook file-sync + port-forward (reference: internal/client/sync.go:
28-135, 184-273 and internal/tui/portforward.go:18-63).

Flow parity: ship the nbwatch binary into the pod, exec it, stream its JSON
event lines, and mirror each changed file back locally (download on
WRITE/CREATE, delete on REMOVE). Transport: kubectl subprocesses — the
reference linked client-go for SPDY exec/cp; shelling out to kubectl keeps
the same behavior without reimplementing the SPDY/WebSocket stack (a later
round can inline it into kube/real.py).
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import time
from typing import Callable, Optional

NBWATCH_LOCAL = os.path.join(os.path.dirname(__file__), "..", "..", "native")


def _kubectl() -> str:
    path = shutil.which("kubectl")
    if path is None:
        raise RuntimeError("kubectl not found on PATH (needed for notebook sync)")
    return path


def ensure_nbwatch_binary() -> str:
    """Locate (or build from native/nbwatch.cc) the nbwatch binary."""
    candidates = [
        shutil.which("nbwatch"),
        os.path.join(NBWATCH_LOCAL, "nbwatch"),
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return c
    src = os.path.join(NBWATCH_LOCAL, "nbwatch.cc")
    out = os.path.join(NBWATCH_LOCAL, "nbwatch")
    subprocess.run(["g++", "-O2", "-o", out, src], check=True)
    return out


def sync_files_from_notebook(
    namespace: str,
    pod: str,
    local_dir: str,
    container_dir: str = "/content",
    on_event: Optional[Callable[[dict], None]] = None,
    stop: Optional[threading.Event] = None,
) -> None:
    """Stream nbwatch events from the pod and mirror files locally."""
    kubectl = _kubectl()
    # The runtime image ships nbwatch at /usr/local/bin (Dockerfile); use it
    # — copying a host-built binary breaks on arch mismatch (e.g. arm64
    # laptop -> amd64 pod). Copy only as a fallback for foreign images.
    in_pod = "/usr/local/bin/nbwatch"
    probe = subprocess.run(
        [kubectl, "-n", namespace, "exec", pod, "--", "test", "-x", in_pod],
        capture_output=True,
    )
    if probe.returncode != 0:
        binary = ensure_nbwatch_binary()
        in_pod = "/tmp/nbwatch"
        subprocess.run(
            [kubectl, "-n", namespace, "cp", binary, f"{pod}:{in_pod}"],
            check=True,
        )
        subprocess.run(
            [kubectl, "-n", namespace, "exec", pod, "--", "chmod", "+x",
             in_pod],
            check=True,
        )
    proc = subprocess.Popen(
        [kubectl, "-n", namespace, "exec", pod, "--", in_pod, container_dir],
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        for line in proc.stdout:
            if stop is not None and stop.is_set():
                break
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            rel = os.path.relpath(event["path"], container_dir)
            local_path = os.path.join(local_dir, rel)
            if event["op"] == "REMOVE":
                if os.path.exists(local_path):
                    os.unlink(local_path)
            else:
                os.makedirs(os.path.dirname(local_path), exist_ok=True)
                subprocess.run(
                    [kubectl, "-n", namespace, "cp",
                     f"{pod}:{event['path']}", local_path],
                    check=False,
                )
            if on_event:
                on_event(event)
    finally:
        proc.terminate()


def port_forward(
    namespace: str,
    pod: str,
    local_port: int,
    remote_port: int,
    stop: Optional[threading.Event] = None,
    max_retries: int = 10,
) -> None:
    """kubectl port-forward with exponential-backoff restart (reference
    tui/portforward.go:20-61)."""
    kubectl = _kubectl()
    delay = 1.0
    retries = 0
    while not (stop is not None and stop.is_set()):
        started = time.monotonic()
        proc = subprocess.Popen(
            [kubectl, "-n", namespace, "port-forward", f"pod/{pod}",
             f"{local_port}:{remote_port}"],
        )
        code = proc.wait()
        if stop is not None and stop.is_set():
            return
        if time.monotonic() - started > 10.0:
            # The forward was healthy for a while; an idle disconnect is not
            # a failure — reset the budget so long sessions never die.
            retries, delay = 0, 1.0
        retries += 1
        if retries > max_retries:
            raise RuntimeError(
                f"port-forward failed {max_retries} times (last exit {code})"
            )
        time.sleep(delay)
        delay = min(delay * 2, 30.0)
