"""CLI command registry. Grows as subsystems land."""
from __future__ import annotations

import argparse
from typing import List


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sub",
        description="substratus-tpu: TPU-native ML on Kubernetes",
    )
    p.add_argument("--version", action="store_true", help="print version")
    p.set_defaults(func=None)
    sub = p.add_subparsers(dest="command")

    from substratus_tpu.cli import commands

    commands.register(sub)
    return p


def run(argv: List[str]) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if getattr(args, "version", False) and args.command is None:
        from substratus_tpu import __version__

        print(f"sub {__version__}")
        return 0
    if args.func is None:
        parser.print_help()
        return 1
    return args.func(args)
