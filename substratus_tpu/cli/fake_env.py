"""In-process fake cluster for `sub --fake`: fake apiserver + manager +
fake data plane.

The reference needs a kind cluster even for local smoke (install/kind/up.sh);
`--fake` gives the same control-plane behavior with zero infrastructure.
The data-plane simulation completes Jobs/Deployments a moment after they
appear — enough to exercise CR flows end to end from the CLI.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from substratus_tpu.cloud.base import LocalCloud
from substratus_tpu.cloud.common import CommonConfig
from substratus_tpu.controller.manager_main import build_manager
from substratus_tpu.kube.fake import FakeKube
from substratus_tpu.sci.client import FakeSCIClient

STATE_FILE = os.environ.get(
    "SUBSTRATUS_FAKE_STATE", "/tmp/substratus-fake-cluster.json"
)


class FakeEnv:
    """State persists to STATE_FILE so sequential `sub --fake` invocations
    (apply, then get, then delete) see one continuous cluster."""

    def __init__(self):
        self.client = FakeKube()
        self._load()
        self.client.add_listener(lambda *_: self._save())
        self.cloud = LocalCloud(
            CommonConfig(
                cluster_name="fake",
                artifact_bucket_url="local:///tmp/substratus-bucket",
                registry_url="registry.fake:5000",
            )
        )
        self.sci = FakeSCIClient()
        self.manager = build_manager(self.client, self.cloud, self.sci)
        self.manager.bootstrap()

    def _load(self) -> None:
        if not os.path.exists(STATE_FILE):
            return
        try:
            with open(STATE_FILE) as f:
                state = json.load(f)
        except (json.JSONDecodeError, OSError):
            return
        for obj in state.get("objects", []):
            key = self.client._key(
                obj["kind"],
                obj["metadata"].get("namespace", "default"),
                obj["metadata"]["name"],
            )
            self.client._store[key] = obj
        self.client._rv = state.get("rv", len(state.get("objects", [])))
        self.client._uid = state.get("uid", self.client._rv)

    def _save(self) -> None:
        state = {
            "objects": list(self.client._store.values()),
            "rv": self.client._rv,
            "uid": self.client._uid,
        }
        tmp = STATE_FILE + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, STATE_FILE)

    def step(self) -> None:
        """One control-plane + data-plane tick."""
        self.manager.run_until_idle()
        # Fake kubelet: everything created eventually runs/succeeds.
        for job in self.client.list("Job"):
            if not job.get("status"):
                self.client.mark_job_complete(
                    job["metadata"]["namespace"], job["metadata"]["name"]
                )
        for js in self.client.list("JobSet"):
            if not js.get("status"):
                self.client.mark_jobset_complete(
                    js["metadata"]["namespace"], js["metadata"]["name"]
                )
        for dep in self.client.list("Deployment"):
            if not dep.get("status"):
                self.client.mark_deployment_ready(
                    dep["metadata"]["namespace"], dep["metadata"]["name"]
                )
        for pod in self.client.list("Pod"):
            if not pod.get("status"):
                self.client.mark_pod_ready(
                    pod["metadata"]["namespace"], pod["metadata"]["name"]
                )
        self.manager.run_until_idle()

    def accept_upload(self, data: bytes, md5: str) -> None:
        """Simulate the storage side of the signed-URL PUT: register the
        stored md5 for every pending upload object that expects it."""
        for kind in ("Dataset", "Model", "Notebook", "Server"):
            for obj in self.client.list(kind):
                up = (obj.get("spec", {}).get("build") or {}).get("upload")
                if up and up.get("md5Checksum") == md5:
                    md = obj["metadata"]
                    path = (
                        f"uploads/{md['namespace']}/{kind.lower()}s/"
                        f"{md['name']}/{md5}.tar.gz"
                    )
                    self.sci.md5s[path] = hashlib.md5(data).hexdigest()
