"""Composable interactive terminal UI for `sub` (reference: the bubbletea
TUI in internal/tui — NotebookModel/RunModel composed from manifestsModel,
uploadModel, readinessModel, podsModel; internal/tui/notebook.go:65-91).

Dependency-free ANSI implementation of the same architecture:

  * a Model has update(msg) -> messages-consumed state machine and a
    view() -> str render; the runtime owns the terminal (cbreak mode,
    alternate-screen-free incremental redraw) and the message queue;
  * messages: KeyMsg (keyboard), TickMsg (timer), or any object a
    background command posts; commands run in daemon threads via
    ctx.spawn(fn) and their return values (or raised exceptions) are
    posted back as messages — update() never blocks;
  * Sequence composes stage models: each stage's `result` feeds the next
    stage's factory, mirroring the reference's flow composition.

When stdout is not a TTY every flow falls back to the plain line-printing
path (the pre-TUI behavior), so scripts and CI logs stay sane.
"""
from __future__ import annotations

import queue
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

SPINNER = "⠋⠙⠹⠸⠼⠴⠦⠧⠇⠏"


@dataclass
class KeyMsg:
    key: str  # "up", "down", "enter", "q", single chars, ...


@dataclass
class TickMsg:
    t: float


@dataclass
class ErrMsg:
    error: BaseException


@dataclass
class DoneMsg:
    result: Any = None


class Quit(Exception):
    """Raised (or posted) to stop the runtime; .result carries the value."""

    def __init__(self, result: Any = None):
        self.result = result


class Context:
    """Runtime handle given to models: post messages, spawn commands."""

    def __init__(self):
        self.queue: "queue.Queue[Any]" = queue.Queue()

    def post(self, msg: Any) -> None:
        self.queue.put(msg)

    def spawn(self, fn: Callable[[], Any]) -> None:
        """Run fn on a daemon thread; post its return value (or ErrMsg)."""

        def run():
            try:
                out = fn()
                if out is not None:
                    self.post(out)
            except BaseException as e:  # sublint: allow[broad-except]: surfaced to update() as ErrMsg, not lost
                self.post(ErrMsg(e))

        threading.Thread(target=run, daemon=True).start()


class Model:
    """Base stage: subclasses set self.done=True (+ self.result) or raise
    Quit to abort the whole program."""

    done = False
    result: Any = None
    failed: Optional[str] = None

    def start(self, ctx: Context) -> None:  # begin async work
        pass

    def update(self, ctx: Context, msg: Any) -> None:
        pass

    def view(self) -> str:
        return ""

    def cancel(self) -> None:  # user quit while this stage was running
        pass


_KEYMAP = {
    "\x1b[A": "up", "\x1b[B": "down", "\x1b[C": "right", "\x1b[D": "left",
    "\r": "enter", "\n": "enter", "\x7f": "backspace", "\x1b": "esc",
    "\x03": "ctrl-c",
}


def _read_keys(stdin, ctx: Context, stop: threading.Event) -> None:
    """Raw key reader thread. Bytes are fed through an incremental UTF-8
    decoder (a split multi-byte keypress must never read as EOF), and a
    lone Esc is disambiguated from an escape sequence by a short timeout —
    only an empty os.read (true EOF) ends the thread."""
    import codecs
    import os
    import select as _select

    fd = stdin.fileno()
    dec = codecs.getincrementaldecoder("utf-8")("ignore")
    pending = ""  # chars accumulating a possible escape sequence

    def flush_pending():
        nonlocal pending
        if pending == "\x1b":
            ctx.post(KeyMsg("esc"))
        elif pending:  # truncated sequence: best-effort last char
            ctx.post(KeyMsg(pending[-1]))
        pending = ""

    while not stop.is_set():
        try:
            ready, _, _ = _select.select([fd], [], [], 0.05)
        except OSError:
            return
        if not ready:
            if pending:
                flush_pending()
            continue
        try:
            data = os.read(fd, 64)
        except OSError:
            return
        if not data:
            return
        for ch in dec.decode(data):
            pending += ch
            if pending == "\x1b":
                continue  # maybe an escape sequence; wait for more
            if pending.startswith("\x1b") and len(pending) < 3:
                continue
            key = _KEYMAP.get(
                pending, pending if len(pending) == 1 else pending[-1]
            )
            pending = ""
            ctx.post(KeyMsg(key))


class Runtime:
    """Owns the terminal; runs one (possibly composed) model to completion.

    Rendering is incremental: move home + erase-to-end per frame, no
    alternate screen — the final frame stays in the scrollback, which is
    what operators want from one-shot flows like `sub run`.
    """

    def __init__(self, stdin=None, stdout=None, fps: float = 15.0):
        self.stdin = stdin or sys.stdin
        self.stdout = stdout or sys.stdout
        self.fps = fps

    def run(self, model: Model) -> Any:
        import termios
        import tty

        ctx = Context()
        stop = threading.Event()
        fd = self.stdin.fileno()
        old = termios.tcgetattr(fd)
        tty.setcbreak(fd)
        reader = threading.Thread(
            target=_read_keys, args=(self.stdin, ctx, stop), daemon=True
        )
        reader.start()

        def ticker():
            while not stop.is_set():
                ctx.post(TickMsg(time.time()))
                time.sleep(1.0 / self.fps)

        threading.Thread(target=ticker, daemon=True).start()

        last_lines = 0
        self.stdout.write("\x1b[?25l")  # hide cursor
        try:
            model.start(ctx)
            while True:
                frame = model.view()
                last_lines = self._paint(frame, last_lines)
                msg = ctx.queue.get()
                if isinstance(msg, KeyMsg) and msg.key == "ctrl-c":
                    raise Quit(None)
                model.update(ctx, msg)
                if model.failed is not None:
                    raise Quit(SystemExit(model.failed))
                if model.done:
                    self._paint(model.view(), last_lines, final=True)
                    return model.result
        except KeyboardInterrupt:
            # cbreak keeps ISIG, so Ctrl-C raises wherever the main thread
            # happens to be (queue.get, update, view, paint) — always a
            # clean quit, never a traceback.
            model.cancel()
            self._paint(model.view(), last_lines, final=True)
            return None
        except Quit as q:
            model.cancel()
            self._paint(model.view(), last_lines, final=True)
            if isinstance(q.result, BaseException):
                raise q.result
            return q.result
        finally:
            stop.set()
            self.stdout.write("\x1b[?25h")  # show cursor
            self.stdout.flush()
            termios.tcsetattr(fd, termios.TCSADRAIN, old)

    def _paint(self, frame: str, last_lines: int, final: bool = False) -> int:
        """Repaint in place; returns the painted line count.

        Each logical line is truncated to the terminal width: a wrapped
        line would consume extra rows the cursor-up math can't see, and
        stale half-frames would stack above. The final paint keeps full
        lines (it scrolls naturally into scrollback).
        """
        import shutil

        if not final:
            width = shutil.get_terminal_size().columns
            frame = "\n".join(
                line[: max(width - 1, 1)] for line in frame.split("\n")
            )
        # Move up over the previous frame, erase below, draw.
        out = ""
        if last_lines:
            out += f"\x1b[{last_lines - 1}F" if last_lines > 1 else "\r"
        out += "\x1b[J" + frame
        if final:
            out += "\n"
        self.stdout.write(out)
        self.stdout.flush()
        return frame.count("\n") + 1


class Sequence(Model):
    """Run stages one after another; each factory receives the previous
    stage's result (the reference composes NotebookModel the same way)."""

    def __init__(self, factories: List[Callable[[Any], Optional[Model]]]):
        self.factories = list(factories)
        self.current: Optional[Model] = None
        self.history: List[str] = []
        self._ctx: Optional[Context] = None
        self._last_result: Any = None

    def cancel(self) -> None:
        if self.current is not None:
            self.current.cancel()

    def start(self, ctx: Context) -> None:
        self._ctx = ctx
        self._advance(None)

    def _advance(self, result: Any) -> None:
        self._last_result = result
        while self.factories:
            factory = self.factories.pop(0)
            nxt = factory(result)
            if nxt is None:  # stage skipped for this flow
                continue
            self.current = nxt
            nxt.start(self._ctx)
            if nxt.failed is not None:
                self.failed = nxt.failed
                return
            if nxt.done:  # completed synchronously (e.g. one-item picker)
                final = nxt.view().rstrip("\n")
                if final:
                    self.history.append(final)
                result = nxt.result
                continue
            return
        self.current = None
        self.done, self.result = True, result

    def update(self, ctx: Context, msg: Any) -> None:
        if self.current is None:
            return
        self.current.update(ctx, msg)
        if self.current.failed is not None:
            self.failed = self.current.failed
            return
        if self.current.done:
            final = self.current.view().rstrip("\n")
            if final:
                self.history.append(final)
            self._advance(self.current.result)

    def view(self) -> str:
        parts = list(self.history)
        if self.current is not None:
            parts.append(self.current.view().rstrip("\n"))
        return "\n".join(parts) if parts else ""


# --- reusable stage models -------------------------------------------------


class Picker(Model):
    """Choose one item with arrows+enter; auto-picks a single candidate.
    (reference: manifestsModel — scan dir, prefer kinds, pick)."""

    def __init__(self, title: str, items: List[Any],
                 label: Callable[[Any], str] = str):
        if not items:
            raise SystemExit(f"{title}: nothing to choose from")
        self.title = title
        self.items = items
        self.label = label
        self.idx = 0
        if len(items) == 1:
            self.done, self.result = True, items[0]

    def update(self, ctx: Context, msg: Any) -> None:
        if not isinstance(msg, KeyMsg):
            return
        if msg.key in ("up", "k"):
            self.idx = (self.idx - 1) % len(self.items)
        elif msg.key in ("down", "j", "\t"):
            self.idx = (self.idx + 1) % len(self.items)
        elif msg.key == "enter":
            self.done, self.result = True, self.items[self.idx]
        elif msg.key in ("q", "esc"):
            raise Quit(None)

    def view(self) -> str:
        if self.done:
            return f"✓ {self.title}: {self.label(self.result)}"
        lines = [f"? {self.title} (↑/↓ + enter):"]
        for i, it in enumerate(self.items):
            cursor = "➤" if i == self.idx else " "
            lines.append(f"  {cursor} {self.label(it)}")
        return "\n".join(lines)


class Spinner(Model):
    """Run one background function with a spinner + live status line.
    fn(set_status) -> result. (reference: readinessModel)."""

    def __init__(self, title: str, fn: Callable[[Callable[[str], None]], Any]):
        self.title = title
        self.fn = fn
        self.status = ""
        self.frame = 0

    def start(self, ctx: Context) -> None:
        def run():
            out = self.fn(lambda s: ctx.post(("status", s)))
            return DoneMsg(out)

        ctx.spawn(run)

    def update(self, ctx: Context, msg: Any) -> None:
        if isinstance(msg, TickMsg):
            self.frame += 1
        elif isinstance(msg, tuple) and msg and msg[0] == "status":
            self.status = msg[1]
        elif isinstance(msg, DoneMsg):
            self.done, self.result = True, msg.result
        elif isinstance(msg, ErrMsg):
            self.failed = str(msg.error)

    def view(self) -> str:
        if self.done:
            return f"✓ {self.title}" + (f" — {self.status}" if self.status else "")
        spin = SPINNER[self.frame % len(SPINNER)]
        tail = f" — {self.status}" if self.status else ""
        return f"{spin} {self.title}{tail}"


class Progress(Model):
    """Byte progress bar; the worker posts ("progress", done, total) and a
    final DoneMsg. (reference: uploadModel, upload.go:92-140)."""

    def __init__(self, title: str,
                 fn: Callable[[Callable[[int, int], None]], Any]):
        self.title = title
        self.fn = fn
        self.sent = 0
        self.total = 0

    def start(self, ctx: Context) -> None:
        def run():
            out = self.fn(
                lambda done, total: ctx.post(("progress", done, total))
            )
            return DoneMsg(out)

        ctx.spawn(run)

    def update(self, ctx: Context, msg: Any) -> None:
        if isinstance(msg, tuple) and msg and msg[0] == "progress":
            _, self.sent, self.total = msg
        elif isinstance(msg, DoneMsg):
            self.done, self.result = True, msg.result
        elif isinstance(msg, ErrMsg):
            self.failed = str(msg.error)

    def view(self) -> str:
        width = 28
        if self.total:
            frac = min(1.0, self.sent / self.total)
            fill = int(frac * width)
            bar = "█" * fill + "░" * (width - fill)
            pct = f"{frac * 100:3.0f}%"
        else:
            bar, pct = "░" * width, "  …"
        mark = "✓" if self.done else "⇡"
        return f"{mark} {self.title} [{bar}] {pct}"


class LogView(Model):
    """Scrolling tail of lines posted as ("log", line); finishes on
    DoneMsg. (reference: podsModel log pane)."""

    def __init__(self, title: str, fn: Callable[[Callable[[str], None]], Any],
                 height: int = 8,
                 on_cancel: Optional[Callable[[], None]] = None):
        self.title = title
        self.fn = fn
        self.lines: List[str] = []
        self.height = height
        self.on_cancel = on_cancel

    def cancel(self) -> None:
        if self.on_cancel is not None:
            self.on_cancel()

    def start(self, ctx: Context) -> None:
        def run():
            out = self.fn(lambda line: ctx.post(("log", line)))
            return DoneMsg(out)

        ctx.spawn(run)

    def update(self, ctx: Context, msg: Any) -> None:
        if isinstance(msg, tuple) and msg and msg[0] == "log":
            self.lines.append(msg[1])
        elif isinstance(msg, DoneMsg):
            self.done, self.result = True, msg.result
        elif isinstance(msg, ErrMsg):
            self.failed = str(msg.error)

    def view(self) -> str:
        head = f"{'✓' if self.done else '┃'} {self.title}"
        tail = self.lines[-self.height:]
        return "\n".join([head] + [f"  │ {ln}" for ln in tail])


def interactive(stdout=None) -> bool:
    """TUI flows only when attached to a real terminal."""
    out = stdout or sys.stdout
    return hasattr(out, "isatty") and out.isatty() and sys.stdin.isatty()
