"""Interactive `sub` flows: TUI compositions of the run/notebook pipelines
(reference: internal/tui/run.go:15, internal/tui/notebook.go:65-91 —
manifest picker → upload progress → readiness → pods/logs → sync +
port-forward → browser).

Each flow builds a tui.Sequence of stage models over the same primitives
the plain CLI path uses (commands._tarball, the kube client, the fake env),
so `--fake` drives the full composition against the in-process cluster.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from substratus_tpu.cli import tui


def _fake_env():
    from substratus_tpu.cli import commands

    return commands._FAKE_ENV


def _manifest_label(doc: dict) -> str:
    return f"{doc['kind'].lower()}/{doc['metadata'].get('name', '?')}"


_KIND_PREFERENCE = ("Notebook", "Model", "Dataset", "Server")


def _pick_manifests(args, prefer_kinds=_KIND_PREFERENCE):
    """Stage 0: scan + order candidate manifests (reference
    manifestsModel's kind preference, tui/notebook.go:66-71)."""
    from substratus_tpu.cli.commands import _load_manifests

    docs = _load_manifests(args.filename)
    docs.sort(
        key=lambda d: (
            prefer_kinds.index(d["kind"])
            if d["kind"] in prefer_kinds
            else len(prefer_kinds)
        )
    )
    return docs


def _upload_stage(args, client, doc) -> tui.Progress:
    """Tar + signed-URL PUT with a live bar (reference uploadModel,
    tui/upload.go:92-140); the protocol lives in commands.upload_context.
    The stage runs inside a `cli.flow.upload` span, so the flow's HTTP
    calls carry a traceparent (observability/propagation.py)."""
    from substratus_tpu.cli.commands import upload_context
    from substratus_tpu.observability.tracing import tracer

    def work(progress):
        with tracer.span(
            "cli.flow.upload", kind=doc["kind"],
            object=doc["metadata"].get("name", "?"),
        ):
            return upload_context(args, client, doc, progress=progress)

    return tui.Progress("upload build context", work)


def _readiness_stage(args, client, obj) -> tui.Spinner:
    from substratus_tpu.cli.commands import _wait_ready
    from substratus_tpu.observability.tracing import tracer

    kind, name = obj["kind"], obj["metadata"]["name"]
    ns = obj["metadata"]["namespace"]

    def work(set_status):
        with tracer.span(
            "cli.flow.wait_ready", kind=kind, object=name, namespace=ns
        ):
            return _wait_ready(
                client, kind, ns, name, fake=args.fake, on_status=set_status
            )

    return tui.Spinner(f"waiting for {kind.lower()}/{name}", work)


def _logs_stage(args, client, obj) -> Optional[tui.LogView]:
    """Workload status/log tail (reference podsModel). Fake cluster: the
    workload object's status; real cluster: kubectl log tail."""
    from substratus_tpu.cli.commands import (
        WORKLOAD_SUFFIX,
        fake_workload_status_lines,
        stream_workload_logs,
    )

    kind, name = obj["kind"], obj["metadata"]["name"]
    ns = obj["metadata"]["namespace"]
    workload = f"{name}{WORKLOAD_SUFFIX[kind]}"

    def work(log: Callable[[str], None]) -> Any:
        if args.fake:
            for line in fake_workload_status_lines(
                client, ns, kind, name
            ) or [f"no workload found for {kind.lower()}/{name}"]:
                log(line)
            return obj
        stream_workload_logs(client, ns, kind, name, emit=log)
        return obj

    return tui.LogView(f"{workload} status", work)


def run_flow(args) -> int:
    """`sub run` interactively: pick → upload → readiness → logs."""
    from substratus_tpu.cli.commands import _client

    client = _client(args)
    docs = _pick_manifests(args, prefer_kinds=("Model", "Dataset"))
    seq = tui.Sequence([
        lambda _: tui.Picker("run which manifest?", docs, _manifest_label),
        lambda doc: _upload_stage(args, client, doc),
        lambda obj: _readiness_stage(args, client, obj),
        lambda obj: _logs_stage(args, client, obj),
    ])
    tui.Runtime().run(seq)
    return 0


def notebook_flow(args) -> int:
    """`sub notebook` interactively: pick → convert → readiness → sync +
    port-forward → browser (reference tui/notebook.go:65-91)."""
    from substratus_tpu.cli.commands import _client
    from substratus_tpu.cli.notebook import notebook_for_object

    client = _client(args)
    docs = _pick_manifests(args)

    def to_notebook(doc):
        nb = doc if doc["kind"] == "Notebook" else notebook_for_object(doc)
        nb.setdefault("metadata", {}).setdefault("namespace", args.namespace)
        nb.setdefault("spec", {})["suspend"] = False
        return client.apply(nb)

    def devloop_stage(obj):
        if args.fake:
            return None  # no kubelet to forward to
        import threading

        from substratus_tpu.cli.sync import notebook_dev_loop

        name = obj["metadata"]["name"]
        ns = obj["metadata"]["namespace"]
        stop = threading.Event()

        def work(log: Callable[[str], None]) -> Any:
            notebook_dev_loop(
                client, ns, f"{name}-notebook",
                open_browser=not args.no_open, emit=log, stop=stop,
            )
            return obj

        return tui.LogView(
            "notebook dev loop", work, height=12, on_cancel=stop.set,
        )

    seq = tui.Sequence([
        lambda _: tui.Picker("open which manifest?", docs, _manifest_label),
        lambda doc: tui.Spinner(
            "applying notebook", lambda set_status: to_notebook(doc)
        ),
        lambda obj: _readiness_stage(args, client, obj),
        devloop_stage,
    ])
    tui.Runtime().run(seq)
    return 0
