"""Interactive `sub` flows: TUI compositions of the run/notebook pipelines
(reference: internal/tui/run.go:15, internal/tui/notebook.go:65-91 —
manifest picker → upload progress → readiness → pods/logs → sync +
port-forward → browser).

Each flow builds a tui.Sequence of stage models over the same primitives
the plain CLI path uses (commands._tarball, the kube client, the fake env),
so `--fake` drives the full composition against the in-process cluster.
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, Optional

from substratus_tpu.cli import tui


def _fake_env():
    from substratus_tpu.cli import commands

    return commands._FAKE_ENV


def _manifest_label(doc: dict) -> str:
    return f"{doc['kind'].lower()}/{doc['metadata'].get('name', '?')}"


_KIND_PREFERENCE = ("Notebook", "Model", "Dataset", "Server")


def _pick_manifests(args, prefer_kinds=_KIND_PREFERENCE):
    """Stage 0: scan + order candidate manifests (reference
    manifestsModel's kind preference, tui/notebook.go:66-71)."""
    from substratus_tpu.cli.commands import _load_manifests

    docs = _load_manifests(args.filename)
    docs.sort(
        key=lambda d: (
            prefer_kinds.index(d["kind"])
            if d["kind"] in prefer_kinds
            else len(prefer_kinds)
        )
    )
    return docs


def _upload_stage(args, client, doc) -> tui.Progress:
    """Tar + signed-URL PUT with a live bar (reference uploadModel,
    tui/upload.go:92-140); the protocol lives in commands.upload_context."""
    from substratus_tpu.cli.commands import upload_context

    return tui.Progress(
        "upload build context",
        lambda progress: upload_context(args, client, doc, progress=progress),
    )


def _readiness_stage(args, client, obj) -> tui.Spinner:
    from substratus_tpu.cli.commands import _wait_ready

    kind, name = obj["kind"], obj["metadata"]["name"]
    ns = obj["metadata"]["namespace"]
    return tui.Spinner(
        f"waiting for {kind.lower()}/{name}",
        lambda set_status: _wait_ready(
            client, kind, ns, name, fake=args.fake, on_status=set_status
        ),
    )


def _logs_stage(args, client, obj) -> Optional[tui.LogView]:
    """Workload status/log tail (reference podsModel). Fake cluster: the
    workload object's status; real cluster: kubectl log tail."""
    from substratus_tpu.cli.commands import (
        WORKLOAD_SUFFIX,
        fake_workload_status_lines,
    )

    kind, name = obj["kind"], obj["metadata"]["name"]
    ns = obj["metadata"]["namespace"]
    workload = f"{name}{WORKLOAD_SUFFIX[kind]}"

    def work(log: Callable[[str], None]) -> Any:
        if args.fake:
            for line in fake_workload_status_lines(
                client, ns, kind, name
            ) or [f"no workload found for {kind.lower()}/{name}"]:
                log(line)
            return obj
        import shutil
        import subprocess

        kubectl = shutil.which("kubectl")
        if kubectl is None:
            log("kubectl not on PATH; skipping logs")
            return obj
        sel = f"substratus.ai/object={kind.lower()}-{name}"
        proc = subprocess.Popen(
            [kubectl, "-n", ns, "logs", "-l", sel, "--tail", "20"],
            stdout=subprocess.PIPE, text=True,
        )
        for line in proc.stdout:
            log(line.rstrip())
        return obj

    return tui.LogView(f"{workload} status", work)


def run_flow(args) -> int:
    """`sub run` interactively: pick → upload → readiness → logs."""
    from substratus_tpu.cli.commands import _client

    client = _client(args)
    docs = _pick_manifests(args, prefer_kinds=("Model", "Dataset"))
    seq = tui.Sequence([
        lambda _: tui.Picker("run which manifest?", docs, _manifest_label),
        lambda doc: _upload_stage(args, client, doc),
        lambda obj: _readiness_stage(args, client, obj),
        lambda obj: _logs_stage(args, client, obj),
    ])
    tui.Runtime().run(seq)
    return 0


def notebook_flow(args) -> int:
    """`sub notebook` interactively: pick → convert → readiness → sync +
    port-forward → browser (reference tui/notebook.go:65-91)."""
    from substratus_tpu.cli.commands import _client
    from substratus_tpu.cli.notebook import notebook_for_object

    client = _client(args)
    docs = _pick_manifests(args)

    def to_notebook(doc):
        nb = doc if doc["kind"] == "Notebook" else notebook_for_object(doc)
        nb.setdefault("metadata", {}).setdefault("namespace", args.namespace)
        nb.setdefault("spec", {})["suspend"] = False
        return client.apply(nb)

    def devloop_stage(obj):
        if args.fake:
            return None  # no kubelet to forward to
        name = obj["metadata"]["name"]
        ns = obj["metadata"]["namespace"]
        pod = f"{name}-notebook"

        def work(log: Callable[[str], None]) -> Any:
            import socket
            import threading
            import webbrowser

            from substratus_tpu.cli.sync import (
                port_forward,
                sync_files_from_notebook,
            )

            stop = threading.Event()
            threading.Thread(
                target=sync_files_from_notebook,
                args=(ns, pod, os.getcwd()),
                kwargs={
                    "stop": stop,
                    "on_event": lambda e: log(f"sync: {e['op']} {e['path']}"),
                },
                daemon=True,
            ).start()
            fwd = threading.Thread(
                target=port_forward, args=(ns, pod, 8888, 8888),
                kwargs={"stop": stop}, daemon=True,
            )
            fwd.start()
            url = "http://localhost:8888?token=default"
            for _ in range(60):
                try:
                    with socket.create_connection(
                        ("localhost", 8888), timeout=0.5
                    ):
                        break
                except OSError:
                    time.sleep(0.5)
            log(f"forwarding :8888 — {url} (ctrl-c to stop)")
            if not args.no_open:
                webbrowser.open(url)
            while fwd.is_alive():
                fwd.join(timeout=1.0)
            return obj

        return tui.LogView("notebook dev loop", work, height=12)

    seq = tui.Sequence([
        lambda _: tui.Picker("open which manifest?", docs, _manifest_label),
        lambda doc: tui.Spinner(
            "applying notebook", lambda set_status: to_notebook(doc)
        ),
        lambda obj: _readiness_stage(args, client, obj),
        devloop_stage,
    ])
    tui.Runtime().run(seq)
    return 0
