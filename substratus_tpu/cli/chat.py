"""`sub chat` — interactive chat against a served model (reference:
internal/tui/infer_chat.go — the bubbletea chat surface wired to a
served endpoint; dead code upstream behind the commented-out `infer`
command at internal/cli/root.go:19, implemented here as a live command).

Talks the OpenAI chat API the serving engine exposes
(POST /v1/chat/completions with stream=true, SSE chunks), so the same
REPL works against `sub serve`, a Server CR behind a port-forward, or
any OpenAI-compatible endpoint.

Endpoint resolution:
  sub chat --url http://localhost:8080      # direct (local `sub serve`)
  sub chat srv                              # Server CR: resolve the
      -server pod and port-forward :8080 through the apiserver
      (kube/ws.py portforward.k8s.io streams), then chat over loopback.
"""
from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional

# Load-shed retry policy: 429/503 with Retry-After is the serving
# tier TELLING us when to come back (gateway/server admission control,
# docs/serving.md "Shedding"); honoring it beats failing the turn.
RETRY_STATUSES = (429, 503)
MAX_RETRIES = 3
MAX_RETRY_AFTER_S = 30.0


def _retry_after_s(err: "urllib.error.HTTPError") -> float:
    """The server's Retry-After in seconds, clamped sane; 1 s when the
    header is absent or unparseable (HTTP-date form included — not
    worth a date parser for a sleep hint)."""
    raw = (err.headers.get("Retry-After") or "").strip()
    try:
        return min(MAX_RETRY_AFTER_S, max(0.0, float(raw)))
    except ValueError:
        return 1.0


ANSI_USER = "\x1b[36m"     # cyan
ANSI_MODEL = "\x1b[32m"    # green
ANSI_DIM = "\x1b[2m"
ANSI_RESET = "\x1b[0m"


def _color(enabled: bool, code: str) -> str:
    return code if enabled else ""


def stream_chat(
    url: str,
    messages: List[dict],
    *,
    max_tokens: int = 256,
    temperature: float = 0.7,
    timeout: float = 300.0,
    model: Optional[str] = None,
):
    """POST /v1/chat/completions stream=true; yields content deltas.

    Each request runs inside a `cli.chat_request` span whose W3C
    traceparent rides the request headers — the server adopts it, so the
    CLI, HTTP, and engine spans of one turn share one trace id end to end
    (docs/observability.md "Distributed tracing")."""
    from substratus_tpu.observability.propagation import (
        format_traceparent, inject_headers,
    )
    from substratus_tpu.observability.tracing import tracer

    payload = {
        "messages": messages,
        "max_tokens": max_tokens,
        "temperature": temperature,
        "stream": True,
    }
    if model:
        # The OpenAI `model` field end to end: the gateway routes by it
        # (adapter affinity) and the server maps it to a LoRA adapter
        # slot (multi-tenant serving, docs/serving.md).
        payload["model"] = model
    body = json.dumps(payload).encode()
    with tracer.span(
        "cli.chat_request", endpoint="/v1/chat/completions",
        messages=len(messages),
    ) as span:
        span.set_attribute("traceparent", format_traceparent(span.context()))
        req = urllib.request.Request(
            url.rstrip("/") + "/v1/chat/completions",
            data=body,
            headers=inject_headers({"Content-Type": "application/json"}),
        )
        resp = None
        for attempt in range(1 + MAX_RETRIES):
            try:
                resp = urllib.request.urlopen(req, timeout=timeout)
                break
            except urllib.error.HTTPError as e:
                # A shed (429/503) names its own comeback time; anything
                # else propagates to the REPL's error handling.
                if e.code not in RETRY_STATUSES or attempt == MAX_RETRIES:
                    raise
                wait = _retry_after_s(e)
                span.set_attribute("retried_after_s", wait)
                sys.stderr.write(
                    f"(server busy, retrying in {wait:.0f}s)\n"
                )
                time.sleep(wait)
        with resp:
            server_trace = resp.headers.get("x-trace-id")
            if server_trace:
                span.set_attribute("server_trace_id", server_trace)
            for raw in resp:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data:"):
                    continue
                payload = line[len("data:"):].strip()
                if payload == "[DONE]":
                    return
                try:
                    chunk = json.loads(payload)
                except ValueError:
                    continue
                for choice in chunk.get("choices", []):
                    delta = choice.get("delta", {}).get("content")
                    if delta:
                        yield delta


def repl(
    url: str,
    *,
    stdin=None,
    stdout=None,
    max_tokens: int = 256,
    temperature: float = 0.7,
    system: Optional[str] = None,
    color: Optional[bool] = None,
    model: Optional[str] = None,
) -> int:
    """The chat loop. Plain readline REPL (works over any terminal or
    pty; /quit or EOF exits, /reset clears the conversation)."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    if color is None:
        color = getattr(stdout, "isatty", lambda: False)()
    messages: List[dict] = []
    if system:
        messages.append({"role": "system", "content": system})

    stdout.write(
        f"{_color(color, ANSI_DIM)}chatting with {url} — /quit to exit, "
        f"/reset to clear history{_color(color, ANSI_RESET)}\n"
    )
    stdout.flush()
    while True:
        stdout.write(f"{_color(color, ANSI_USER)}you>{_color(color, ANSI_RESET)} ")
        stdout.flush()
        try:
            line = stdin.readline()
        except KeyboardInterrupt:
            # ctrl-c at the prompt is the normal way out of an
            # interactive tool — exit cleanly, no traceback
            stdout.write("\n")
            return 0
        if not line:
            stdout.write("\n")
            return 0
        line = line.strip()
        if not line:
            continue
        if line in ("/quit", "/exit"):
            return 0
        if line == "/reset":
            messages = [m for m in messages if m["role"] == "system"]
            stdout.write(
                f"{_color(color, ANSI_DIM)}(history cleared)"
                f"{_color(color, ANSI_RESET)}\n"
            )
            continue
        messages.append({"role": "user", "content": line})
        stdout.write(
            f"{_color(color, ANSI_MODEL)}model>{_color(color, ANSI_RESET)} "
        )
        stdout.flush()
        reply = []
        try:
            for delta in stream_chat(
                url, messages, max_tokens=max_tokens,
                temperature=temperature, model=model,
            ):
                reply.append(delta)
                stdout.write(delta)
                stdout.flush()
        except KeyboardInterrupt:
            stdout.write(
                f"\n{_color(color, ANSI_DIM)}(interrupted)"
                f"{_color(color, ANSI_RESET)}"
            )
        except OSError as e:
            stdout.write(
                f"\n{_color(color, ANSI_DIM)}request failed: {e}"
                f"{_color(color, ANSI_RESET)}\n"
            )
            messages.pop()  # request never answered; keep history clean
            continue
        stdout.write("\n")
        stdout.flush()
        messages.append({"role": "assistant", "content": "".join(reply)})


def run_chat(args) -> int:
    # --plain forces uncolored output (the REPL is line-based either way)
    color = False if getattr(args, "plain", False) else None
    model = getattr(args, "model", None)
    if args.url:
        return repl(
            args.url,
            max_tokens=args.max_tokens,
            temperature=args.temperature,
            system=args.system,
            color=color,
            model=model,
        )
    if not args.name:
        raise SystemExit("sub chat: give a Server name or --url")
    # Server CR path: find the -server pod, port-forward 8080, chat over
    # loopback (same machinery as `sub notebook`'s forward).
    import threading

    from substratus_tpu.cli import commands
    from substratus_tpu.cli.sync import port_forward

    client = commands._client(args)
    ns = getattr(args, "namespace", "default") or "default"
    client.get("Server", ns, args.name)  # NotFound here beats a pod hunt
    pods = [
        p for p in client.list("Pod", ns)
        if p["metadata"].get("labels", {}).get("substratus.ai/object")
        == f"server-{args.name}"
        and p.get("status", {}).get("phase") == "Running"
    ]
    if not pods:
        raise SystemExit(
            f"no running pod for server {args.name!r} (is it Ready?)"
        )
    pod = pods[0]["metadata"]["name"]
    local_port = args.local_port
    t = threading.Thread(
        target=port_forward, args=(client, ns, pod, local_port, 8080),
        daemon=True,
    )
    t.start()
    # Wait for the forward to round-trip before the first request — the
    # local listener accepts before any pod-side stream exists
    # (cli/sync.py::_probe_forward; same wait the notebook loop does).
    from substratus_tpu.cli.sync import _probe_forward

    for _ in range(60):
        if not t.is_alive():
            raise SystemExit("port-forward failed — `sub logs server "
                             f"{args.name}` for the pod side")
        if _probe_forward(local_port):
            break
        time.sleep(0.5)
    return repl(
        f"http://127.0.0.1:{local_port}",
        max_tokens=args.max_tokens,
        temperature=args.temperature,
        system=args.system,
        color=color,
        model=model,
    )
