"""`sub` CLI entrypoint (reference: internal/cli/root.go:15-22).

Commands are registered as the corresponding subsystems land; this module is
the stable console-script target.
"""
from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    from substratus_tpu.cli.root import run

    return run(argv)


if __name__ == "__main__":
    sys.exit(main())
