"""`sub notebook` dev loop (reference: internal/cli/notebook.go +
internal/tui/notebook.go:65-91 compose manifests->upload->readiness->
port-forward->browser; internal/client/notebook.go:20-86 converts
Model/Server/Dataset manifests into Notebooks).

Terminal (non-TUI) rendition of the same flow. Port-forward/file-sync need a
real cluster; under --fake the flow stops after readiness.
"""
from __future__ import annotations


def notebook_for_object(doc: dict) -> dict:
    """Convert a Model/Server/Dataset manifest to a Notebook (reference
    client/notebook.go:20-86): same image/build/resources/params, refs
    carried over."""
    kind = doc.get("kind")
    spec = doc.get("spec", {})
    nb_spec = {
        k: spec[k]
        for k in ("image", "build", "resources", "params", "env")
        if k in spec
    }
    if kind == "Model":
        for k in ("model", "dataset"):
            if k in spec:
                nb_spec[k] = spec[k]
    elif kind == "Server":
        if "model" in spec:
            nb_spec["model"] = spec["model"]
    elif kind == "Dataset":
        pass
    return {
        "apiVersion": "substratus.ai/v1",
        "kind": "Notebook",
        "metadata": dict(doc.get("metadata", {})),
        "spec": nb_spec,
    }


def run_notebook(args, client) -> int:
    from substratus_tpu.cli.commands import _load_manifests, _wait_ready

    docs = _load_manifests(args.filename)
    if not docs:
        raise SystemExit(f"no substratus manifests under {args.filename}")
    # Prefer an explicit Notebook, else convert (kind preference mirrors
    # reference tui/notebook.go:66-71).
    doc = next((d for d in docs if d["kind"] == "Notebook"), None)
    if doc is None:
        doc = notebook_for_object(docs[0])
    doc.setdefault("metadata", {}).setdefault("namespace", args.namespace)
    doc.setdefault("spec", {})["suspend"] = False
    obj = client.apply(doc)
    name = obj["metadata"]["name"]
    ns = obj["metadata"]["namespace"]
    print(f"notebook.substratus.ai/{name} applied")
    _wait_ready(client, "Notebook", ns, name, fake=args.fake)

    if args.fake:
        print("fake mode: skipping port-forward/browser")
        return 0

    # Dev loop: file-sync + port-forward in the background, browser in front
    # (reference tui/notebook.go:65-91 composition).
    from substratus_tpu.cli.sync import notebook_dev_loop

    notebook_dev_loop(
        client, ns, f"{name}-notebook", open_browser=not args.no_open,
    )
    return 0
