"""`sub` subcommands (reference: internal/cli/{apply,get,delete,run,notebook,
serve}.go).

Command surface parity: apply -f, get [kind [name]], delete kind name,
run (build-upload a local dir as a Dataset/Model and wait), notebook (dev
loop), serve. The bubbletea TUI becomes plain terminal progress output; the
flows (tar+md5 -> apply CR with build.upload -> wait for signed URL -> PUT ->
wait ready) are the same (reference internal/tui/upload.go:92-140,
internal/client/upload.go:38-192).

`--fake` runs every command against an in-process fake apiserver +
controller manager (kube/fake.py) — the local dev loop without a cluster.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import re
import sys
import tarfile
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Optional

import yaml

from substratus_tpu.api.types import KIND_OF_PLURAL, KINDS, PLURALS
from substratus_tpu.kube.client import NotFound

_FAKE_ENV = None


def _client(args):
    """Build a KubeClient: real (kubeconfig/in-cluster) or fake."""
    global _FAKE_ENV
    if getattr(args, "fake", False):
        if _FAKE_ENV is None:
            from substratus_tpu.cli.fake_env import FakeEnv

            _FAKE_ENV = FakeEnv()
        return _FAKE_ENV.client
    from substratus_tpu.kube.config import default_client

    # Full auth surface (in-cluster SA, tokens, client certs, exec plugins
    # like gke-gcloud-auth-plugin) lives in kube/config.py.
    try:
        return default_client()
    except FileNotFoundError:
        raise SystemExit("no kubeconfig found and not in-cluster (try --fake)")


def _load_manifests(path: str):
    docs = []
    skipped = []
    paths = []
    if os.path.isdir(path):
        for f in sorted(os.listdir(path)):
            if f.endswith((".yaml", ".yml")):
                paths.append(os.path.join(path, f))
    else:
        paths = [path]
    for p in paths:
        with open(p) as f:
            for doc in yaml.safe_load_all(f):
                if doc and doc.get("kind") in KINDS:
                    docs.append(doc)
                elif doc:
                    skipped.append((p, doc.get("kind")))
    for p, kind in skipped:
        print(
            f"warning: skipping non-substratus doc in {p} (kind={kind!r})",
            file=sys.stderr,
        )
    if not docs and not os.path.isdir(path):
        raise SystemExit(f"no substratus manifests in {path}")
    return docs


def _norm_kind(kind: str) -> str:
    k = kind.rstrip("s").title() if kind.lower() in KIND_OF_PLURAL else kind.title()
    if k not in KINDS:
        k = KIND_OF_PLURAL.get(kind.lower(), kind)
    if k not in KINDS:
        raise SystemExit(f"unknown kind {kind!r} (known: {', '.join(KINDS)})")
    return k


# CR kind -> the workload object it owns (shared by `sub logs` and the
# TUI's log stage — one map, or the two drift).
WORKLOAD_SUFFIX = {
    "Dataset": "-data-loader",
    "Model": "-modeller",
    "Notebook": "-notebook",
    "Server": "-server",
}


def _wait_ready(client, kind, ns, name, timeout=720, fake=False,
                on_status=None):
    """Poll status.ready (reference client.go:114-135 WaitReady; the 720s
    budget mirrors test/system.sh:53-54). on_status replaces line printing
    (the TUI spinner narrates through it)."""
    t0 = time.time()
    last_msg = ""
    while time.time() - t0 < timeout:
        if fake and _FAKE_ENV is not None:
            _FAKE_ENV.step()
        obj = client.get_or_none(kind, ns, name)
        conds = (obj or {}).get("status", {}).get("conditions", [])
        msg = "; ".join(
            f"{c['type']}={c['status']}({c.get('reason', '')})" for c in conds
        )
        if on_status is not None:
            if msg:
                on_status(msg)
        elif msg != last_msg:
            print(f"  waiting: {msg or 'no status yet'}")
            last_msg = msg
        if obj and obj.get("status", {}).get("ready"):
            if on_status is None:
                print(f"{kind} {name} ready")
            return obj
        time.sleep(0.05 if fake else 2)
    raise SystemExit(f"timed out waiting for {kind} {name}")


def fake_workload_status_lines(client, ns, kind, name):
    """Fake-cluster workload inspection lines, or None if no workload
    exists (shared by `sub logs --fake` and the TUI log stage)."""
    workload = f"{name}{WORKLOAD_SUFFIX[kind]}"
    for wkind in ("Job", "JobSet", "Deployment", "Pod"):
        w = client.get_or_none(wkind, ns, workload)
        if w is not None:
            lines = [f"{wkind.lower()}/{workload}"]
            lines += json.dumps(w.get("status", {}), indent=2).splitlines()
            return lines
    return None


# -- commands --------------------------------------------------------------


def cmd_apply(args) -> int:
    client = _client(args)
    for doc in _load_manifests(args.filename):
        doc.setdefault("metadata", {}).setdefault("namespace", args.namespace)
        out = client.apply(doc)
        print(f"{out['kind'].lower()}.substratus.ai/{out['metadata']['name']} applied")
        if args.wait:
            _wait_ready(
                client, out["kind"], out["metadata"]["namespace"],
                out["metadata"]["name"], fake=args.fake,
            )
    return 0


def _render_table(client, args) -> None:
    kinds = [_norm_kind(args.kind)] if args.kind else list(KINDS)
    rows = []
    for kind in kinds:
        for obj in client.list(kind, args.namespace):
            if args.name and obj["metadata"]["name"] != args.name:
                continue
            conds = obj.get("status", {}).get("conditions", [])
            latest = conds[-1]["reason"] if conds else ""
            rows.append(
                (
                    PLURALS[kind],
                    obj["metadata"]["name"],
                    str(obj.get("status", {}).get("ready", False)).lower(),
                    latest or "",
                )
            )
    if not rows:
        print("no resources found")
        return
    widths = [max(len(r[i]) for r in rows + [("KIND", "NAME", "READY", "STATUS")]) for i in range(4)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format("KIND", "NAME", "READY", "STATUS"))
    for r in rows:
        print(fmt.format(*r))


def cmd_get(args) -> int:
    client = _client(args)
    if getattr(args, "watch", False):
        # Live status view (the reference's TUI readiness panel, terminal
        # rendition): redraw on an interval, reusing one client.
        try:
            while True:
                print("\033[2J\033[H", end="")
                _render_table(client, args)
                time.sleep(2)
        except KeyboardInterrupt:
            return 0
    _render_table(client, args)
    return 0


def cmd_delete(args) -> int:
    client = _client(args)
    kind = _norm_kind(args.kind)
    client.delete(kind, args.namespace, args.name)
    print(f"{kind.lower()}.substratus.ai/{args.name} deleted")
    return 0


class _HashingFile:
    """File wrapper feeding an incremental md5 as bytes are written."""

    def __init__(self, f):
        self.f = f
        self.md5 = hashlib.md5()

    def write(self, data):
        self.md5.update(data)
        return self.f.write(data)

    def __getattr__(self, name):
        return getattr(self.f, name)


def _tarball(directory: str):
    """tar.gz a build context to a tempfile with incremental md5 — build
    contexts can be multi-GB, so never buffer in RAM (reference
    client/upload.go:38-68). Returns (path, md5_hex, md5_b64, size)."""
    import base64
    import tempfile

    if not os.path.exists(os.path.join(directory, "Dockerfile")):
        raise SystemExit(f"no Dockerfile in {directory}")
    tmp = tempfile.NamedTemporaryFile(
        suffix=".tar.gz", delete=False, mode="wb"
    )
    hasher = _HashingFile(tmp)
    with tarfile.open(fileobj=hasher, mode="w:gz") as tar:
        for root, dirs, files in os.walk(directory):
            dirs[:] = [d for d in dirs if not d.startswith(".")]
            for f in files:
                full = os.path.join(root, f)
                tar.add(full, arcname=os.path.relpath(full, directory))
    tmp.close()
    digest = hasher.md5.digest()
    return (
        tmp.name,
        hasher.md5.hexdigest(),
        base64.b64encode(digest).decode(),
        os.path.getsize(tmp.name),
    )


class _ProgressReader:
    """File wrapper reporting bytes read (drives the TUI upload bar)."""

    def __init__(self, f, total, cb):
        self.f, self.total, self.cb, self.sent = f, total, cb, 0

    def read(self, n=-1):
        data = self.f.read(n)
        self.sent += len(data)
        self.cb(self.sent, self.total)
        return data


def upload_context(args, client, doc, progress=None):
    """Tar the build context, apply the CR with build.upload, wait for the
    controller's signed URL, PUT the tarball (reference upload.go:38-192).
    progress(done_bytes, total_bytes) drives the TUI bar; None prints the
    plain-CLI lines. Returns the applied object."""
    tar_path, md5, md5_b64, size = _tarball(args.dir)
    if progress is not None:
        progress(0, size)
    request_id = uuid.uuid4().hex
    doc.setdefault("metadata", {}).setdefault("namespace", args.namespace)
    ns0 = doc["metadata"]["namespace"]
    if getattr(args, "increment", False):
        # -i: create `{name}-{N+1}` next to the highest existing
        # `{name}-N` (reference tui/common.go nextModelVersion /
        # nextDatasetVersion — iterate-and-version, never overwrite).
        base = doc["metadata"]["name"]
        pat = re.compile(re.escape(base) + r"-(\d+)$")
        highest = 0
        for item in client.list(doc["kind"], ns0):
            m = pat.fullmatch(item["metadata"]["name"])
            if m:
                highest = max(highest, int(m.group(1)))
        doc["metadata"]["name"] = f"{base}-{highest + 1}"
        if progress is None:
            print(f"next version: {doc['metadata']['name']}")
    elif getattr(args, "replace", False):
        # -r: delete any existing object so the new build context starts
        # a fresh lifecycle (reference common.go:192-201 delete-and-
        # recreate). Validate the new manifest BEFORE deleting — a
        # malformed replacement must not destroy the old object and its
        # cascade-owned children.
        from substratus_tpu.kube.schema import SchemaError, validate

        try:
            validate(doc)
        except SchemaError as e:
            raise SystemExit(f"--replace refused: new manifest invalid: {e}")
        try:
            client.delete(doc["kind"], ns0, doc["metadata"]["name"])
            if progress is None:
                print(f"replaced existing {doc['kind'].lower()}/"
                      f"{doc['metadata']['name']}")
        except NotFound:
            pass
    doc.setdefault("spec", {})["build"] = {
        "upload": {"md5Checksum": md5, "requestId": request_id}
    }
    obj = client.apply(doc)
    kind, name = obj["kind"], obj["metadata"]["name"]
    ns = obj["metadata"]["namespace"]
    if progress is None:
        print(f"{kind.lower()}/{name} applied (upload {size} bytes, md5 {md5})")

    # Wait for our signed URL (reference upload.go:126-178).
    url = None
    for _ in range(300):
        if args.fake and _FAKE_ENV is not None:
            _FAKE_ENV.step()
        live = client.get(kind, ns, name)
        bu = live.get("status", {}).get("buildUpload", {})
        if bu.get("requestId") == request_id and bu.get("signedUrl"):
            url = bu["signedUrl"]
            break
        time.sleep(0.05 if args.fake else 2)
    if url is None:
        raise SystemExit("controller never published a signed upload URL")

    from substratus_tpu.observability.propagation import inject_headers
    from substratus_tpu.observability.tracing import tracer

    try:
        with tracer.span(
            "cli.upload", kind=kind, object=name, bytes=size,
        ):
            if args.fake and _FAKE_ENV is not None:
                with open(tar_path, "rb") as f:
                    _FAKE_ENV.accept_upload(f.read(), md5)
                if progress is not None:
                    progress(size, size)
                else:
                    print("uploaded to fake storage")
            else:
                with open(tar_path, "rb") as f:
                    data = f if progress is None else _ProgressReader(
                        f, size, progress
                    )
                    req = urllib.request.Request(
                        url, data=data, method="PUT",
                        # traceparent rides along so a storage-side proxy
                        # (or the SCI local-FS handler) can join the trace.
                        headers=inject_headers({
                            "Content-Type": "application/octet-stream",
                            # Signed URLs are md5-bound; storage rejects a
                            # PUT without the matching header (reference
                            # client/upload.go:337, sci/kind/server.go:39).
                            "Content-MD5": md5_b64,
                            "Content-Length": str(size),
                        }),
                    )
                    with urllib.request.urlopen(req, timeout=300) as r:
                        r.read()
                if progress is None:
                    print(f"uploaded ({r.status})")
                # nudge the controller (reference upload.go:184-189)
                live = client.get(kind, ns, name)
                live["metadata"].setdefault("annotations", {})[
                    "substratus.ai/upload-timestamp"
                ] = str(time.time())
                client.update(live)
    finally:
        os.unlink(tar_path)
    return obj


def cmd_run(args) -> int:
    """Upload the current dir and run it as a Dataset or Model (reference
    internal/cli/run.go:16-104). On a real terminal this is the interactive
    TUI flow (cli/flows.py); --plain (or a non-tty) selects line output."""
    from substratus_tpu.cli import tui

    if tui.interactive() and not getattr(args, "plain", False):
        from substratus_tpu.cli.flows import run_flow

        return run_flow(args)
    client = _client(args)
    docs = _load_manifests(args.filename) if args.filename else []
    if not docs:
        raise SystemExit("run requires -f manifest describing the Dataset/Model")
    obj = upload_context(args, client, docs[0])
    _wait_ready(
        client, obj["kind"], obj["metadata"]["namespace"],
        obj["metadata"]["name"], fake=args.fake,
    )
    return 0


def cmd_serve(args) -> int:
    """Run the serving container locally (reference `sub serve`)."""
    from substratus_tpu.serve.main import main as serve_main

    argv = []
    if args.model:
        argv += ["--model", args.model]
    if args.config:
        argv += ["--config", args.config]
    argv += ["--port", str(args.port)]
    return serve_main(argv)


def cmd_batchgen(args) -> int:
    """Run offline batch generation locally against a prompt manifest
    (serve/batchgen.py). The cluster path is a Server CR whose
    `params.batchGenerate` is set, submitted like any other CR with
    `sub run`/`sub apply` — the controller renders it as a Job (or a
    JobSet gang for multi-host slices); docs/batch-generation.md."""
    from substratus_tpu.serve.batchgen import main as batchgen_main

    argv = ["--manifest", args.manifest, "--output", args.output]
    if args.model:
        argv += ["--model", args.model]
    if args.config:
        argv += ["--config", args.config]
    if args.max_tokens is not None:
        argv += ["--max-tokens", str(args.max_tokens)]
    if args.temperature is not None:
        argv += ["--temperature", str(args.temperature)]
    if args.no_resume:
        argv += ["--no-resume"]
    if args.progress_port is not None:
        argv += ["--progress-port", str(args.progress_port)]
    return batchgen_main(argv)


def cmd_chat(args) -> int:
    """Interactive chat REPL (reference tui/infer_chat.go)."""
    from substratus_tpu.cli.chat import run_chat

    return run_chat(args)


def cmd_notebook(args) -> int:
    from substratus_tpu.cli import tui

    if tui.interactive() and not getattr(args, "plain", False):
        from substratus_tpu.cli.flows import notebook_flow

        return notebook_flow(args)
    from substratus_tpu.cli.notebook import run_notebook

    return run_notebook(args, _client(args))


def cmd_logs(args) -> int:
    """Logs for the workload a CR owns (reference: the TUI's pods panel,
    internal/tui — pod list/log streaming). Real clusters stream via
    client.pod_logs (REST, follow); the fake cluster prints the workload
    object's status."""
    client = _client(args)
    kind = _norm_kind(args.kind)
    if args.fake and _FAKE_ENV is not None:
        _FAKE_ENV.step()  # reconcile so just-applied CRs have workloads
    obj = client.get_or_none(kind, args.namespace, args.name)
    if obj is None:
        raise SystemExit(f"{kind.lower()}/{args.name} not found")
    if args.fake:
        lines = fake_workload_status_lines(
            client, args.namespace, kind, args.name
        )
        if lines is None:
            print(f"no workload found for {kind.lower()}/{args.name}")
            return 1
        print(f"{lines[0]} (fake cluster; no kubelet logs)")
        for line in lines[1:]:
            print(line)
        return 0
    try:
        return stream_workload_logs(
            client, args.namespace, kind, args.name,
            tail=args.tail, follow=args.follow,
        )
    except KeyboardInterrupt:
        return 0


def workload_selector(kind: str, name: str) -> str:
    """Label selector for the pods a CR's workload owns (the controllers
    stamp substratus.ai/object on every workload pod template)."""
    return f"substratus.ai/object={kind.lower()}-{name}"


def stream_workload_logs(
    client, namespace: str, kind: str, name: str,
    *, tail: int = 20, follow: bool = False, emit=print,
) -> int:
    """Tail a CR's workload pod logs through the in-library pod log API
    (kube/real.py) — no kubectl. Shared by `sub logs` and the TUI's log
    stage. With follow, multi-pod workloads stream concurrently (one
    follow generator never returns, so sequential iteration would hide
    every pod after the first)."""
    pods = client.list_selected(
        "Pod", namespace, workload_selector(kind, name)
    )
    if not pods:
        emit(f"no pods found for {kind.lower()}/{name}")
        return 1
    prefix = len(pods) > 1

    def tail_one(pod_name: str) -> None:
        for line in client.pod_logs(
            namespace, pod_name, tail=tail, follow=follow
        ):
            emit(f"[{pod_name}] {line}" if prefix else line)

    if follow and len(pods) > 1:
        import threading

        threads = [
            threading.Thread(
                target=tail_one, args=(p["metadata"]["name"],), daemon=True
            )
            for p in pods
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return 0
    for pod in pods:
        tail_one(pod["metadata"]["name"])
    return 0


def cmd_events(args) -> int:
    """`sub events` — the controller event stream (reconcile transitions,
    build lifecycle, upload handshakes) as `kubectl get events` renders
    core/v1 Events. The controller's EventRecorder (observability/
    events.py) upserts count-deduped Event objects; this lists them
    newest-first. Works identically against the fake cluster."""
    client = _client(args)
    if args.fake and _FAKE_ENV is not None:
        _FAKE_ENV.step()  # reconcile so just-applied CRs have narrated
    evs = client.list("Event", args.namespace)
    if not evs:
        print("no events found")
        return 0
    evs.sort(key=lambda e: e.get("lastTimestamp", ""), reverse=True)
    rows = [("LAST SEEN", "TYPE", "REASON", "OBJECT", "COUNT", "MESSAGE")]
    for e in evs:
        inv = e.get("involvedObject", {})
        obj_ref = (
            f"{inv.get('kind', '?').lower()}/{inv.get('name', '?')}"
            if inv.get("kind") or inv.get("name") else "-"
        )
        rows.append(
            (
                e.get("lastTimestamp", "?"),
                e.get("type", "?"),
                e.get("reason", "?"),
                obj_ref,
                str(e.get("count", 1)),
                e.get("message", ""),
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(5)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths) + "  {}"
    for r in rows:
        print(fmt.format(*r))
    return 0


def cmd_trace(args) -> int:
    """`sub trace <id>` — the full request-journey waterfall for one trace
    id (the `x-trace-id` response header) or request id: every lifecycle
    event from gateway arrival through prefill, the KV handoff, decode,
    and token emission, one row per event. Against a gateway `--url` it
    queries /debug/journeyz (which joins the edge-side journey with every
    replica's stitched engine journey); against a bare replica it falls
    back to /debug/requestz?id=."""
    base = (args.url or "http://localhost:8080").rstrip("/")
    headers = {}
    if getattr(args, "token", None):
        headers["Authorization"] = f"Bearer {args.token}"
    qid = urllib.parse.quote(args.id)
    body = None
    last_err = None
    for path in ("/debug/journeyz", "/debug/requestz"):
        req = urllib.request.Request(f"{base}{path}?id={qid}", headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=15) as resp:
                body = json.loads(resp.read().decode())
            break
        except urllib.error.HTTPError as e:
            # 404: replica without a gateway (no /debug/journeyz route) or
            # an evicted/unknown journey — try the fallback endpoint.
            last_err = f"{path} -> HTTP {e.code}"
            if e.code not in (404,):
                print(f"error: {base}{path} answered {e.code} {e.reason}",
                      file=sys.stderr)
                return 1
        except (urllib.error.URLError, OSError) as e:
            print(f"error: cannot reach {base}: {e}", file=sys.stderr)
            return 1
    if body is None or not isinstance(body, dict) or "journey" not in body:
        print(f"no journey found for {args.id!r} ({last_err})",
              file=sys.stderr)
        return 1
    journey = body.get("journey") or {}
    events = body.get("waterfall") or []
    print(f"trace {journey.get('trace_id', '?')}  "
          f"request {journey.get('rid') or '-'}")
    if not events:
        print("no events recorded")
        return 0
    t0 = int(events[0].get("ts_us", 0))
    rows = [("T+MS", "ORIGIN", "EVENT", "DETAIL")]
    for ev in events:
        data = ev.get("data") or {}
        detail = (
            " ".join(f"{k}={v}" for k, v in sorted(data.items()))
            if isinstance(data, dict) else str(data)
        )
        rows.append(
            (
                f"{(int(ev.get('ts_us', t0)) - t0) / 1000.0:+.3f}",
                str(ev.get("origin", "?")),
                str(ev.get("type", "?")),
                detail,
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths) + "  {}"
    for r in rows:
        print(fmt.format(*r))
    breaches = journey.get("breaches") or []
    if breaches:
        print(
            "SLO breaches: "
            + ", ".join(
                f"{b.get('slo', '?')}={b.get('seconds', 0):.4f}s"
                f" (limit {b.get('threshold_s', 0):.4f}s)"
                for b in breaches
            )
        )
    return 0


def cmd_rollout(args) -> int:
    """`sub rollout` — operator-driven zero-downtime rolling weight-swap
    (controller/rollout.py RolloutCoordinator): one replica at a time,
    fleet-health-gated, POST /swapz + verify via /loadz. Replicas come
    from an explicit `--replicas` list or are discovered from a gateway
    `--url`'s /debug/fleetz."""
    from substratus_tpu.controller.rollout import (
        RolloutCoordinator, _default_fetch, _default_post,
    )

    token = getattr(args, "token", None)
    if args.replicas:
        replicas = [
            r.strip().rstrip("/") for r in args.replicas.split(",")
            if r.strip()
        ]
    else:
        base = (args.url or "http://localhost:8080").rstrip("/")
        status, body = _default_fetch(f"{base}/debug/fleetz", token=token)
        if status != 200 or not isinstance(body, dict):
            print(
                f"error: {base}/debug/fleetz answered {status} — pass "
                "--replicas to name the fleet explicitly",
                file=sys.stderr,
            )
            return 1
        replicas = sorted(body.get("replicas") or {})
    if not replicas:
        print("error: no replicas to roll", file=sys.stderr)
        return 1
    coord = RolloutCoordinator(
        fetch=lambda u: _default_fetch(u, token=token),
        post=lambda u, b: _default_post(u, b, token=token),
    )
    print(f"rolling {args.checkpoint} across {len(replicas)} replicas")
    res = coord.run(replicas, args.checkpoint, version=args.version)
    for url in res["swapped"]:
        print(f"  swapped {url} -> weights_version={res['version']}")
    if not res["ok"]:
        print(
            f"rollout aborted at {res['failed']}: {res['reason']}",
            file=sys.stderr,
        )
        return 1
    print(f"rollout complete: weights_version={res['version']}")
    return 0


def cmd_version(args) -> int:
    from substratus_tpu import __version__

    print(f"sub {__version__}")
    return 0


def register(sub) -> None:
    def common(p):
        p.add_argument("-n", "--namespace", default="default")
        p.add_argument(
            "--fake", action="store_true",
            help="in-process fake cluster (local dev)",
        )
        p.add_argument(
            "--plain", action="store_true",
            help="line output instead of the interactive TUI",
        )

    p = sub.add_parser("apply", help="apply substratus manifests")
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("--wait", action="store_true", help="wait for ready")
    common(p)
    p.set_defaults(func=cmd_apply)

    p = sub.add_parser("get", help="list substratus objects")
    p.add_argument("kind", nargs="?")
    p.add_argument("name", nargs="?")
    p.add_argument("-w", "--watch", action="store_true", help="live refresh")
    common(p)
    p.set_defaults(func=cmd_get)

    p = sub.add_parser("delete", help="delete an object")
    p.add_argument("kind")
    p.add_argument("name")
    common(p)
    p.set_defaults(func=cmd_delete)

    p = sub.add_parser(
        "run", help="upload current dir + run as Dataset/Model"
    )
    p.add_argument("-f", "--filename", required=True)
    p.add_argument("-d", "--dir", default=".")
    vg = p.add_mutually_exclusive_group()
    vg.add_argument(
        "-i", "--increment", action="store_true",
        help="create {name}-{N+1} next to the highest existing {name}-N",
    )
    vg.add_argument(
        "-r", "--replace", action="store_true",
        help="delete an existing object of the same name first",
    )
    common(p)
    p.set_defaults(func=cmd_run)

    p = sub.add_parser("notebook", help="launch a notebook dev environment")
    p.add_argument("-f", "--filename", default=".")
    p.add_argument("--no-open", action="store_true")
    common(p)
    p.set_defaults(func=cmd_notebook)

    p = sub.add_parser(
        "events", help="controller events (reconcile/build transitions)"
    )
    common(p)
    p.set_defaults(func=cmd_events)

    p = sub.add_parser("logs", help="logs for a CR's workload")
    p.add_argument("kind")
    p.add_argument("name")
    p.add_argument("-f", "--follow", action="store_true")
    p.add_argument("--tail", type=int, default=100)
    common(p)
    p.set_defaults(func=cmd_logs)

    p = sub.add_parser("serve", help="serve a model locally")
    p.add_argument("--model")
    p.add_argument("--config")
    p.add_argument("--port", type=int, default=8080)
    p.set_defaults(func=cmd_serve)

    p = sub.add_parser(
        "batchgen",
        help="offline batch generation from a JSONL prompt manifest",
    )
    p.add_argument("--manifest", required=True,
                   help="JSONL prompt manifest (docs/batch-generation.md)")
    p.add_argument("--output", required=True,
                   help="output shard directory (also the resume ledger)")
    p.add_argument("--model", help="checkpoint dir")
    p.add_argument("--config", help="named config for weightless smoke runs")
    p.add_argument("--max-tokens", type=int, default=None)
    p.add_argument("--temperature", type=float, default=None)
    p.add_argument("--no-resume", action="store_true",
                   help="ignore existing output shards")
    p.add_argument("--progress-port", type=int, default=None,
                   help="serve /loadz + /metrics while running")
    p.set_defaults(func=cmd_batchgen)

    p = sub.add_parser(
        "chat", help="interactive chat with a served model"
    )
    p.add_argument("name", nargs="?", help="Server CR name (port-forwards)")
    p.add_argument("--url", help="direct endpoint (e.g. http://localhost:8080)")
    p.add_argument(
        "--model", "--adapter", dest="model", default=None,
        help="model (or LoRA adapter id) to chat with — sent as the "
             "OpenAI `model` field; the gateway routes by it and the "
             "server selects the adapter (multi-tenant serving)",
    )
    p.add_argument("--max-tokens", type=int, default=256)
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument("--system", help="system prompt")
    p.add_argument("--local-port", type=int, default=18080)
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--fake", action="store_true",
                   help="in-process fake cluster (local dev)")
    p.add_argument("--plain", action="store_true",
                   help="uncolored output")
    p.set_defaults(func=cmd_chat)

    p = sub.add_parser(
        "trace",
        help="request-journey waterfall for one trace/request id",
    )
    p.add_argument("id", help="trace id (x-trace-id header) or request id")
    p.add_argument(
        "--url", default="http://localhost:8080",
        help="gateway (or replica) endpoint",
    )
    p.add_argument("--token", help="bearer token for the /debug RBAC gate")
    p.set_defaults(func=cmd_trace)

    p = sub.add_parser(
        "rollout",
        help="zero-downtime rolling weight-swap across a replica fleet",
    )
    p.add_argument(
        "--checkpoint", required=True,
        help="checkpoint ref the replicas should hot-swap to",
    )
    p.add_argument(
        "--version", type=int, default=None,
        help="explicit weights_version (default: first replica names it)",
    )
    p.add_argument(
        "--replicas",
        help="comma-separated replica base URLs (skips fleetz discovery)",
    )
    p.add_argument(
        "--url", default="http://localhost:8080",
        help="gateway endpoint for /debug/fleetz replica discovery",
    )
    p.add_argument("--token", help="bearer token for the /swapz RBAC gate")
    p.set_defaults(func=cmd_rollout)

    p = sub.add_parser("version", help="print version")
    p.set_defaults(func=cmd_version)
