"""Subcommand registration for `sub`. Placeholder registry; real commands
(apply/get/delete/run/notebook/serve) land with the controller + client
subsystems."""
from __future__ import annotations


def register(sub) -> None:
    pass
