"""SCI backends: the cloud side-effect implementations behind the gRPC
service.

  * LocalFSBackend — signed-URL emulation over the local filesystem + a
    plain HTTP PUT handler (reference internal/sci/kind/server.go:27-110):
    the test double that makes the whole control plane runnable on kind or
    in CI with zero cloud credentials.
  * GCSBackend — V4 signed PUT URLs via IAM SignBlob, object MD5 from GCS
    metadata, workload-identity binding via IAM policy edit (reference
    internal/sci/gcp/manager.go:50-144). Requires google-cloud libraries +
    credentials at runtime; import is deferred and failures are explicit.
  * S3Backend — presigned PUT with Content-MD5, ETag-as-MD5, IRSA trust
    policy editing (reference internal/sci/aws/server.go:36-162). Requires
    boto3 at runtime.
"""
from __future__ import annotations

import base64
import hashlib
import http.server
import os
import threading
import urllib.parse
from abc import ABC, abstractmethod
from typing import Optional


class SCIBackend(ABC):
    @abstractmethod
    def create_signed_url(
        self, bucket: str, object_name: str, md5_checksum: str,
        expiration_seconds: int,
    ) -> str: ...

    @abstractmethod
    def get_object_md5(self, bucket: str, object_name: str) -> Optional[str]: ...

    @abstractmethod
    def bind_identity(self, principal: str, namespace: str, name: str) -> None: ...


def split_bucket_url(bucket_url: str) -> tuple:
    """gs://bucket/prefix | s3://bucket/prefix -> (bucket, prefix).

    Bucket URLs may carry a path prefix; every backend must resolve objects
    under it, because the rest of the system (kaniko build context,
    controller addressing) composes `{bucket_url}/{object_path}`."""
    for scheme in ("gs://", "s3://", "local://"):
        if bucket_url.startswith(scheme):
            rest = bucket_url[len(scheme):]
            bucket, _, prefix = rest.partition("/")
            return bucket, prefix.strip("/")
    return bucket_url, ""


def _prefixed(bucket_url: str, object_name: str) -> str:
    _, prefix = split_bucket_url(bucket_url)
    return f"{prefix}/{object_name}" if prefix else object_name


class LocalFSBackend(SCIBackend):
    """Bucket = a directory (`root` IS the bucket; the bucket URL's path is
    resolved against it); signed URL = http://host:port/<object> served by
    an embedded PUT handler that writes the file + an md5 sidecar."""

    def __init__(self, root: str = "/bucket", external_host: str = "localhost",
                 http_port: int = 30080):
        self.root = root
        self.external_host = external_host
        self.http_port = http_port
        self.bound: list = []
        self._http_server: Optional[http.server.ThreadingHTTPServer] = None

    def _path(self, bucket: str, object_name: str) -> str:
        # The PUT handler and md5 lookup must agree on one filesystem root:
        # self.root (deployments point --bucket-root at the bucket dir).
        base = self.root
        full = os.path.normpath(os.path.join(base, object_name))
        if not full.startswith(os.path.normpath(base) + os.sep):
            raise ValueError(f"object path escapes bucket: {object_name!r}")
        return full

    def create_signed_url(self, bucket, object_name, md5_checksum,
                          expiration_seconds) -> str:
        return (
            f"http://{self.external_host}:{self.http_port}/"
            f"{urllib.parse.quote(object_name)}?md5={md5_checksum}"
        )

    def get_object_md5(self, bucket, object_name) -> Optional[str]:
        sidecar = self._path(bucket, object_name) + ".md5"
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                return f.read().strip()
        path = self._path(bucket, object_name)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return hashlib.md5(f.read()).hexdigest()
        return None

    def bind_identity(self, principal, namespace, name) -> None:
        self.bound.append((principal, namespace, name))

    # -- HTTP PUT handler (the "storage" side of the signed URL) -----------

    def start_http(self, port: Optional[int] = None) -> int:
        backend = self
        port = port if port is not None else self.http_port

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_PUT(self):
                length = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(length)
                object_name = urllib.parse.unquote(
                    urllib.parse.urlparse(self.path).path.lstrip("/")
                )
                md5_hex = hashlib.md5(data).hexdigest()
                sent = self.headers.get("Content-MD5")
                if sent:
                    expect = base64.b64encode(
                        hashlib.md5(data).digest()
                    ).decode()
                    if sent != expect:
                        self.send_response(400)
                        self.end_headers()
                        self.wfile.write(b"md5 mismatch")
                        return
                try:
                    path = backend._path(backend.root, object_name)
                except ValueError:
                    self.send_response(400)
                    self.end_headers()
                    return
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "wb") as f:
                    f.write(data)
                with open(path + ".md5", "w") as f:
                    f.write(md5_hex)
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self._http_server = server
        self.http_port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return self.http_port

    def stop_http(self):
        if self._http_server:
            self._http_server.shutdown()


class GCSBackend(SCIBackend):
    """GCS/IAM implementation; requires google-cloud-storage +
    google-api-python-client and ambient credentials."""

    def __init__(self, project_id: Optional[str] = None):
        from google.cloud import storage  # deferred; not in the dev image

        self.project_id = project_id or os.environ.get("PROJECT_ID")
        self.client = storage.Client(project=self.project_id)

    def create_signed_url(self, bucket, object_name, md5_checksum,
                          expiration_seconds) -> str:
        import datetime

        name, _ = split_bucket_url(bucket)
        blob = self.client.bucket(name).blob(_prefixed(bucket, object_name))
        return blob.generate_signed_url(
            version="v4",
            method="PUT",
            expiration=datetime.timedelta(seconds=expiration_seconds),
            content_md5=base64.b64encode(bytes.fromhex(md5_checksum)).decode(),
        )

    def get_object_md5(self, bucket, object_name) -> Optional[str]:
        name, _ = split_bucket_url(bucket)
        blob = self.client.bucket(name).get_blob(
            _prefixed(bucket, object_name)
        )
        if blob is None or blob.md5_hash is None:
            return None
        return base64.b64decode(blob.md5_hash).hex()

    def bind_identity(self, principal, namespace, name) -> None:
        """Grant roles/iam.workloadIdentityUser on the GSA to the KSA
        member (get-modify-set, reference gcp/manager.go:118-144)."""
        import googleapiclient.discovery

        iam = googleapiclient.discovery.build("iam", "v1")
        resource = (
            f"projects/{self.project_id}/serviceAccounts/{principal}"
        )
        member = (
            f"serviceAccount:{self.project_id}.svc.id.goog[{namespace}/{name}]"
        )
        policy = (
            iam.projects()
            .serviceAccounts()
            .getIamPolicy(resource=resource)
            .execute()
        )
        bindings = policy.setdefault("bindings", [])
        for b in bindings:
            if b["role"] == "roles/iam.workloadIdentityUser":
                if member not in b["members"]:
                    b["members"].append(member)
                break
        else:
            bindings.append(
                {
                    "role": "roles/iam.workloadIdentityUser",
                    "members": [member],
                }
            )
        iam.projects().serviceAccounts().setIamPolicy(
            resource=resource, body={"policy": policy}
        ).execute()


class S3Backend(SCIBackend):
    """S3/IRSA implementation; requires boto3 and ambient credentials."""

    def __init__(self, oidc_provider_url: Optional[str] = None):
        import boto3

        self.s3 = boto3.client("s3")
        self.iam = boto3.client("iam")
        self.oidc_provider_url = oidc_provider_url or os.environ.get(
            "OIDC_PROVIDER_URL", ""
        )

    def create_signed_url(self, bucket, object_name, md5_checksum,
                          expiration_seconds) -> str:
        name, _ = split_bucket_url(bucket)
        return self.s3.generate_presigned_url(
            "put_object",
            Params={
                "Bucket": name,
                "Key": _prefixed(bucket, object_name),
                "ContentMD5": base64.b64encode(
                    bytes.fromhex(md5_checksum)
                ).decode(),
            },
            ExpiresIn=expiration_seconds,
        )

    def get_object_md5(self, bucket, object_name) -> Optional[str]:
        import botocore.exceptions

        name, _ = split_bucket_url(bucket)
        try:
            head = self.s3.head_object(
                Bucket=name, Key=_prefixed(bucket, object_name)
            )
        except botocore.exceptions.ClientError:
            return None
        # Single-part uploads: ETag is the hex md5 (reference
        # aws/server.go:36-58).
        return head["ETag"].strip('"')

    def bind_identity(self, principal, namespace, name) -> None:
        """Append the KSA subject to the IAM role's IRSA trust policy
        (reference aws/server.go:88-162)."""
        import json

        role_name = principal.split("/")[-1]
        role = self.iam.get_role(RoleName=role_name)["Role"]
        doc = role["AssumeRolePolicyDocument"]
        sub = f"system:serviceaccount:{namespace}:{name}"
        provider = self.oidc_provider_url.removeprefix("https://")
        for stmt in doc.get("Statement", []):
            cond = stmt.setdefault("Condition", {}).setdefault(
                "StringEquals", {}
            )
            key = f"{provider}:sub"
            subs = cond.get(key)
            if subs is None:
                cond[key] = [sub]
            elif isinstance(subs, list):
                if sub not in subs:
                    subs.append(sub)
            elif subs != sub:
                cond[key] = [subs, sub]
        self.iam.update_assume_role_policy(
            RoleName=role_name, PolicyDocument=json.dumps(doc)
        )
