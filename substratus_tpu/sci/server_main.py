"""SCI server entrypoints (reference: cmd/sci-{gcp,aws,kind}/main.go).

    python -m substratus_tpu.sci.server_main --backend local [--port 10080]
    python -m substratus_tpu.sci.server_main --backend gcs
    python -m substratus_tpu.sci.server_main --backend s3

The local backend also starts the HTTP PUT handler that plays the storage
side of signed URLs (reference sci-kind's NodePort 30080,
install/kind/up.sh:6-14).
"""
from __future__ import annotations

import argparse
import logging


def main(argv=None) -> int:
    import os

    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", choices=["local", "gcs", "s3"], default="local")
    ap.add_argument("--port", type=int, default=10080)
    ap.add_argument("--http-port", type=int, default=30080)
    ap.add_argument("--bucket-root", default="/bucket")
    ap.add_argument("--external-host", default="localhost")
    ap.add_argument(
        "--trace-export",
        default=os.environ.get("SUBSTRATUS_TRACE_EXPORT"),
        help="JSONL path; buffered spans (per-RPC sci.server.* spans "
        "included) are appended here on shutdown",
    )
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from substratus_tpu.observability.propagation import context_from_env
    from substratus_tpu.observability.tracing import tracer
    from substratus_tpu.sci import backends
    from substratus_tpu.sci.grpc_transport import serve

    # Whoever spawned this process (operator shell, a launcher Job) may
    # hand down a TRACEPARENT env var; the startup span joins that trace
    # so the JSONL export links back to the spawn.
    with tracer.span(
        "sci.server.start", parent=context_from_env(), backend=args.backend
    ):
        pass
    if args.trace_export:
        import atexit

        atexit.register(tracer.export_jsonl, args.trace_export)

    if args.backend == "local":
        backend = backends.LocalFSBackend(
            root=args.bucket_root,
            external_host=args.external_host,
            http_port=args.http_port,
        )
        backend.start_http()
        logging.info("local storage HTTP PUT handler on :%d", backend.http_port)
    elif args.backend == "gcs":
        backend = backends.GCSBackend()
    else:
        backend = backends.S3Backend()

    logging.info("SCI gRPC (%s backend) on :%d", args.backend, args.port)
    serve(backend, port=args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
