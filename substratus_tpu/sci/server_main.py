"""SCI server entrypoints (reference: cmd/sci-{gcp,aws,kind}/main.go).

    python -m substratus_tpu.sci.server_main --backend local [--port 10080]
    python -m substratus_tpu.sci.server_main --backend gcs
    python -m substratus_tpu.sci.server_main --backend s3

The local backend also starts the HTTP PUT handler that plays the storage
side of signed URLs (reference sci-kind's NodePort 30080,
install/kind/up.sh:6-14).
"""
from __future__ import annotations

import argparse
import logging


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", choices=["local", "gcs", "s3"], default="local")
    ap.add_argument("--port", type=int, default=10080)
    ap.add_argument("--http-port", type=int, default=30080)
    ap.add_argument("--bucket-root", default="/bucket")
    ap.add_argument("--external-host", default="localhost")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from substratus_tpu.sci import backends
    from substratus_tpu.sci.grpc_transport import serve

    if args.backend == "local":
        backend = backends.LocalFSBackend(
            root=args.bucket_root,
            external_host=args.external_host,
            http_port=args.http_port,
        )
        backend.start_http()
        logging.info("local storage HTTP PUT handler on :%d", backend.http_port)
    elif args.backend == "gcs":
        backend = backends.GCSBackend()
    else:
        backend = backends.S3Backend()

    logging.info("SCI gRPC (%s backend) on :%d", args.backend, args.port)
    serve(backend, port=args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
