from substratus_tpu.sci.client import SCIClient, FakeSCIClient

__all__ = ["SCIClient", "FakeSCIClient"]
