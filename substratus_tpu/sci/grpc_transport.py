"""gRPC plumbing for the SCI service: hand-rolled stubs over the protoc-
generated messages (no grpcio-tools in the image; the service layer is
~60 lines so a plugin buys nothing).

Server: `serve(backend, port)` exposes any `SCIBackend` (local/gcp/aws) as
the sci.v1.Controller service + standard gRPC health service semantics
(reference cmd/sci-gcp/main.go:87-90).
Client: `GrpcSCIClient` implements sci.client.SCIClient for controllers.
"""
from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from substratus_tpu.observability.propagation import (
    current_traceparent, parse_traceparent,
)
from substratus_tpu.observability.tracing import tracer
from substratus_tpu.sci import sci_pb2 as pb
from substratus_tpu.sci.backends import SCIBackend
from substratus_tpu.sci.client import SCIClient, SignedURL, traced

SERVICE = "sci.v1.Controller"


def _trace_metadata() -> Optional[tuple]:
    """gRPC invocation metadata carrying the active span's traceparent —
    the controller's reconcile trace survives into the SCI server process
    (same W3C value HTTP uses; gRPC metadata keys must be lowercase)."""
    tp = current_traceparent()
    return (("traceparent", tp),) if tp is not None else None


def _split_bucket(bucket_url: str) -> str:
    """gs://bucket/prefix or local:///path -> backend-native bucket name."""
    return bucket_url


class GrpcSCIClient(SCIClient):
    def __init__(self, address: str):
        self.channel = grpc.insecure_channel(address)
        self._signed_url = self.channel.unary_unary(
            f"/{SERVICE}/CreateSignedURL",
            request_serializer=pb.CreateSignedURLRequest.SerializeToString,
            response_deserializer=pb.CreateSignedURLResponse.FromString,
        )
        self._md5 = self.channel.unary_unary(
            f"/{SERVICE}/GetObjectMd5",
            request_serializer=pb.GetObjectMd5Request.SerializeToString,
            response_deserializer=pb.GetObjectMd5Response.FromString,
        )
        self._bind = self.channel.unary_unary(
            f"/{SERVICE}/BindIdentity",
            request_serializer=pb.BindIdentityRequest.SerializeToString,
            response_deserializer=pb.BindIdentityResponse.FromString,
        )

    @traced("CreateSignedURL")
    def create_signed_url(self, bucket_url, object_path, md5_checksum,
                          expiration_seconds=300) -> SignedURL:
        resp = self._signed_url(
            pb.CreateSignedURLRequest(
                bucket_name=_split_bucket(bucket_url),
                object_name=object_path,
                expiration_seconds=expiration_seconds,
                md5_checksum=md5_checksum,
            ),
            metadata=_trace_metadata(),
        )
        return SignedURL(url=resp.url, expiration_seconds=expiration_seconds)

    @traced("GetObjectMd5")
    def get_object_md5(self, bucket_url, object_path) -> Optional[str]:
        resp = self._md5(
            pb.GetObjectMd5Request(
                bucket_name=_split_bucket(bucket_url), object_name=object_path
            ),
            metadata=_trace_metadata(),
        )
        return resp.md5_checksum if resp.exists else None

    @traced("BindIdentity")
    def bind_identity(self, principal, namespace, name) -> None:
        self._bind(
            pb.BindIdentityRequest(
                principal=principal,
                kubernetes_namespace=namespace,
                kubernetes_service_account=name,
            ),
            metadata=_trace_metadata(),
        )


def _server_span(method: str, context):
    """Server-side span for one RPC, parented under the caller's
    traceparent metadata when present (explicit None parent = a fresh
    root trace — the server thread's contextvar is never consulted)."""
    parent = None
    if context is not None:
        try:
            meta = {k: v for k, v in (context.invocation_metadata() or ())}
            parent = parse_traceparent(meta.get("traceparent"))
        except Exception:  # sublint: allow[broad-except]: tracing never fails an RPC; a bad traceparent just starts a fresh root
            parent = None
    return tracer.span(f"sci.server.{method}", parent=parent)


def _handlers(backend: SCIBackend) -> grpc.GenericRpcHandler:
    def create_signed_url(request: pb.CreateSignedURLRequest, context):
        with _server_span("CreateSignedURL", context):
            url = backend.create_signed_url(
                request.bucket_name,
                request.object_name,
                request.md5_checksum,
                request.expiration_seconds or 300,
            )
            return pb.CreateSignedURLResponse(url=url)

    def get_object_md5(request: pb.GetObjectMd5Request, context):
        with _server_span("GetObjectMd5", context):
            md5 = backend.get_object_md5(
                request.bucket_name, request.object_name
            )
            return pb.GetObjectMd5Response(
                md5_checksum=md5 or "", exists=md5 is not None
            )

    def bind_identity(request: pb.BindIdentityRequest, context):
        with _server_span("BindIdentity", context):
            backend.bind_identity(
                request.principal,
                request.kubernetes_namespace,
                request.kubernetes_service_account,
            )
            return pb.BindIdentityResponse()

    return grpc.method_handlers_generic_handler(
        SERVICE,
        {
            "CreateSignedURL": grpc.unary_unary_rpc_method_handler(
                create_signed_url,
                request_deserializer=pb.CreateSignedURLRequest.FromString,
                response_serializer=pb.CreateSignedURLResponse.SerializeToString,
            ),
            "GetObjectMd5": grpc.unary_unary_rpc_method_handler(
                get_object_md5,
                request_deserializer=pb.GetObjectMd5Request.FromString,
                response_serializer=pb.GetObjectMd5Response.SerializeToString,
            ),
            "BindIdentity": grpc.unary_unary_rpc_method_handler(
                bind_identity,
                request_deserializer=pb.BindIdentityRequest.FromString,
                response_serializer=pb.BindIdentityResponse.SerializeToString,
            ),
        },
    )


def serve(backend: SCIBackend, port: int = 10080, block: bool = True):
    """Start the SCI gRPC server; the bound port (useful with port=0) is
    exposed as `server.bound_port`."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((_handlers(backend),))
    server.bound_port = server.add_insecure_port(f"[::]:{port}")
    server.start()
    if block:
        server.wait_for_termination()
    return server
