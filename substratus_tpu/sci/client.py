"""SCI (Substratus Cloud Interface) client surface.

The reference isolates cloud side-effects behind a 3-RPC gRPC service
(internal/sci/sci.proto:6-38): CreateSignedURL, GetObjectMd5, BindIdentity.
Same split here — controllers never talk to cloud storage/IAM directly; they
call an SCI client. Implementations:

  * FakeSCIClient       — returns canned values (reference
                          fake_sci_client.go:9-21), for controller tests;
  * GrpcSCIClient       — sci/grpc_transport.py, dials a real SCI server
                          (sci/server.py serves local-FS; sci/gcp.py GCS/IAM;
                          sci/aws.py S3/IRSA).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Optional


@dataclass
class SignedURL:
    url: str
    expiration_seconds: int = 300


class SCIClient(ABC):
    @abstractmethod
    def create_signed_url(
        self, bucket_url: str, object_path: str, md5_checksum: str,
        expiration_seconds: int = 300,
    ) -> SignedURL: ...

    @abstractmethod
    def get_object_md5(self, bucket_url: str, object_path: str) -> Optional[str]:
        """None when the object does not exist."""

    @abstractmethod
    def bind_identity(self, principal: str, namespace: str, name: str) -> None:
        """Bind a cloud principal to the k8s ServiceAccount ns/name."""


class FakeSCIClient(SCIClient):
    def __init__(self):
        self.bound = []  # (principal, namespace, name)
        self.md5s = {}  # object_path -> md5

    def create_signed_url(self, bucket_url, object_path, md5_checksum,
                          expiration_seconds=300) -> SignedURL:
        return SignedURL(
            url=f"https://signed.invalid/{object_path}?md5={md5_checksum}",
            expiration_seconds=expiration_seconds,
        )

    def get_object_md5(self, bucket_url, object_path) -> Optional[str]:
        return self.md5s.get(object_path)

    def bind_identity(self, principal, namespace, name) -> None:
        self.bound.append((principal, namespace, name))
