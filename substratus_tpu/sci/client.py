"""SCI (Substratus Cloud Interface) client surface.

The reference isolates cloud side-effects behind a 3-RPC gRPC service
(internal/sci/sci.proto:6-38): CreateSignedURL, GetObjectMd5, BindIdentity.
Same split here — controllers never talk to cloud storage/IAM directly; they
call an SCI client. Implementations:

  * FakeSCIClient       — returns canned values (reference
                          fake_sci_client.go:9-21), for controller tests;
  * GrpcSCIClient       — sci/grpc_transport.py, dials a real SCI server
                          (sci/server.py serves local-FS; sci/gcp.py GCS/IAM;
                          sci/aws.py S3/IRSA).
"""
from __future__ import annotations

import functools
import logging
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Optional

from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.observability.tracing import current_trace_id, tracer

log = logging.getLogger(__name__)

METRICS.histogram(
    "substratus_sci_request_seconds",
    "SCI client call latency by RPC method (seconds).",
)
METRICS.describe(
    "substratus_sci_errors_total",
    "SCI client calls that raised, by RPC method.", type="counter",
)


def traced(method: str):
    """Instrument an SCI client call: a `sci.<method>` span (joining the
    caller's trace — reconcile spans show their cloud round-trips) plus the
    shared latency histogram and error counter. Decorates every
    implementation, so controller tests against FakeSCIClient exercise the
    same telemetry path production GrpcSCIClient traffic does."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            t0 = time.perf_counter()
            try:
                with tracer.span(
                    f"sci.{method}", client=type(self).__name__
                ):
                    return fn(self, *args, **kwargs)
            except Exception:
                # Counted and logged with the trace id, then propagated:
                # callers own retry policy, operators own correlation.
                METRICS.inc(
                    "substratus_sci_errors_total", {"method": method}
                )
                log.warning(
                    "sci.%s failed (trace_id=%s)", method,
                    current_trace_id(), exc_info=True,
                )
                raise
            finally:
                METRICS.observe(
                    "substratus_sci_request_seconds",
                    time.perf_counter() - t0,
                    {"method": method},
                )

        return wrapper

    return deco


@dataclass
class SignedURL:
    url: str
    expiration_seconds: int = 300


class SCIClient(ABC):
    @abstractmethod
    def create_signed_url(
        self, bucket_url: str, object_path: str, md5_checksum: str,
        expiration_seconds: int = 300,
    ) -> SignedURL: ...

    @abstractmethod
    def get_object_md5(self, bucket_url: str, object_path: str) -> Optional[str]:
        """None when the object does not exist."""

    @abstractmethod
    def bind_identity(self, principal: str, namespace: str, name: str) -> None:
        """Bind a cloud principal to the k8s ServiceAccount ns/name."""


class FakeSCIClient(SCIClient):
    def __init__(self):
        self.bound = []  # (principal, namespace, name)
        self.md5s = {}  # object_path -> md5

    @traced("CreateSignedURL")
    def create_signed_url(self, bucket_url, object_path, md5_checksum,
                          expiration_seconds=300) -> SignedURL:
        return SignedURL(
            url=f"https://signed.invalid/{object_path}?md5={md5_checksum}",
            expiration_seconds=expiration_seconds,
        )

    @traced("GetObjectMd5")
    def get_object_md5(self, bucket_url, object_path) -> Optional[str]:
        return self.md5s.get(object_path)

    @traced("BindIdentity")
    def bind_identity(self, principal, namespace, name) -> None:
        self.bound.append((principal, namespace, name))
