"""Speculative decoding: a small draft model proposes, the target verifies.

Decode is HBM-bandwidth-bound — each target step streams every weight for
one token. Speculation amortizes that stream: the draft greedily proposes k
tokens (k cheap steps), then ONE target forward scores all k+1 positions;
the longest prefix where the target's greedy choice matches the proposal is
accepted, plus the target's own next token as a bonus. Greedy acceptance
makes the output token-for-token identical to plain target greedy decoding —
speculation is a pure latency/throughput trade, never a quality one.

Cache correctness note: verification writes draft-proposed k/v at positions
beyond the accepted prefix. Those slots are harmless-then-overwritten: the
causal mask (q_positions) never reads a slot beyond the current query
position, and the next round rewrites exactly those positions with the
accepted tokens.

This module is the standalone per-request API (llama-family) and the
numerical reference for acceptance semantics. Production serving uses the
ENGINE-INTEGRATED batched speculation: Engine(..., draft=(cfg, params)) with
EngineConfig.spec_k > 0 (serve/engine.py::_spec_dispatch/_spec_drain — the
pipelined round split with on-device accept-mask chaining and per-stream
adaptive draft length) — same greedy acceptance rule, whole-batch
proposals, paged KV on both models.
"""
from __future__ import annotations

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp

from substratus_tpu.models import llama
from substratus_tpu.models.llama import LlamaConfig, Params


@partial(jax.jit, static_argnames=("cfg", "k"), donate_argnames=("cache",))
def _propose(params, cache, token, pos, cfg, k):
    """Draft k greedy tokens; returns (proposal [k], updated cache)."""

    def step(carry, _):
        cache, token, pos = carry
        logits, cache = llama.forward(
            params, token[:, None], cfg, positions=pos[:, None], cache=cache
        )
        nxt = logits[:, 0].argmax(-1).astype(jnp.int32)
        return (cache, nxt, pos + 1), nxt[0]

    (cache, _, _), proposal = jax.lax.scan(
        step, (cache, token, pos), None, length=k
    )
    return proposal, cache


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _verify(params, cache, tokens, pos0, cfg):
    """One target forward over [last_accepted, d1..dk]; returns the greedy
    choice at every position [k+1] and the updated cache."""
    b, s = 1, tokens.shape[0]
    positions = pos0 + jnp.arange(s, dtype=jnp.int32)[None, :]
    logits, cache = llama.forward(
        params, tokens[None, :], cfg, positions=positions, cache=cache
    )
    return logits[0].argmax(-1).astype(jnp.int32), cache


def speculative_generate(
    target_params: Params,
    target_cfg: LlamaConfig,
    draft_params: Params,
    draft_cfg: LlamaConfig,
    prompt_tokens: List[int],
    max_tokens: int = 64,
    k: int = 4,
    eos_token_id: int = -1,
    cache_len: int = 1024,
) -> Tuple[List[int], dict]:
    """Greedy generation from the target model, accelerated by the draft.

    Returns (tokens, stats) where stats counts target forward passes vs
    tokens produced (the speedup ratio decode would see).
    """
    prompt = jnp.asarray([prompt_tokens], jnp.int32)
    n_prompt = len(prompt_tokens)

    t_cache = llama.init_cache(target_cfg, 1, cache_len)
    d_cache = llama.init_cache(draft_cfg, 1, cache_len)
    t_logits, t_kv = llama.forward(target_params, prompt, target_cfg)
    _, d_kv = llama.forward(draft_params, prompt, draft_cfg)
    from substratus_tpu.ops.kvcache import insert_prefill

    t_cache = insert_prefill(t_cache, t_kv, n_prompt)
    d_cache = insert_prefill(d_cache, d_kv, n_prompt)

    out: List[int] = []
    last = int(t_logits[0, -1].argmax())
    out.append(last)
    pos = n_prompt  # next position to write for both models
    target_passes = 1

    while len(out) < max_tokens and out[-1] != eos_token_id:
        # Verify writes positions pos..pos+step_k; the last slot is
        # cache_len-1, so step_k may reach cache_len - 1 - pos.
        step_k = min(k, max_tokens - len(out), cache_len - 1 - pos)
        if step_k < 1:
            break
        proposal, d_cache = _propose(
            draft_params, d_cache,
            jnp.asarray([last], jnp.int32),
            jnp.asarray([pos], jnp.int32),
            draft_cfg, step_k,
        )
        block = jnp.concatenate(
            [jnp.asarray([last], jnp.int32), proposal]
        )  # [step_k + 1]
        choices, t_cache = _verify(
            target_params, t_cache, block, jnp.asarray([pos], jnp.int32),
            target_cfg,
        )
        target_passes += 1

        proposal_host = [int(x) for x in proposal]
        choices_host = [int(x) for x in choices]
        accepted = 0
        while (
            accepted < step_k
            and proposal_host[accepted] == choices_host[accepted]
        ):
            accepted += 1
        if accepted == step_k:
            # Full acceptance: no bonus token — the draft never wrote the
            # last proposal's kv, so it must be the next round's `last`
            # (both caches then stay hole-free).
            new_tokens = proposal_host
            pos += accepted
        else:
            # Partial: accepted draft tokens + the target's correction.
            new_tokens = proposal_host[:accepted] + [choices_host[accepted]]
            pos += accepted + 1
        for tok in new_tokens:
            out.append(tok)
            if tok == eos_token_id or len(out) >= max_tokens:
                break
        last = out[-1]
        # Stale cache rows beyond `pos` (rejected drafts) are never read:
        # the causal mask stops at the query position and the next round
        # rewrites exactly those slots.

    stats = {
        "tokens": len(out),
        "target_passes": target_passes,
        "tokens_per_target_pass": round(len(out) / max(1, target_passes), 2),
    }
    return out, stats
