"""OpenAI-compatible HTTP server on the container contract.

Contract (reference: docs/container-contract.md:50-56, test/system.sh:73-78):
  * listens on port 8080;
  * `GET /` returns 200 once the model is ready (readiness probe target);
  * `POST /v1/completions` accepts {prompt, max_tokens, temperature, top_p,
    stream} and returns an OpenAI-style completion body.

Also exposes `/v1/chat/completions` (template-joined messages) and
`/v1/models`. The HTTP layer is a thin asyncio shim over the Engine's
thread-safe request queue; all device work stays on the engine thread.
"""
from __future__ import annotations

import asyncio
import json
import logging
import math
import os
import re
import time
import uuid
from typing import Optional

from aiohttp import web

from substratus_tpu.gateway.limiter import deadline_remaining, parse_deadline
from substratus_tpu.gateway.loadreport import HEADER as LOAD_HEADER
from substratus_tpu.gateway.loadreport import LoadReport
from substratus_tpu.observability.events import EVENTS
from substratus_tpu.observability.httpstats import count_http_response
from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.observability.propagation import parse_traceparent
from substratus_tpu.observability.tracing import tracer
from substratus_tpu.serve.adapters import UnknownAdapter
from substratus_tpu.serve.engine import Engine, EngineOverloaded, Request
from substratus_tpu.serve.tokenizer import Tokenizer

# Structured access log: one JSON line per traced request, carrying the
# trace id so log pipelines join lines to span exports
# (docs/observability.md "Joining logs to traces").
access_log = logging.getLogger("substratus.serve.access")

# Scrape-time engine gauges (request-latency histograms live in
# serve/engine.py; the full catalog is docs/observability.md).
for _name, _help in (
    ("substratus_serve_active_slots", "Decode slots currently generating."),
    ("substratus_serve_max_slots", "Configured decode slot count (max_batch)."),
    ("substratus_serve_queue_depth", "Requests waiting for a decode slot."),
    ("substratus_serve_kv_pages_total", "KV pool size in pages (paged layout)."),
    ("substratus_serve_kv_pages_free", "Unallocated KV pages (paged layout)."),
):
    METRICS.describe(_name, _help, type="gauge")
METRICS.describe(
    "substratus_serve_requests_total",
    "Completion requests received.", type="counter",
)


class ServerState:
    def __init__(self, engine: Engine, tokenizer: Tokenizer, model_name: str,
                 authorizer=None, checkpoint_loader=None):
        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        # Checkpoint ref -> param tree ready to install (same family/
        # shape/quantization pipeline the boot path used). POST /swapz
        # needs it; None = the replica cannot hot-swap (endpoint answers
        # 501 so a rollout controller skips it honestly).
        self.checkpoint_loader = checkpoint_loader
        self.ready = True
        # SIGTERM flips this: readiness (`GET /`, `/loadz`) answers 503
        # so the gateway/Service stop routing here, while in-flight
        # streams keep running to the drain deadline (serve_forever).
        self.draining = False
        # The /debug/* plane is gated by the same RBAC check as protected
        # /metrics (observability/authz.py MetricsAuthorizer); None = open
        # (local dev, no kube client to review tokens against).
        self.authorizer = authorizer
        # In-flight request registry for /debug/requestz: request id ->
        # {req, endpoint, trace_id, start}. Mutated only on the event
        # loop (track on submit, untrack when the handler finishes).
        self.inflight: dict = {}

    def track_request(self, req: Request, endpoint: str) -> None:
        ctx = tracer.current_context()
        self.inflight[req.id] = {
            "req": req,
            "endpoint": endpoint,
            "trace_id": ctx.trace_id if ctx is not None else None,
            "start": time.time(),
        }

    def untrack_request(self, req: Request) -> None:
        self.inflight.pop(req.id, None)

    def render_chat(self, messages):
        """Messages -> (prompt, templated) using the MODEL'S chat
        template when the tokenizer carries one (HF apply_chat_template,
        or a GGUF's embedded jinja tokenizer.chat_template) — chat
        checkpoints are trained on their template and degrade badly off
        it. `templated` tells encoding to parse the special tokens the
        template rendered and skip the automatic BOS (the template
        already placed one). Falls back to the generic role-joined
        transcript, loudly when a template EXISTS but fails."""
        tmpl = getattr(self.tokenizer, "apply_chat_template", None)
        if tmpl is not None:
            try:
                rendered = tmpl(messages)
            except Exception:  # sublint: allow[broad-except]: a broken template must not take down the endpoint
                # ...but silence here would serve off-format prompts
                # with no trace, hence the loud log below.
                logging.getLogger(__name__).exception(
                    "chat template failed; using the generic transcript"
                )
                rendered = None
            if rendered is not None:
                return rendered, True
        prompt = "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in messages
        )
        return prompt + "\nassistant:", False

    def encode_prompt(self, prompt: str, templated: bool = False):
        """Prompt -> ids; template-rendered prompts use the tokenizer's
        special-token-aware path (no doubled BOS, control tokens as ids)
        when it has one."""
        if templated:
            enc = getattr(self.tokenizer, "encode_templated", None)
            if enc is not None:
                return enc(prompt)
        return self.tokenizer.encode(prompt)


def _find_stop(text: str, stop) -> Optional[int]:
    """Earliest index of any stop sequence in text, or None. The single
    matching semantic shared by the cancellation trigger and the final
    truncation."""
    cuts = [idx for s in stop or [] if s and (idx := text.find(s)) != -1]
    return min(cuts) if cuts else None


async def _collect(req: Request, tokenizer=None, stop=None) -> list[int]:
    """Await all tokens of a request without blocking the event loop.

    With `stop` sequences, a bounded tail of the accumulating text is
    checked per token (O(n), not O(n^2)); on a match the engine request is
    cancelled so its slot frees immediately instead of decoding to
    max_tokens."""
    loop = asyncio.get_running_loop()
    out: list[int] = []
    # A match must end at the newest token; decoding the last
    # 4*max_stop_len+8 tokens always covers it (>=1 byte per token, <=4
    # bytes per char).
    window = 4 * max((len(s) for s in stop), default=0) + 8 if stop else 0
    while True:
        tok = await loop.run_in_executor(None, req.out.get)
        if tok is None:
            return out
        out.append(tok)
        if stop and tokenizer is not None:
            tail = tokenizer.decode(out[-window:])
            if _find_stop(tail, stop) is not None and _find_stop(
                tokenizer.decode(out), stop
            ) is not None:
                # The tail decode is a cheap filter; BPE boundary effects
                # (leading-space stripping) can make it differ from the
                # suffix of the full decode, so confirm on the full text
                # before cancelling — a false positive would silently
                # truncate output while reporting finish_reason "stop".
                req.cancelled = True
                while (
                    await loop.run_in_executor(None, req.out.get) is not None
                ):
                    pass
                return out


_TRACED_PREFIXES = ("/v1/", "/debug/")


@web.middleware
async def trace_middleware(request: web.Request, handler):
    """Distributed-tracing boundary for the serving plane.

    Parses the W3C `traceparent` request header (CLI and upstream proxies
    inject it) and wraps the handler in a `serve.http` span parented under
    the remote context — so one trace id survives CLI -> server -> engine.
    The trace id is echoed as an `x-trace-id` response header (streamed
    responses stamp it before prepare, see _stream), stamped into every
    error payload, and logged as a structured access line. Probe and
    scrape paths (`/`, `/metrics`) stay untraced — a 5 s Prometheus
    scrape interval would otherwise dominate the span ring — but every
    path, traced or not, bumps substratus_http_requests_total (the
    shed-rate denominator shared with the gateway) and /v1/ responses
    carry the x-substratus-load report header."""
    if not request.path.startswith(_TRACED_PREFIXES):
        try:
            resp = await handler(request)
        except web.HTTPException as e:
            count_http_response(request.path, e.status)
            raise
        count_http_response(request.path, resp.status)
        return resp
    remote = parse_traceparent(request.headers.get("traceparent"))
    span = tracer.span(
        "serve.http", parent=remote,
        method=request.method, path=request.path,
    )
    t0 = time.perf_counter()
    status = 500
    try:
        with span:
            try:
                resp = await handler(request)
            except web.HTTPException as e:
                # aiohttp error responses ARE responses; stamp the trace
                # id so the client can quote it back.
                status = e.status
                span.set_attribute("http_status", e.status)
                e.headers["x-trace-id"] = span.trace_id
                raise
            except Exception as e:  # sublint: allow[broad-except]: last-resort handler — a JSON 500 with the trace id beats an opaque text 500
                logging.getLogger(__name__).exception(
                    "unhandled error serving %s", request.path
                )
                span.set_attribute("http_status", 500)
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}",
                     "trace_id": span.trace_id},
                    status=500, headers={"x-trace-id": span.trace_id},
                )
            status = resp.status
            span.set_attribute("http_status", status)
            if not resp.prepared:
                resp.headers["x-trace-id"] = span.trace_id
                state = request.app.get("state")
                if state is not None and request.path.startswith("/v1/"):
                    # Passive load reporting: the gateway learns this
                    # replica's load from the responses it already gets
                    # (streamed responses stamp it in _stream).
                    resp.headers[LOAD_HEADER] = LoadReport.from_snapshot(
                        state.engine.load_snapshot()
                    ).to_header()
            return resp
    finally:
        count_http_response(request.path, status)
        access_log.info(
            json.dumps(
                {
                    "event": "http_request",
                    "method": request.method,
                    "path": request.path,
                    "status": status,
                    "duration_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3
                    ),
                    "trace_id": span.trace_id,
                    "span_id": span.span_id,
                },
                separators=(",", ":"),
            )
        )


def _completion_body(state: ServerState, text: str, n_prompt: int,
                     n_gen: int, finish_reason: str = "stop",
                     model: Optional[str] = None):
    return {
        "id": f"cmpl-{uuid.uuid4().hex[:24]}",
        "object": "text_completion",
        "created": int(time.time()),
        # Echo the tenant the request named (OpenAI semantics); the
        # base model's name when none was given.
        "model": model or state.model_name,
        "choices": [
            {
                "index": 0,
                "text": text,
                "finish_reason": finish_reason,
                "logprobs": None,
            }
        ],
        "usage": {
            "prompt_tokens": n_prompt,
            "completion_tokens": n_gen,
            "total_tokens": n_prompt + n_gen,
        },
    }


def build_app(state: ServerState) -> web.Application:
    routes = web.RouteTableDef()

    @routes.get("/")
    async def root(request: web.Request) -> web.Response:
        if state.engine.error is not None:
            return web.Response(status=500, text=str(state.engine.error))
        if state.draining:
            return web.Response(status=503, text="draining")
        return web.Response(status=200 if state.ready else 503, text="ok")

    @routes.get("/loadz")
    async def loadz(request: web.Request) -> web.Response:
        """The load-report endpoint of the gateway protocol (gateway/
        loadreport.py): engine queue/slot/KV counters plus readiness.
        Answers 503 while draining — the gateway's poller treats any
        non-200 as 'stop routing here' without ejecting, which is
        exactly the graceful-shutdown contract."""
        snap = state.engine.load_snapshot()
        snap["model"] = state.model_name
        snap["draining"] = state.draining
        if state.engine.error is not None:
            return web.json_response(
                {**snap, "error": str(state.engine.error)}, status=500
            )
        status = 200 if (state.ready and not state.draining) else 503
        return web.json_response(snap, status=status)

    async def _authorize_debug(request: web.Request) -> None:
        """Gate a /debug/* route with the metrics RBAC check (TokenReview +
        SubjectAccessReview through state.authorizer); open when no
        authorizer is configured (local dev)."""
        if state.authorizer is None:
            return
        loop = asyncio.get_running_loop()
        status, reason = await loop.run_in_executor(
            None, state.authorizer.allow,
            request.headers.get("Authorization"),
        )
        if status == 200:
            return
        if status == 401:
            raise web.HTTPUnauthorized(
                text=reason, headers={"WWW-Authenticate": "Bearer"}
            )
        if status == 403:
            raise web.HTTPForbidden(text=reason)
        raise web.HTTPInternalServerError(text=reason)

    swap_lock = asyncio.Lock()

    @routes.post("/swapz")
    async def swapz(request: web.Request) -> web.Response:
        """Hot weight-swap: load the named checkpoint ref and install it
        on the live engine via Engine.swap_params — no drain, no engine
        teardown, compiled programs kept (docs/serving.md "Zero-downtime
        rollout"). Body: {"checkpoint": ref, "version": optional int,
        "source": "swap"|"rollout"}. Gated by the same RBAC check as the
        /debug plane: swapping weights is strictly more powerful than
        reading debug state."""
        await _authorize_debug(request)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(text="invalid JSON body")
        ref = body.get("checkpoint")
        if not ref or not isinstance(ref, str):
            raise web.HTTPBadRequest(text="missing 'checkpoint'")
        source = str(body.get("source", "swap"))
        if source not in ("swap", "rollout"):
            raise web.HTTPBadRequest(
                text="'source' must be 'swap' or 'rollout'"
            )
        version = body.get("version")
        if version is not None:
            try:
                version = int(version)
            except (TypeError, ValueError):
                raise web.HTTPBadRequest(text="'version' must be an integer")
        if state.checkpoint_loader is None:
            raise web.HTTPNotImplemented(
                text=json.dumps({"error": {
                    "message": "this replica has no checkpoint loader "
                               "configured; hot swap is unavailable",
                    "type": "swap_unavailable",
                }}),
                content_type="application/json",
            )
        loop = asyncio.get_running_loop()
        # One swap at a time per replica: concurrent loads would race on
        # version ordering and double the peak host memory for no benefit.
        async with swap_lock:
            try:
                params = await loop.run_in_executor(
                    None, state.checkpoint_loader, ref
                )
                applied = await loop.run_in_executor(
                    None,
                    lambda: state.engine.swap_params(
                        params, version=version, source=source
                    ),
                )
            except ValueError as e:
                # Shape/dtype/tree mismatch — the engine rejected the
                # swap and kept serving the old weights (409: the request
                # conflicts with the live model's structure).
                raise web.HTTPConflict(
                    text=json.dumps({"error": {
                        "message": str(e), "type": "swap_rejected",
                    }}),
                    content_type="application/json",
                )
            except FileNotFoundError as e:
                raise web.HTTPBadRequest(
                    text=json.dumps({"error": {
                        "message": str(e), "type": "checkpoint_not_found",
                    }}),
                    content_type="application/json",
                )
        return web.json_response(
            {"weights_version": applied, "checkpoint": ref,
             "source": source}
        )

    profile_lock = asyncio.Lock()
    # On-demand capture state: {"dir", "started", "task"} while a
    # start/stop capture is live, else empty.
    profile_state: dict = {}
    PROFILE_CAP_S = 60.0

    def _profiler():
        """The JAX profiler module, or None (no-op fallback: serving
        builds without a working profiler still answer the endpoint)."""
        try:
            import jax

            jax.profiler.start_trace  # attribute probe
            return jax.profiler
        except Exception:  # sublint: allow[broad-except]: any import/attr failure means the profiler is absent; endpoint answers no-op
            return None

    def _profile_dir() -> str:
        base = os.environ.get("PROFILE_DIR", "/tmp/substratus-profile")
        return os.path.join(base, time.strftime("%Y%m%d-%H%M%S"))

    def _stop_capture(prof) -> dict:
        """Stop the live capture; returns its summary (caller holds the
        invariants: profile_state non-empty, prof available)."""
        info = dict(profile_state)
        profile_state.clear()
        task = info.pop("task", None)
        if task is not None:
            task.cancel()
        try:
            prof.stop_trace()
        except Exception as e:  # sublint: allow[broad-except]: a capture that failed to start must still be clearable; error surfaces in the response
            info["stop_error"] = str(e)
        elapsed = round(time.perf_counter() - info.pop("t0"), 3)
        with tracer.span(
            "serve.profile", mode="capture", dir=info.get("dir", ""),
        ) as span:
            span.set_attribute("seconds", elapsed)
        EVENTS.emit(
            "ProfileCaptureStopped", kind="Server", name=state.model_name,
            message=f"device trace in {info.get('dir', '')}",
        )
        info["seconds"] = elapsed
        return info

    @routes.post("/debug/profile")
    async def profile(request: web.Request) -> web.Response:
        """Capture a JAX/XLA device trace while serving traffic (SURVEY.md
        §5: the reference had no profiling story; here it is an endpoint).

        Two modes, both writing TensorBoard-format traces under a fixed
        base dir (PROFILE_DIR env overrides; never caller-controlled):

          * {"seconds": N (0 < N <= 60)} — blocking capture of N seconds;
          * {"action": "start"} / {"action": "stop"} — on-demand capture
            bracketing exactly the traffic you care about, with a 60 s
            watchdog cap so a forgotten "stop" can't profile forever.

        Every capture records a `serve.profile` span and a
        ProfileCapture* event. Without a working profiler the endpoint
        answers {"profiler": "unavailable"} instead of failing."""
        await _authorize_debug(request)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        if not isinstance(body, dict):
            raise web.HTTPBadRequest(text="body must be a JSON object")
        prof = _profiler()
        action = body.get("action")
        if action not in (None, "start", "stop"):
            raise web.HTTPBadRequest(text="'action' must be start or stop")

        if action == "stop":
            if not profile_state:
                raise web.HTTPConflict(text="no profile capture is running")
            if prof is None:  # started state can't exist without a profiler
                profile_state.clear()
                return web.json_response({"profiler": "unavailable"})
            return web.json_response({"stopped": True, **_stop_capture(prof)})

        if action == "start":
            if profile_state or profile_lock.locked():
                raise web.HTTPConflict(
                    text="a profile capture is already running"
                )
            if prof is None:
                return web.json_response(
                    {"profiler": "unavailable", "started": False}
                )
            out_dir = _profile_dir()
            try:
                prof.start_trace(out_dir)
            except Exception as e:  # sublint: allow[broad-except]: profiler backends raise anything; converted to a 500 with the message
                raise web.HTTPInternalServerError(
                    text=f"profiler failed to start: {e}"
                )
            EVENTS.emit(
                "ProfileCaptureStarted", kind="Server",
                name=state.model_name, message=f"device trace to {out_dir}",
            )

            async def watchdog():
                await asyncio.sleep(PROFILE_CAP_S)
                if profile_state:
                    _stop_capture(prof)

            profile_state.update(
                {"dir": out_dir, "t0": time.perf_counter(),
                 "task": asyncio.get_running_loop().create_task(watchdog())}
            )
            return web.json_response(
                {"started": True, "dir": out_dir,
                 "cap_seconds": PROFILE_CAP_S}
            )

        # Blocking mode: {"seconds": N}.
        try:
            seconds = float(body.get("seconds", 3))
        except (TypeError, ValueError):
            raise web.HTTPBadRequest(text="'seconds' must be a number")
        if not (0 < seconds <= 60):
            raise web.HTTPBadRequest(text="'seconds' must be in (0, 60]")

        out_dir = _profile_dir()
        if profile_lock.locked() or profile_state:
            raise web.HTTPConflict(text="a profile capture is already running")
        if prof is None:
            return web.json_response(
                {"profiler": "unavailable", "dir": out_dir, "files": []}
            )
        async with profile_lock:
            loop = asyncio.get_running_loop()

            def capture():
                with tracer.span(
                    "serve.profile", mode="blocking", dir=out_dir,
                    seconds=seconds,
                ):
                    prof.start_trace(out_dir)
                    try:
                        time.sleep(seconds)
                    finally:
                        prof.stop_trace()

            await loop.run_in_executor(None, capture)
        EVENTS.emit(
            "ProfileCaptureStopped", kind="Server", name=state.model_name,
            message=f"device trace in {out_dir}",
        )
        files = []
        for root, _, names in os.walk(out_dir):
            files.extend(os.path.join(root, n) for n in names)
        return web.json_response(
            {"dir": out_dir, "seconds": seconds, "files": sorted(files)[-10:]}
        )

    @routes.get("/debug/tracez")
    async def tracez(request: web.Request) -> web.Response:
        """Flight recorder: recent traces from the span ring, grouped by
        root span and latency-bucketed — the 'what has the server been
        doing' page, no collector required."""
        await _authorize_debug(request)
        spans = tracer.finished()
        by_trace: dict = {}
        for s in spans:
            by_trace.setdefault(s["trace_id"], []).append(s)
        buckets = (0.01, 0.1, 1.0)  # seconds; final bucket is +Inf

        def bucket_label(duration_us: int) -> str:
            sec = duration_us / 1e6
            for b in buckets:
                if sec <= b:
                    return f"le_{b}s"
            return "gt_1s"

        traces = []
        by_root: dict = {}
        for tid, ss in by_trace.items():
            ids = {s["span_id"] for s in ss}
            # Root = no parent, or a parent outside the buffer (remote
            # caller / ring-evicted ancestor).
            root = next(
                (s for s in ss
                 if not s["parent_id"] or s["parent_id"] not in ids),
                ss[0],
            )
            errors = [s["status"] for s in ss if s["status"] != "ok"]
            traces.append(
                {
                    "trace_id": tid,
                    "root": root["name"],
                    "start_us": root["start_us"],
                    "duration_us": root["duration_us"],
                    "spans": len(ss),
                    "status": errors[0] if errors else "ok",
                }
            )
            hist = by_root.setdefault(
                root["name"],
                {f"le_{b}s": 0 for b in buckets} | {"gt_1s": 0},
            )
            hist[bucket_label(root["duration_us"])] += 1
        traces.sort(key=lambda t: t["start_us"], reverse=True)
        return web.json_response(
            {
                "traces": traces[:100],
                "latency_buckets": by_root,
                "buffered_spans": len(spans),
                "dropped_spans": tracer.dropped,
            }
        )

    @routes.get("/debug/requestz")
    async def requestz(request: web.Request) -> web.Response:
        """In-flight completion requests: age, where each one is in the
        engine (decoding slot / queue position), tokens emitted so far.

        With ?id=<trace id or request id> the page upgrades to the full
        request journey (observability/journey.py) — the stitched event
        timeline plus a Chrome-trace rendering (save "chrome_trace" and
        load it in chrome://tracing / Perfetto). Lookup order: live
        in-flight requests first, then the engine's completed-journey
        ring, then the slow ring."""
        await _authorize_debug(request)
        eng = state.engine
        wanted = request.query.get("id")
        if wanted:
            from substratus_tpu.observability.journey import (
                chrome_trace,
                waterfall,
            )

            snap = None
            for info in list(state.inflight.values()):
                j = getattr(info["req"], "journey", None)
                if j is not None and wanted in (j.trace_id, info["req"].id):
                    snap = j.snapshot()
                    break
            if snap is None:
                snap = eng.journey_log.find(wanted)
            if snap is None:
                for entry in eng.slow.snapshot():
                    if wanted in (entry.get("trace_id"), entry.get("rid")):
                        snap = entry.get("journey")
                        break
            if snap is None:
                raise web.HTTPNotFound(
                    text=f"no journey for id {wanted!r}"
                )
            return web.json_response({
                "journey": snap,
                "waterfall": waterfall(snap),
                "chrome_trace": chrome_trace(snap),
            })
        now = time.time()
        # Snapshots; the scheduler thread mutates these concurrently and
        # a debug page may be slightly stale, never wrong-by-crash.
        slot_req = list(eng.slot_req)
        queued = list(getattr(eng.queue, "queue", ()))
        rows = []
        for info in list(state.inflight.values()):
            req = info["req"]
            slot = next(
                (i for i, r in enumerate(slot_req) if r is req), None
            )
            if slot is not None:
                where = "decoding"
                tokens = eng.slot_generated[slot]
                queue_position = None
            else:
                pos = next(
                    (i for i, r in enumerate(queued) if r is req), None
                )
                where = "queued" if pos is not None else "pending"
                tokens = 0
                queue_position = pos
            rows.append(
                {
                    "request_id": req.id,
                    "endpoint": info["endpoint"],
                    "trace_id": info["trace_id"],
                    "age_s": round(now - info["start"], 3),
                    "state": where,
                    "slot": slot,
                    "queue_position": queue_position,
                    "prompt_tokens": len(req.prompt_tokens),
                    "max_tokens": req.max_tokens,
                    "tokens_emitted": tokens,
                }
            )
        rows.sort(key=lambda r: r["age_s"], reverse=True)
        return web.json_response(
            {
                "inflight": rows,
                "queue_depth": eng.queue.qsize(),
                # Completed journeys retrievable via ?id= (newest last).
                "journeys": eng.journey_log.ids(),
            }
        )

    @routes.get("/debug/perfz")
    async def perfz(request: web.Request) -> web.Response:
        """Performance flight recorder: the scheduler's phase-level
        timing breakdown (admission / broadcast / prefill / decode /
        sample), first-compile duration, request-latency quantiles, and
        the engine's live counters — the 'where does an iteration's time
        go' page, rendered from the shared registry with no scrape
        pipeline required. Phases NEST (admission contains prefill
        contains sample): they time named sections, not a partition."""
        await _authorize_debug(request)
        from substratus_tpu.observability.metrics import (
            quantile_from_buckets,
        )

        _phase_re = re.compile(r'^phase="(.*)"$')

        def family(name: str, key_label: str = "") -> dict:
            out = {}
            for ls, s in METRICS.histogram_series(name).items():
                m = _phase_re.match(ls) if ls else None
                key = m.group(1) if m else (ls or "all")
                out[key] = {
                    "count": s["count"],
                    "sum_s": round(s["sum"], 6),
                    "mean_s": (
                        round(s["sum"] / s["count"], 6) if s["count"] else None
                    ),
                    **{
                        f"p{int(q * 100)}_s": (
                            None
                            if (v := quantile_from_buckets(s["buckets"], q))
                            is None
                            else round(v, 6)
                        )
                        for q in (0.5, 0.9, 0.99)
                    },
                }
            return out

        eng = state.engine
        return web.json_response(
            {
                "phases": family("substratus_serve_phase_seconds"),
                "first_compile_seconds": METRICS.get(
                    "substratus_serve_first_compile_seconds"
                ),
                "latencies": {
                    short: family(f"substratus_serve_{short}_seconds")
                    for short in ("ttft", "inter_token", "queue_wait")
                },
                "occupancy": family("substratus_serve_batch_occupancy_ratio"),
                "train_phases": family("substratus_train_phase_seconds"),
                "engine": {
                    "active_slots": int(eng.active.sum()),
                    "max_slots": eng.ec.max_batch,
                    "queue_depth": eng.queue.qsize(),
                    "kv_layout": "paged" if eng.paged else "dense",
                    "stats": dict(eng.stats),
                },
            }
        )

    @routes.get("/debug/stepz")
    async def stepz(request: web.Request) -> web.Response:
        """Engine step timeline as Chrome-trace JSON (observability/
        timeline.py): one span per scheduler iteration with admission/
        drain/flush sub-spans and per-cause pipeline-bubble attribution
        in the args — save the body and load it in chrome://tracing or
        Perfetto. `otherData` carries the lifetime bubble totals
        (substratus_serve_pipeline_bubble_seconds mirrors them as
        counters) and the floor estimate. Same RBAC gate as the rest
        of the /debug plane."""
        await _authorize_debug(request)
        tl = state.engine.timeline
        body = tl.chrome_trace()
        body["otherData"]["bubble"] = tl.bubble_totals()
        floor = tl.floor_estimate()
        body["otherData"]["floor_estimate_s"] = (
            round(floor, 6) if floor is not None else None
        )
        body["otherData"]["configured_step_floor_s"] = (
            state.engine.ec.step_floor_s
        )
        return web.json_response(body)

    @routes.get("/debug/slowz")
    async def slowz(request: web.Request) -> web.Response:
        """Slow-request exemplars: the bounded ring of SLO-breaching
        journeys (observability/journey.py SlowRing) plus the per-bucket
        exemplar trace ids attached to the TTFT / inter-token latency
        histograms — a dashboard can jump from a p99 bucket straight to
        the offending journey via /debug/requestz?id=<trace_id>. Same
        RBAC gate as the rest of the /debug plane."""
        await _authorize_debug(request)
        eng = state.engine
        return web.json_response({
            "slow": eng.slow.snapshot(),
            "total_breaching": eng.slow.total,
            "slo": eng.slo.snapshot(),
            "exemplars": {
                short: METRICS.exemplars(
                    f"substratus_serve_{short}_seconds"
                )
                for short in ("ttft", "inter_token")
            },
        })

    @routes.get("/debug/eventz")
    async def eventz(request: web.Request) -> web.Response:
        """Recent events from the shared recorder (count-deduped, newest
        first) — reconcile transitions when a controller shares the
        process, profile captures, anything emitted through EVENTS."""
        await _authorize_debug(request)
        return web.json_response(
            {"events": EVENTS.recent(100), "dropped": EVENTS.dropped}
        )

    @routes.get("/metrics")
    async def metrics(request: web.Request) -> web.Response:
        """Prometheus-format serving metrics: point-in-time engine gauges
        refreshed at scrape, plus everything already in the shared registry
        (latency histograms from serve/engine.py, reconcile counters when a
        controller shares the process). One registry, one exposition."""
        eng = state.engine
        METRICS.set("substratus_serve_active_slots", int(eng.active.sum()))
        METRICS.set("substratus_serve_max_slots", eng.ec.max_batch)
        METRICS.set("substratus_serve_queue_depth", eng.queue.qsize())
        for k, v in eng.stats.items():
            METRICS.set(f"substratus_serve_{k}", v)
        if getattr(eng, "paged", False):
            METRICS.set("substratus_serve_kv_pages_total", eng.n_pages)
            METRICS.set("substratus_serve_kv_pages_free", eng.alloc.free_pages)
        # The versioned content type Prometheus negotiates for (the
        # controller endpoint in observability/health.py already sends it;
        # a bare text/plain leaves the scraper guessing the format version).
        return web.Response(
            body=METRICS.render().encode(),
            headers={"Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
        )

    @routes.get("/v1/models")
    async def models(request: web.Request) -> web.Response:
        data = [
            {
                "id": state.model_name,
                "object": "model",
                "owned_by": "substratus-tpu",
            }
        ]
        if state.engine.adapters is not None:
            # Every servable tenant adapter is a model clients can name
            # in the OpenAI `model` field (loaded or hot-loadable).
            loaded = set(state.engine.adapters.loaded_ids())
            data.extend(
                {
                    "id": aid,
                    "object": "model",
                    "owned_by": "substratus-tpu",
                    "parent": state.model_name,
                    "loaded": aid in loaded,
                }
                for aid in state.engine.adapters.available_ids()
            )
        return web.json_response({"object": "list", "data": data})

    def _validate_body(body: dict) -> None:
        """Reject malformed request knobs BEFORE any engine work happens
        (applies to streaming and non-streaming alike)."""
        stop = body.get("stop")
        if stop is not None and not (
            isinstance(stop, str)
            or (isinstance(stop, list) and all(isinstance(s, str) for s in stop))
        ):
            raise web.HTTPBadRequest(
                text="'stop' must be a string or list of strings"
            )
        if "max_tokens" in body:
            try:
                v = int(body["max_tokens"])
            except (TypeError, ValueError):
                raise web.HTTPBadRequest(text="'max_tokens' must be an integer")
            if v < 1:
                raise web.HTTPBadRequest(text="'max_tokens' must be >= 1")
        for key in ("temperature", "top_p"):
            if key in body:
                try:
                    v = float(body[key])
                except (TypeError, ValueError):
                    raise web.HTTPBadRequest(text=f"'{key}' must be a number")
                if not math.isfinite(v):
                    # json.loads accepts NaN/Infinity literals, and NaN
                    # passes any < comparison — reject explicitly.
                    raise web.HTTPBadRequest(text=f"'{key}' must be finite")
                if key == "temperature" and v < 0:
                    raise web.HTTPBadRequest(text="'temperature' must be >= 0")
                if key == "top_p" and not (0 < v <= 1):
                    raise web.HTTPBadRequest(
                        text="'top_p' must be in (0, 1]"
                    )

    def _check_admission(request: web.Request) -> None:
        """Per-request admission before any engine work: a draining
        server stops taking NEW requests (503 so the caller retries on
        a live replica), and an already-expired deadline is shed as
        504 — decoding for a client that gave up wastes a slot."""
        if state.engine.ec.role == "decode":
            # Disaggregated decode tier (serve/disagg.py): requests
            # arrive as KV migrations over the transfer port, never as
            # client completions. A role-aware gateway never routes
            # here; a misdirected client gets an honest shed.
            raise web.HTTPServiceUnavailable(
                text=json.dumps({"error": {
                    "message": "decode-role replica: completions are "
                               "admitted by the prefill tier",
                    "type": "wrong_role",
                }}),
                content_type="application/json",
                headers={"Retry-After": "1"},
            )
        if state.draining:
            raise web.HTTPServiceUnavailable(
                text=json.dumps({"error": {
                    "message": "server is draining", "type": "draining",
                }}),
                content_type="application/json",
                headers={"Retry-After": "1"},
            )
        remaining = deadline_remaining(parse_deadline(request.headers))
        if remaining is not None and remaining <= 0:
            raise web.HTTPGatewayTimeout(
                text=json.dumps({"error": {
                    "message": "request deadline already expired",
                    "type": "deadline",
                }}),
                content_type="application/json",
            )

    def _resolve_adapter(body: dict) -> Optional[str]:
        """The OpenAI `model` field -> an engine adapter id. The base
        model's own name (or an absent/empty field) means no adapter;
        anything else must be a servable adapter or the request is a
        404 before any engine work."""
        name = body.get("model")
        if not name or name == state.model_name:
            return None
        eng = state.engine
        if eng.adapters is not None and eng.adapters.known(str(name)):
            return str(name)
        raise web.HTTPNotFound(
            text=json.dumps({"error": {
                "message": f"model {name!r} not found",
                "type": "invalid_request_error",
                "code": "model_not_found",
            }}),
            content_type="application/json",
        )

    def _submit(prompt: str, body: dict, endpoint: str,
                templated: bool = False) -> Request:
        tok = state.tokenizer
        req = Request(
            prompt_tokens=state.encode_prompt(prompt, templated),
            max_tokens=int(body.get("max_tokens", 16)),
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            eos_token_id=tok.eos_id,
            adapter=_resolve_adapter(body),
            id=uuid.uuid4().hex,
        )
        state.track_request(req, endpoint)
        try:
            return state.engine.submit(req)
        except UnknownAdapter as e:
            # The artifact vanished between the known() check and
            # submit — same client-visible contract as _resolve_adapter.
            state.untrack_request(req)
            raise web.HTTPNotFound(
                text=json.dumps({"error": {
                    "message": str(e), "type": "invalid_request_error",
                    "code": "model_not_found",
                }}),
                content_type="application/json",
            )
        except EngineOverloaded as e:
            state.untrack_request(req)
            # Bounded queue -> explicit shed: 429 + Retry-After beats
            # admitting into a queue whose wait exceeds any deadline.
            raise web.HTTPTooManyRequests(
                text=json.dumps({"error": {
                    "message": str(e), "type": "overloaded",
                }}),
                content_type="application/json",
                headers={
                    "Retry-After": str(max(1, int(e.retry_after + 0.999)))
                },
            )

    async def _generate(request: web.Request, prompt: str, body: dict,
                        templated: bool = False):
        req = _submit(prompt, body, request.path, templated)
        try:
            stop = body.get("stop")
            if isinstance(stop, str):
                stop = [stop]
            gen_ids = await _collect(req, state.tokenizer, stop)
        finally:
            state.untrack_request(req)
        if state.engine.error is not None:
            raise web.HTTPInternalServerError(text=str(state.engine.error))
        text = state.tokenizer.decode(gen_ids)
        # OpenAI `stop`: truncate at the earliest stop sequence (exclusive),
        # computed over the full text so the result is order-independent.
        # _collect already cancelled the engine slot when the match appeared
        # (non-streaming only; streamed responses don't hold tokens back).
        if stop is not None:
            cut = _find_stop(text, stop)
            if cut is not None:
                return text[:cut], len(req.prompt_tokens), len(gen_ids), "stop"
        # The engine recorded why generation ended (eos vs budget/window).
        return text, len(req.prompt_tokens), len(gen_ids), req.finish_reason

    async def _stream(
        request: web.Request, prompt: str, body: dict, chat: bool,
        templated: bool = False,
    ) -> web.StreamResponse:
        """OpenAI-style SSE streaming: one data: chunk per decoded token,
        then [DONE]. The engine already streams per-token through the
        request queue; this just relays it."""
        req = _submit(prompt, body, request.path, templated)
        if state.engine.error is not None:
            state.untrack_request(req)
            raise web.HTTPInternalServerError(text=str(state.engine.error))
        stop = body.get("stop")
        if isinstance(stop, str):
            stop = [stop]
        headers = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            # Load report at stream START: by the time it ends the
            # snapshot would be stale anyway; the gateway treats it as
            # one more passive sample.
            LOAD_HEADER: LoadReport.from_snapshot(
                state.engine.load_snapshot()
            ).to_header(),
        }
        # SSE headers go out at prepare(), before the middleware sees the
        # response — stamp the trace id here (same id the middleware span
        # carries: we're inside it).
        ctx = tracer.current_context()
        if ctx is not None:
            headers["x-trace-id"] = ctx.trace_id
        resp = web.StreamResponse(headers=headers)
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        created = int(time.time())
        cid = f"cmpl-{uuid.uuid4().hex[:24]}"
        resp_model = str(body.get("model") or state.model_name)

        async def write_piece(piece: str, finish=None):
            if chat:
                delta = {"content": piece} if piece else {}
                choice = {"index": 0, "delta": delta, "finish_reason": finish}
                obj = "chat.completion.chunk"
            else:
                choice = {"index": 0, "text": piece, "finish_reason": finish}
                obj = "text_completion"
            chunk = {
                "id": cid,
                "object": obj,
                "created": created,
                "model": resp_model,
                "choices": [choice],
            }
            await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())

        # Stop handling mirrors OpenAI semantics on the streamed path too:
        # never emit the stop sequence or anything after it. Matching runs
        # on the FULL decode of all generated tokens — concatenating
        # per-token decodes diverges from it under BPE boundary effects
        # (leading-space stripping), which would make streamed truncation
        # disagree with the non-streaming path. The full re-decode per
        # token is O(n^2) in characters, accepted on this host-side path.
        # A match can span chunk boundaries, so when stop sequences exist
        # the stream holds back the last max(len(stop))-1 chars until more
        # text (or the end) proves they're not a prefix of a match.
        max_stop = max((len(s) for s in stop), default=0) if stop else 0
        holdback = max(0, max_stop - 1)
        tokens: list[int] = []
        sent = 0  # chars already streamed
        finish_reason: Optional[str] = None
        async def pump():
            """Relay tokens until the request finishes (split out so
            untracking can't be skipped by any of the loop's exits)."""
            nonlocal sent, finish_reason
            while True:
                tok_id = await loop.run_in_executor(None, req.out.get)
                if tok_id is None:
                    full = state.tokenizer.decode(tokens)
                    if stop and (cut := _find_stop(full, stop)) is not None:
                        full, finish_reason = full[:cut], "stop"
                    else:
                        # The engine reports "error" on the request itself
                        # when its thread died mid-stream — the committed
                        # 200 stream then ends honestly instead of
                        # fabricating "stop".
                        finish_reason = req.finish_reason
                    if len(full) > sent:
                        await write_piece(full[sent:])
                    return
                tokens.append(tok_id)
                full = state.tokenizer.decode(tokens)
                if stop:
                    # A new match must end inside the unsent tail (plus the
                    # holdback window) — search only there.
                    base = max(0, sent - holdback)
                    cut = _find_stop(full[base:], stop)
                    if cut is not None:
                        cut += base
                        if cut > sent:
                            await write_piece(full[sent:cut])
                            sent = cut
                        req.cancelled = True
                        while (
                            await loop.run_in_executor(None, req.out.get)
                            is not None
                        ):
                            pass
                        finish_reason = "stop"
                        return
                # Hold back the stop window plus any trailing partial UTF-8
                # codepoint (<= 3 replacement chars; a longer run is
                # genuinely invalid output and streams as-is).
                emit_to = len(full) - holdback
                trail = 0
                while (
                    trail < 3
                    and emit_to - 1 - trail >= 0
                    and full[emit_to - 1 - trail] == "�"
                ):
                    trail += 1
                emit_to -= trail if trail < 3 else 0
                if emit_to > sent:
                    await write_piece(full[sent:emit_to])
                    sent = emit_to

        try:
            await pump()
        finally:
            state.untrack_request(req)
        await write_piece("", finish_reason)
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    @routes.post("/v1/completions")
    async def completions(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(text="invalid JSON body")
        prompt = body.get("prompt")
        if prompt is None:
            raise web.HTTPBadRequest(text="missing 'prompt'")
        _validate_body(body)
        _check_admission(request)
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        METRICS.inc("substratus_serve_requests_total")
        with tracer.span(
            "serve.completion", endpoint="/v1/completions",
            stream=bool(body.get("stream")),
        ) as span:
            if body.get("stream"):
                return await _stream(request, str(prompt), body, chat=False)
            text, n_prompt, n_gen, finish = await _generate(
                request, str(prompt), body
            )
            span.set_attribute("prompt_tokens", n_prompt)
            span.set_attribute("completion_tokens", n_gen)
            span.set_attribute("finish_reason", finish)
        return web.json_response(
            _completion_body(state, text, n_prompt, n_gen, finish,
                             model=body.get("model"))
        )

    @routes.post("/v1/chat/completions")
    async def chat(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(text="invalid JSON body")
        _validate_body(body)
        _check_admission(request)
        messages = body.get("messages") or []
        prompt, templated = state.render_chat(messages)
        METRICS.inc("substratus_serve_requests_total")
        with tracer.span(
            "serve.completion", endpoint="/v1/chat/completions",
            stream=bool(body.get("stream")), messages=len(messages),
        ):
            if body.get("stream"):
                return await _stream(
                    request, prompt, body, chat=True, templated=templated
                )
            text, n_prompt, n_gen, finish = await _generate(
                request, prompt, body, templated
            )
        resp = _completion_body(state, text, n_prompt, n_gen, finish,
                                model=body.get("model"))
        resp["object"] = "chat.completion"
        resp["choices"] = [
            {
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish,
            }
        ]
        return web.json_response(resp)

    app = web.Application(middlewares=[trace_middleware])
    app["state"] = state  # middleware reads it for the load header
    app.add_routes(routes)
    return app


async def drain(state: ServerState, grace_s: float = 30.0,
                poll_s: float = 0.1) -> bool:
    """Graceful-shutdown core, shared by serve_forever and tests:
    flip readiness off (new requests 503, `/loadz` fails so the
    gateway stops routing here), then wait for in-flight requests —
    including active SSE streams — to finish, up to `grace_s`.
    Returns True when everything drained inside the deadline."""
    state.draining = True
    loop = asyncio.get_running_loop()
    deadline = loop.time() + grace_s
    while state.inflight and loop.time() < deadline:
        await asyncio.sleep(poll_s)
    return not state.inflight


def serve_forever(
    state: ServerState, host: str = "0.0.0.0", port: int = 8080,
    drain_grace_s: Optional[float] = None,
) -> None:
    """Run the app until SIGTERM/SIGINT, then drain gracefully:
    readiness fails first, in-flight streams finish (up to the grace
    deadline, SUBSTRATUS_DRAIN_GRACE env or 30 s), THEN the listener
    closes and the engine stops — kubelet's SIGTERM no longer kills
    active SSE responses mid-stream (docs/serving.md "Drain")."""
    if drain_grace_s is None:
        drain_grace_s = float(os.environ.get("SUBSTRATUS_DRAIN_GRACE", 30))
    app = build_app(state)

    async def _run() -> None:
        runner = web.AppRunner(app, handle_signals=False)
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        import signal as _signal

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix loops
                pass
        await stop.wait()
        clean = await drain(state, grace_s=drain_grace_s)
        logging.getLogger(__name__).info(
            "drained %s (%d requests still in flight)",
            "cleanly" if clean else "at deadline", len(state.inflight),
        )
        await runner.cleanup()

    asyncio.run(_run())
    # Engine last: its scheduler must outlive every stream it feeds.
    state.engine.stop()
