"""Host-side paged-KV bookkeeping: page allocator + shared-prefix registry.

Device layout and ops live in ops/kvcache.py; this module owns the decisions
— which page holds which tokens, who is sharing what — all plain Python on
the scheduler thread (engine threading model: one thread owns device state,
so no locks here).

Sharing model (prefix caching):
  * only FULL pages of prompt tokens are shared; the partially-filled tail
    page and everything a sequence generates stay private, so shared pages
    are immutable by construction;
  * pages are identified by a rolling chain hash — page i's key commits to
    every token before it, so a hit at depth i implies the whole prefix
    matches;
  * the registry holds its own reference on shared pages (they survive the
    sequences that created them) and evicts LRU-first under allocator
    pressure.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple


class PageAllocator:
    """Free-list page allocator with reference counts (shared prefixes hold
    multiple refs on one page)."""

    def __init__(self, num_pages: int, first_page: int = 0):
        """Hands out ids first_page..first_page+num_pages-1. The engine
        reserves physical page 0 as a write-off target: idle decode slots
        (block-table rows zeroed) scatter their garbage tokens there, so
        they can never clobber a live sequence's page."""
        self.num_pages = num_pages
        self.first_page = first_page
        self._free: List[int] = list(
            range(first_page + num_pages - 1, first_page - 1, -1)
        )
        self._refs: List[int] = [0] * (first_page + num_pages)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self) -> Optional[int]:
        """One page at refcount 1, or None when exhausted."""
        if not self._free:
            return None
        pid = self._free.pop()
        self._refs[pid] = 1
        return pid

    def incref(self, pid: int) -> None:
        assert self._refs[pid] > 0, f"incref on free page {pid}"
        self._refs[pid] += 1

    def decref(self, pid: int) -> None:
        assert self._refs[pid] > 0, f"decref on free page {pid}"
        self._refs[pid] -= 1
        if self._refs[pid] == 0:
            self._free.append(pid)

    def refs(self, pid: int) -> int:
        return self._refs[pid]


def chain_entries(
    tokens: Sequence[int], page_size: int, salt: object = None
) -> List[Tuple[int, int, Tuple[int, ...]]]:
    """Per FULL page: (chain_hash, parent_hash, page_tokens). The chain hash
    commits to every token before the page — but hash() is not collision-
    proof on user-controlled token sequences, so the registry also verifies
    (parent_hash, page_tokens) on match: with the parent link verified
    inductively, equal page tokens imply the whole prefix matches.

    `salt` seeds the chain root: multi-tenant serving passes the request's
    adapter id so a prompt prefilled under one LoRA adapter (whose wk/wv
    deltas change the cached K/V values) can never be reused by another
    tenant — same tokens, different adapter, disjoint chains."""
    out: List[Tuple[int, int, Tuple[int, ...]]] = []
    h = 0 if salt is None else hash(("adapter-salt", salt))
    for i in range(len(tokens) // page_size):
        page = tuple(tokens[i * page_size : (i + 1) * page_size])
        parent = h
        h = hash((h, page))
        out.append((h, parent, page))
    return out


class PrefixRegistry:
    """chain-hash -> (page id, parent hash, page tokens) map with LRU
    eviction. The registry owns one reference per registered page; eviction
    drops it (the page is freed once no live sequence still shares it)."""

    def __init__(self, alloc: PageAllocator, max_entries: int = 4096):
        self.alloc = alloc
        self.max_entries = max_entries
        self._map: "OrderedDict[int, Tuple[int, int, Tuple[int, ...]]]" = (
            OrderedDict()
        )
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._map)

    def match(
        self, entries: Sequence[Tuple[int, int, Tuple[int, ...]]]
    ) -> List[int]:
        """Longest-prefix hit: page ids for the leading run of verified
        chain entries (refcounts NOT yet taken — see claim)."""
        run: List[int] = []
        for h, parent, page in entries:
            hit = self._map.get(h)
            if hit is None or hit[1] != parent or hit[2] != page:
                break  # unknown, or a raw hash collision — never trust it
            self._map.move_to_end(h)
            run.append(hit[0])
        self.hits += len(run)
        self.misses += len(entries) - len(run)
        return run

    def claim(self, pids: Sequence[int]) -> None:
        """Take a sequence's reference on matched shared pages."""
        for pid in pids:
            self.alloc.incref(pid)

    def register(
        self,
        entries: Sequence[Tuple[int, int, Tuple[int, ...]]],
        pids: Sequence[int],
    ) -> None:
        """Publish a sequence's full prompt pages. Already-known hashes keep
        their existing page (the caller's copy stays private)."""
        for (h, parent, page), pid in zip(entries, pids):
            if h in self._map:
                self._map.move_to_end(h)
                continue
            if len(self._map) >= self.max_entries and not self.evict_lru():
                return
            self.alloc.incref(pid)
            self._map[h] = (pid, parent, page)

    def evict_lru(self) -> bool:
        """Drop the least-recently-used entry; returns False when empty."""
        if not self._map:
            return False
        _, (pid, _, _) = self._map.popitem(last=False)
        self.alloc.decref(pid)
        return True


class SlotPages:
    """Per-slot page list: which pool pages back each decode slot, and how
    many of the leading ones are shared (read-only for this slot)."""

    def __init__(self, max_batch: int):
        self.pages: List[List[int]] = [[] for _ in range(max_batch)]
        self.shared: List[int] = [0] * max_batch

    def assign(self, slot: int, shared: List[int], owned: List[int]) -> None:
        self.pages[slot] = list(shared) + list(owned)
        self.shared[slot] = len(shared)

    def append(self, slot: int, pid: int) -> None:
        self.pages[slot].append(pid)

    def release(self, slot: int, alloc: PageAllocator) -> None:
        for pid in self.pages[slot]:
            alloc.decref(pid)
        self.pages[slot] = []
        self.shared[slot] = 0
