"""Tokenizer abstraction for the serving stack.

Real checkpoints use their HF tokenizer (tokenizer.json next to the weights);
the ByteTokenizer serves tests and random-weight smoke configs (vocab 256+)
without any tokenizer artifacts — mirroring how the reference's smoke test
used the CPU-sized facebook/opt-125m (test/system.sh) rather than a real LLM.
"""
from __future__ import annotations

from typing import List, Protocol


class Tokenizer(Protocol):
    eos_id: int

    def encode(self, text: str) -> List[int]: ...
    def decode(self, ids: List[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes as tokens; ids 0..255 are bytes, 256 is BOS, 257 is EOS."""

    bos_id = 256
    eos_id = 257
    vocab_size = 258

    def encode(self, text: str) -> List[int]:
        return [self.bos_id] + list(text.encode("utf-8"))

    def decode(self, ids: List[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    """Wraps a transformers tokenizer loaded from a checkpoint directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self.eos_id = self._tok.eos_token_id

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages):
        """Rendered prompt, or None when the checkpoint ships no
        template (callers fall back to the generic transcript)."""
        if not getattr(self._tok, "chat_template", None):
            return None
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=True
        )

    def encode_templated(self, text: str) -> List[int]:
        """Encode a template-rendered prompt: the template already laid
        down BOS/special tokens, so none are added again."""
        return self._tok.encode(text, add_special_tokens=False)


def load_tokenizer(path: str | None) -> Tokenizer:
    if path is None:
        return ByteTokenizer()
    import os

    # A GGUF checkpoint carries its own vocab: prefer the embedded
    # SentencePiece tokenizer, then tokenizer files sitting next to it.
    # An embedded vocab we CANNOT run (BPE) is only an error when no
    # sibling tokenizer files can stand in.
    from substratus_tpu.load.gguf import (
        UnsupportedGGUFTokenizer, resolve_gguf, tokenizer_from_gguf,
    )

    gguf = resolve_gguf(path, weights=False)
    unsupported: UnsupportedGGUFTokenizer | None = None
    if gguf is not None:
        try:
            tok = tokenizer_from_gguf(gguf)
        except UnsupportedGGUFTokenizer as e:
            tok, unsupported = None, e
        if tok is not None:
            return tok
        path = os.path.dirname(gguf) or "."

    if os.path.isdir(path) and any(
        os.path.exists(os.path.join(path, f))
        for f in ("tokenizer.json", "tokenizer.model", "tokenizer_config.json")
    ):
        return HFTokenizer(path)
    if unsupported is not None:
        # no stand-in found: serving raw bytes against a real vocab would
        # be silent garbage — fail with the actionable message instead
        raise SystemExit(str(unsupported))
    return ByteTokenizer()
