"""Serving container entrypoint (container contract).

The reference's Server CR pointed at external images like
`substratusai/model-server-basaran` (examples/llama2-7b/server.yaml) obeying
the contract: model weights RO-mounted at /content/model, params at
/content/params.json, HTTP on :8080 with `GET /` readiness
(docs/container-contract.md:38-56). This module is the in-repo TPU-native
equivalent:

    python -m substratus_tpu.serve.main [--model /content/model] [--port 8080]

Params (from /content/params.json or flags): quantize=int8|w8a8|int4|none
(w8a8 = int8 weights + dynamic per-token int8 activations on the MXU's
native s8xs8 path; int4 = nibble-packed group-quantized weights, the
4-bit parity path for the reference's MODEL_LOAD_IN_4BIT / GGUF examples),
max_batch, max_seq_len, config (named config for weightless smoke runs).
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, Optional

import jax

from substratus_tpu.parallel.distributed import maybe_initialize


def load_params_json(path: str = "/content/params.json") -> Dict[str, Any]:
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return {}


def _resolve_gguf(path: str):
    """Strict GGUF path resolution for --model: loud on missing files and
    ambiguous multi-shard dirs (substratus_tpu.load.gguf)."""
    from substratus_tpu.load.gguf import resolve_gguf_or_exit

    return resolve_gguf_or_exit(path)


def resolve_kv_layout(params_json: Dict[str, Any]) -> str:
    """The decode_attn_impl="fused" kernel lives on the DENSE slot-cache
    path (update_cache_and_attend); paged decode has its own read path
    and never reaches it. Asking for fused with layout auto therefore
    resolves to dense — and asking for fused WITH paged is a config
    contradiction, rejected loudly rather than silently serving unfused."""
    layout = params_json.get("kv_layout", "auto")
    fused = params_json.get("decode_attn_impl") == "fused"
    if fused and layout == "auto":
        return "dense"
    if fused and layout == "paged":
        raise SystemExit(
            "params.json: decode_attn_impl=fused requires kv_layout=dense "
            "(the paged decode path does not use the fused kernel)"
        )
    return layout


def load_checkpoint(path: str):
    """One resolution rule for target and draft models alike (shared
    with the batch-generation entrypoint, serve/batchgen.py): a .gguf
    file (or a mounted artifact dir holding one) loads through the
    llama.cpp-format importer; otherwise orbax artifact if present,
    else HF layout."""
    gguf_path = _resolve_gguf(path)
    if gguf_path is not None:
        from substratus_tpu.load.gguf import load_gguf

        return load_gguf(gguf_path)
    from substratus_tpu.train.checkpoints import maybe_restore_orbax

    restored = maybe_restore_orbax(path)
    if restored is not None:
        return restored
    from substratus_tpu.load.hf import load_pretrained

    return load_pretrained(path)


def build_adapter_store(family, cfg, params_json: Dict[str, Any],
                        adapters_dir_flag: Optional[str]):
    """Multi-tenant AdapterStore from params/--adapters-dir discovery
    (docs/serving.md "Multi-tenant adapters"), shared by the interactive
    server and the batch-generation driver so a manifest's per-record
    `model` field selects the same LoRA slots a chat request would.
    Returns None when no adapters are configured (or the family can't
    index them — loud, not silent)."""
    adapters_cfg = params_json.get("adapters") or {}
    adapters_dir = adapters_dir_flag or adapters_cfg.get("dir") or (
        "/content/adapters" if os.path.isdir("/content/adapters") else None
    )
    if not adapters_dir and not adapters_cfg.get("paths"):
        return None
    if not getattr(family, "SUPPORTS_INDEXED_LORA", False):
        # Same loud-not-silent policy as _maybe_quantize: tell the
        # operator their tenants won't be served instead of 404ing
        # every adapter request with no explanation in the logs.
        print(
            "multi-tenant adapters unsupported for this family; "
            "serving the base model only",
            flush=True,
        )
        return None
    from substratus_tpu.serve.adapters import (
        AdapterStore, infer_store_shape, is_adapter_artifact,
    )

    explicit = dict(adapters_cfg.get("paths") or {})
    discovered = {}
    if adapters_dir and os.path.isdir(adapters_dir):
        for entry in sorted(os.listdir(adapters_dir)):
            p = os.path.join(adapters_dir, entry)
            if is_adapter_artifact(p):
                discovered[entry] = p
    inferred_rank, inferred_targets = infer_store_shape(
        list(explicit.values()) + list(discovered.values())
    )
    adapters = AdapterStore(
        cfg,
        capacity=int(adapters_cfg.get("capacity", 8)),
        rank=int(adapters_cfg.get("rank", inferred_rank)),
        targets=tuple(adapters_cfg.get("targets", inferred_targets)),
        search_dir=adapters_dir,
    )
    for aid, p in explicit.items():
        adapters.register_path(aid, p)
    # Preload up to capacity so first requests don't pay the
    # artifact read; the rest hot-load on demand (cache miss).
    for aid in list(adapters.available_ids())[: adapters.capacity]:
        try:
            adapters.load(aid)
        except (OSError, ValueError) as e:
            print(f"adapter {aid!r} failed to preload: {e}", flush=True)
    print(
        f"adapter store: {len(adapters.loaded_ids())} loaded / "
        f"{len(adapters.available_ids())} available "
        f"(capacity {adapters.capacity}, rank {adapters.rank})",
        flush=True,
    )
    return adapters


def _maybe_quantize(family, cfg, params, quantize: str, quiet: bool = False):
    """Quantize a (cfg, params) pair per the requested mode. Pre-quantized
    artifacts pass through; unsupported families keep dense weights."""
    from substratus_tpu.models import llama

    if quantize not in ("int8", "w8a8", "int4"):
        return cfg, params
    if family is not llama:
        if not quiet:
            print(f"{quantize} quantization not supported for this family; "
                  "skipping")
        return cfg, params
    from substratus_tpu.ops.quant import is_quantized, quantize_params
    from substratus_tpu.ops.quant4 import quantize4_params

    if not is_quantized(params):  # quantized artifacts come pre-done
        qfn = quantize4_params if quantize == "int4" else quantize_params
        params = jax.jit(
            lambda p: qfn(p, llama.quant_contracting(cfg))
        )(params)
    if quantize == "w8a8":
        cfg = cfg.replace(quant_activations=True)
    return cfg, params


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None, help="checkpoint dir (HF or orbax)")
    ap.add_argument("--config", default=None, help="named config for random-weight smoke")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument(
        "--quantize", default=None, choices=["int8", "w8a8", "int4", "none"]
    )
    ap.add_argument(
        "--draft-model", default=None,
        help="draft checkpoint dir for speculative decoding",
    )
    ap.add_argument(
        "--spec-k", type=int, default=None,
        help="draft tokens proposed per verify pass (0 = off)",
    )
    ap.add_argument(
        "--role", default=None, choices=["both", "prefill", "decode"],
        help="disaggregated serving role (serve/disagg.py): prefill "
             "workers hand KV pages to decode workers; default 'both' "
             "(monolithic). Env SUBSTRATUS_SERVE_ROLE / params.json "
             "'role' also set it (flag > env > params)",
    )
    ap.add_argument(
        "--transfer-port", type=int, default=None,
        help="KV-transfer listen port for role=decode (default 8500; "
             "env SUBSTRATUS_TRANSFER_PORT / params 'transfer_port')",
    )
    ap.add_argument(
        "--decode-peers", default=None,
        help="comma-separated host:port transfer endpoints of the "
             "decode tier, for role=prefill (env "
             "SUBSTRATUS_DECODE_PEERS / params 'decode_peers')",
    )
    ap.add_argument(
        "--adapters-dir", default=None,
        help="directory of LoRA adapter artifacts served multi-tenant "
             "(one subdir per adapter id; default /content/adapters "
             "when mounted — docs/serving.md 'Multi-tenant adapters')",
    )
    args = ap.parse_args(argv)

    from substratus_tpu.utils.jaxenv import honor_requested_platform

    honor_requested_platform()

    # Distributed tracing: join the spawner's trace (TRACEPARENT env —
    # the controller stamps it into Server workloads) and, when
    # SUBSTRATUS_TRACE_EXPORT is set, flush buffered spans there as JSONL
    # on shutdown (hack/trace_lint.py validates the format).
    from substratus_tpu.observability.propagation import context_from_env
    from substratus_tpu.observability.tracing import tracer

    with tracer.span("serve.start", parent=context_from_env()):
        pass
    trace_export = os.environ.get("SUBSTRATUS_TRACE_EXPORT")
    if trace_export:
        import atexit

        atexit.register(tracer.export_jsonl, trace_export)

    # Multi-host slice: join the jax.distributed world the operator wired
    # (no-op on single hosts).
    maybe_initialize()

    params_json = load_params_json()
    from substratus_tpu.utils.params import warn_unknown_keys

    warn_unknown_keys(
        params_json,
        (
            "model", "config", "quantize", "max_batch", "max_seq_len",
            "max_prefill_len", "kv_cache_dtype", "kv_layout", "attn_impl",
            "chunk_attn_impl", "decode_attn_impl", "q4_impl", "tensor",
            "sequence", "replicas", "draft_model", "spec_k", "max_queue",
            "drain_grace", "adapters", "baseModel", "disaggregated",
            "role", "transfer_port", "decode_peers", "batchGenerate",
        ),
        "serve.main",
    )
    model_dir = args.model or params_json.get("model") or (
        "/content/model" if os.path.isdir("/content/model") else None
    )
    quantize = args.quantize or params_json.get("quantize", "none")
    max_batch = args.max_batch or int(params_json.get("max_batch", 8))
    max_seq_len = args.max_seq_len or int(params_json.get("max_seq_len", 1024))

    from substratus_tpu.models import llama, registry
    from substratus_tpu.serve.engine import Engine, EngineConfig
    from substratus_tpu.serve.server import ServerState, serve_forever
    from substratus_tpu.serve.tokenizer import load_tokenizer

    if model_dir:
        cfg, params = load_checkpoint(model_dir)
        model_name = os.path.basename(os.path.normpath(model_dir))
        tokenizer = load_tokenizer(model_dir)
    else:
        # Weightless smoke mode (reference parallel: the opt-125m CPU smoke
        # in test/system.sh) — random init of a named config from any
        # registered family.
        name = args.config or params_json.get("config", "tiny")
        smoke_family, cfg = registry.find_named_config(name)
        tokenizer = load_tokenizer(None)
        if cfg.vocab_size < tokenizer.vocab_size:
            cfg = cfg.replace(vocab_size=tokenizer.vocab_size)
        params = smoke_family.init_params(cfg, jax.random.key(0))
        model_name = name

    family = registry.module_of(cfg)

    cfg, params = _maybe_quantize(family, cfg, params, quantize)

    kv_layout = resolve_kv_layout(params_json)
    if family is not llama and params_json.get("decode_attn_impl"):
        # Same loud-not-silent policy as resolve_kv_layout and
        # _maybe_quantize: the knob only exists on the llama family.
        print(
            f"decode_attn_impl ignored: {type(cfg).__name__} has no "
            "decode attention implementation switch",
            flush=True,
        )
    if family is llama:
        # Serving picks its own attention impl (never inherited from
        # training). On TPU the Pallas flash kernel is the prefill default
        # (validated bit-close and never slower on chip, 1.15x at 8k
        # context, and it keeps the [S, S] score matrix out of HBM); other
        # backends get the XLA reference. params.json {"attn_impl": ...}
        # overrides either way.
        default_impl = "flash" if jax.default_backend() == "tpu" else "xla"
        cfg = cfg.replace(
            attn_impl=params_json.get("attn_impl", default_impl),
            # The cached-chunk kernel is parity-tested but its Mosaic
            # lowering has not yet run on a chip (tunnel wedged before the
            # validation completed) — opt-in until it has.
            chunk_attn_impl=params_json.get("chunk_attn_impl", "xla"),
            # "fused" = flash-decode (scatter+attention in one kernel,
            # ops/fused_decode.py); opt-in until on-chip numbers land,
            # same policy as the chunk kernel above. Lives on the dense
            # slot-cache path — resolve_kv_layout picks/polices the layout.
            decode_attn_impl=params_json.get("decode_attn_impl", "xla"),
        )

    # Bounded admission (gateway contract): beyond this many waiters
    # submit() sheds with 429 instead of queueing. params.json
    # {"max_queue": 0} restores the unbounded legacy behavior.
    max_queue_raw = int(params_json.get("max_queue", 4 * max_batch))
    ec = EngineConfig(
        max_batch=max_batch,
        max_seq_len=min(max_seq_len, cfg.max_seq_len),
        max_prefill_len=int(
            params_json.get("max_prefill_len", EngineConfig.max_prefill_len)
        ),
        eos_token_id=tokenizer.eos_id if tokenizer.eos_id is not None else 2,
        kv_cache_dtype=params_json.get("kv_cache_dtype", "model"),
        kv_layout=kv_layout,
        max_queue=max_queue_raw if max_queue_raw > 0 else None,
        # Overlapped decode scheduling escape hatch (params.json
        # {"overlap": false} forces the synchronous scheduler; absent =
        # auto — on for single-host role=both/decode, off under
        # lockstep sync and speculation; docs/performance.md).
        overlap=params_json.get("overlap"),
    )
    # Multi-chip serving: tensor-parallel over as many chips as the kv heads
    # allow (params.json {"tensor": N} overrides), data-parallel the rest.
    n_dev = len(jax.devices())
    mesh = None
    if n_dev > 1:
        from substratus_tpu.parallel.mesh import build_mesh

        # Serving-side context parallelism: {"sequence": N} shards the
        # dense KV cache's sequence dim over N chips (per-chip cache
        # memory drops N×; XLA partitions the attention softmax over the
        # sharded dim — parallel/sharding.serve_rules_for).
        sp = int(params_json.get("sequence", 1)) or 1
        if n_dev % sp:
            raise SystemExit(
                f"sequence={sp} must divide the device count ({n_dev})"
            )
        rest = n_dev // sp
        tp = int(params_json.get("tensor", 0)) or min(rest, cfg.n_kv_heads)
        while rest % tp or cfg.n_kv_heads % tp:
            tp -= 1
        dp = rest // tp
        mesh = build_mesh(data=dp, sequence=sp, tensor=tp)
        if max_batch % dp:
            ec.max_batch = ((max_batch // dp) + 1) * dp
        print(
            f"serving mesh: data={dp} sequence={sp} tensor={tp}",
            flush=True,
        )
        if sp > 1:
            if kv_layout != "dense":
                # The paged pool indexes pages host-side; only the dense
                # layout sequence-shards.
                print("sequence>1 pins kv_layout=dense", flush=True)
                kv_layout = "dense"
                ec.kv_layout = "dense"
            # The Pallas attention kernels' partition rules keep the
            # cache sequence-REPLICATED (their online softmax is local
            # per shard); with an S-sharded cache the XLA paths are the
            # ones whose softmax GSPMD partitions over the sequence —
            # otherwise every chunk would silently all-gather the cache
            # and forfeit SP's N-times memory win.
            if getattr(cfg, "decode_attn_impl", "xla") != "xla":
                print("sequence>1 pins decode_attn_impl=xla", flush=True)
                cfg = cfg.replace(decode_attn_impl="xla")
            if getattr(cfg, "chunk_attn_impl", "xla") != "xla":
                print("sequence>1 pins chunk_attn_impl=xla", flush=True)
                cfg = cfg.replace(chunk_attn_impl="xla")
        # The Pallas kernels (int4 unpack-dequant matmul, fused/unfused
        # decode attention) carry custom_partitioning rules, so they run
        # per-shard under GSPMD — sharded serving no longer pins the XLA
        # fallbacks (round-4 gap). params.json {"q4_impl": "xla"} remains
        # the escape hatch.
    q4_impl = params_json.get("q4_impl")
    if q4_impl:
        from substratus_tpu.ops.quant4 import set_q4_impl

        if q4_impl not in ("xla", "pallas"):
            raise SystemExit(f"q4_impl {q4_impl!r} invalid (xla|pallas)")
        set_q4_impl(q4_impl)
        print(f"int4 lowering pinned: {q4_impl}", flush=True)
    # Speculative decoding: a small draft model (same family) proposes,
    # the target verifies — engine-integrated, batched (serve/engine.py).
    draft = None
    draft_dir = args.draft_model or params_json.get("draft_model")
    spec_k = (
        args.spec_k
        if args.spec_k is not None
        else int(params_json.get("spec_k", 0))
    )
    if spec_k and draft_dir and kv_layout == "dense":
        # Draft-model speculation shares the target's page tables, so it
        # needs the paged pool; warn and serve unsped rather than crash
        # at Engine construction. Prompt-lookup speculation is
        # layout-agnostic and composes with the dense fused-decode
        # kernel (int4 + fused + lookup stack in one config).
        print("draft spec_k needs kv_layout=paged; speculation disabled",
              flush=True)
        spec_k = 0
    if draft_dir and spec_k:
        draft_cfg, draft_params = load_checkpoint(draft_dir)
        if registry.module_of(draft_cfg) is not family:
            raise SystemExit("draft model must be the same family as the target")
        # The draft must ride the same quantization as the target — it
        # exists to cut HBM traffic, not to add bf16 streams.
        draft_cfg, draft_params = _maybe_quantize(
            registry.module_of(draft_cfg), draft_cfg, draft_params, quantize,
            quiet=True,
        )
        draft = (draft_cfg, draft_params)
        ec.spec_k = spec_k
        print(f"speculative decoding: draft={draft_dir} k={spec_k}", flush=True)
    elif spec_k:
        # No draft model: prompt-lookup decoding — the engine proposes the
        # continuation after the latest match of the context's trailing
        # n-gram (host-side, zero model cost; serve/engine.py).
        ec.spec_k = spec_k
        print(f"speculative decoding: prompt-lookup k={spec_k}", flush=True)

    # Multi-host slice: every process builds the same engine over the
    # global mesh and runs the scheduler in lockstep; only process 0
    # binds HTTP (the Service routes to worker 0), followers mirror the
    # computation via the per-iteration event broadcast
    # (serve/multihost.py).
    sync = None
    if jax.process_count() > 1:
        from substratus_tpu.serve.multihost import StepSync

        sync = StepSync()
        print(
            f"multi-host serving: process {sync.process_index}/"
            f"{sync.num_processes} "
            f"({'leader' if sync.leader else 'follower'})",
            flush=True,
        )

    # Multi-tenant adapter serving (docs/serving.md "Multi-tenant
    # adapters"): pack N tenants' LoRA adapters into this one engine.
    # Sources: --adapters-dir / params.json {"adapters": {"dir": ...,
    # "paths": {id: path}, "capacity", "rank", "targets"}}, defaulting
    # to the container-contract /content/adapters mount when present
    # (build_adapter_store — shared with serve/batchgen.py).
    adapters = build_adapter_store(family, cfg, params_json,
                                   args.adapters_dir)

    # Disaggregated prefill/decode serving (serve/disagg.py, ROADMAP
    # item 3). Per-tier values arrive as env vars (the controller stamps
    # SUBSTRATUS_SERVE_ROLE per Deployment — both tiers share one params
    # ConfigMap) with flag > env > params precedence.
    role = (
        args.role
        or os.environ.get("SUBSTRATUS_SERVE_ROLE")
        or str(params_json.get("role", "both"))
    )
    if role not in ("both", "prefill", "decode"):
        raise SystemExit(f"role {role!r} invalid (both|prefill|decode)")
    handoff = None
    if role != "both" and sync is not None:
        raise SystemExit("disaggregated roles don't combine with a "
                         "multi-host lockstep gang")
    if role == "prefill":
        from substratus_tpu.serve.disagg import HandoffManager, PoolSpec

        raw_peers = (
            args.decode_peers
            or os.environ.get("SUBSTRATUS_DECODE_PEERS")
            or ",".join(params_json.get("decode_peers", []) or [])
        )
        peers = [p.strip() for p in raw_peers.split(",") if p.strip()]
        if not peers:
            raise SystemExit("role=prefill needs --decode-peers")
        ec.role = "prefill"
        handoff = HandoffManager(peers, PoolSpec.from_engine_config(cfg, ec))
        print(f"prefill role: decode peers {peers}", flush=True)
    elif role == "decode":
        ec.role = "decode"

    engine = Engine(
        cfg, params, ec, mesh=mesh, model=family, draft=draft, sync=sync,
        adapters=adapters, handoff=handoff,
    )
    engine.start()

    if role == "decode":
        from substratus_tpu.serve.disagg import (
            DEFAULT_TRANSFER_PORT, HandoffServer,
        )

        transfer_port = int(
            args.transfer_port
            or os.environ.get("SUBSTRATUS_TRANSFER_PORT")
            or params_json.get("transfer_port", DEFAULT_TRANSFER_PORT)
        )
        transfer = HandoffServer(engine, host=args.host, port=transfer_port)
        print(f"decode role: KV transfer on :{transfer.port}", flush=True)
    if sync is not None and not sync.leader:
        # Follower: no HTTP. Mirror the leader's scheduler until it
        # broadcasts stop (or the process is torn down with the gang).
        # A crashed follower must exit NON-zero: a Succeeded gang pod
        # would suppress the JobSet failurePolicy restart while the
        # leader hangs at its next collective missing a participant.
        engine._thread.join()
        if engine.error is not None:
            print(f"follower engine died: {engine.error!r}", flush=True)
            return 1
        return 0
    def checkpoint_loader(ref: str):
        """POST /swapz checkpoint ref -> param tree ready to install:
        the exact load + quantize pipeline boot used, so the swapped
        tree matches the live one structurally whenever the checkpoint
        is the same architecture (anything else is rejected by
        Engine.swap_params' shape check, not installed)."""
        new_cfg, new_params = load_checkpoint(ref)
        _, new_params = _maybe_quantize(
            family, new_cfg, new_params, quantize, quiet=True
        )
        return new_params

    state = ServerState(
        engine, tokenizer, model_name,
        checkpoint_loader=checkpoint_loader,
    )
    print(f"serving {model_name} on {args.host}:{args.port}", flush=True)
    serve_forever(
        state, host=args.host, port=args.port,
        drain_grace_s=float(params_json["drain_grace"])
        if "drain_grace" in params_json else None,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
