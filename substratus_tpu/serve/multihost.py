"""Multi-host serving lockstep (leader/follower request broadcast).

A multi-host TPU slice runs one engine process per host, all of them
jointly executing every jitted computation over the global mesh (JAX's
multi-controller SPMD model). That only works if every process issues
IDENTICAL jit calls in IDENTICAL order — but only process 0 has the HTTP
server and therefore knows which requests exist. This module closes the
gap with a *replicated scheduler*:

  * process 0 (the leader) owns HTTP + the request queue. At the top of
    every scheduler iteration it serializes the iteration's events — new
    requests (full admission parameters), cancellation latches, shutdown
    — and broadcasts them to all processes;
  * every process (leader included) then applies those events to an
    identical local scheduler state and runs the exact same iteration
    code. All scheduler decisions (slot choice, paging, preemption,
    speculation accept/reject) are deterministic functions of the event
    stream plus device values that the engine pins to a fully-replicated
    layout (engine._replicated), so the processes cannot diverge;
  * followers attach a null token sink where the leader has the HTTP
    response queue: they compute everything and deliver nothing.

The broadcast rides `multihost_utils.broadcast_one_to_all` — an XLA
collective over ICI/DCN, the same fabric the decode collectives use, so
the control plane needs no extra network plumbing (the reference's
serving images were single-pod and never faced this problem; SURVEY.md
§2.2, reference internal/controller/server_controller.go).

Cost: ONE fixed-size collective per scheduler iteration for the common
case — the message rides a fixed buffer with its length in the first
four bytes — and a second, bucket-padded collective only when a burst of
long prompts overflows it. Fixed buffer sizes mean each shape compiles
once.

Lockstep gangs and the overlapped scheduler: `Engine.overlap` (one-
step-ahead dispatch, docs/performance.md "Overlapped scheduling")
resolves OFF whenever a sync is attached. The event broadcast encodes
decisions every process applies to a settled batch, the leader must
host-read step N's tokens before its consumers can cancel into step
N+1's event frame, and the engine feeds pure host-numpy inputs so all
processes replicate them identically — a pipelined step would tear all
three. Gangs therefore run flush-per-step (`Engine._flush("gang")` at
the top of `_sync_iterate`), preserving the exact pre-overlap
semantics; the ~20 ms idle tick also stays (a follower's wake event
cannot fire for leader-side submissions).
"""
from __future__ import annotations

import json
import socket
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from substratus_tpu.observability.metrics import METRICS


class NullSink:
    """Follower-side stand-in for Request.out: accepts and drops tokens.
    Followers mirror the full scheduler, so _emit runs on them too — the
    tokens just have nowhere to go (the leader answers the HTTP call)."""

    def put(self, item) -> None:  # queue.Queue interface subset
        pass


def _bucket_bytes(n: int, lo: int = 256) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def struct_pack_u32(n: int) -> bytes:
    import struct

    return struct.pack("<I", n)


class _TimedSync:
    """Shared broadcast timing for every sync transport: wall time lands
    in the shared registry (`substratus_serve_phase_seconds{phase=
    "broadcast"}`) and the last few thousand `(payload_bytes, seconds)`
    samples stay on `timings`, so the gang bench (tools/engine_bench.py
    --gang) reports wall-time percentiles — including the bucket-padded
    overflow path a >=8k-token admission takes — without scraping
    /metrics mid-run."""

    timings: "deque[tuple]"

    def broadcast(self, payload: Optional[bytes]) -> bytes:
        if self.num_processes == 1:
            return payload or b""
        t0 = time.perf_counter()
        out = self._broadcast(payload)
        dt = time.perf_counter() - t0
        # Record the DELIVERED length (== payload on the leader), so
        # follower-side samples carry real message sizes too.
        self.timings.append((len(out), dt))
        METRICS.observe(
            "substratus_serve_phase_seconds", dt, {"phase": "broadcast"}
        )
        return out

    def _broadcast(self, payload: Optional[bytes]) -> bytes:
        raise NotImplementedError


class StepSync(_TimedSync):
    """Per-iteration event broadcast for lockstep multi-host serving."""

    def __init__(self) -> None:
        import jax

        self.process_index = jax.process_index()
        self.num_processes = jax.process_count()
        self.leader = self.process_index == 0
        self.timings = deque(maxlen=4096)

    # Inline buffer: 4-byte length prefix + payload. Sized so a typical
    # iteration (a few requests, cancels, or the idle heartbeat) is one
    # collective.
    INLINE = 1024

    def _broadcast(self, payload: Optional[bytes]) -> bytes:
        """Leader sends `payload`; every process returns it. The message
        rides one fixed-size collective (length embedded in the first 4
        bytes); only payloads overflowing the inline buffer pay a second,
        bucket-padded collective — every process derives the same
        collective count from the first buffer, so the gang stays in
        lockstep."""
        from jax.experimental import multihost_utils

        payload = payload or b""
        n = len(payload)
        cap = self.INLINE - 4
        buf = np.zeros((self.INLINE,), np.uint8)
        if self.leader:
            buf[:4] = np.frombuffer(struct_pack_u32(n), np.uint8)
            buf[4 : 4 + min(n, cap)] = np.frombuffer(
                payload[:cap], np.uint8
            )
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        # The header was packed little-endian (struct "<I"); read it back
        # with an EXPLICIT little-endian dtype — a native-order view on a
        # big-endian host would decode a garbage length and desync the gang.
        n = int(out[:4].view(np.dtype("<u4"))[0])
        if n <= cap:
            return bytes(out[4 : 4 + n].tobytes())
        size = _bucket_bytes(n)
        big = np.zeros((size,), np.uint8)
        if self.leader:
            big[:n] = np.frombuffer(payload, np.uint8)
        out2 = np.asarray(multihost_utils.broadcast_one_to_all(big))
        return bytes(out2[:n].tobytes())


class TcpSync(_TimedSync):
    """Lockstep event broadcast over plain TCP (leader fans each
    length-prefixed message out to every follower; followers block on
    recv). The scheduler only ever sees the `broadcast` interface, so
    this is a drop-in StepSync for environments whose backend has no
    multi-process collectives — notably CPU jaxlib, where the gang bench
    (tools/engine_bench.py --gang --transport tcp) still measures a real
    2-process lockstep gang: identical mirrored schedulers, a real
    inter-process hop per iteration, only the ICI transfer time missing.
    Production multi-host serving stays on StepSync (the XLA collective
    needs no extra network plumbing and rides the proven fabric)."""

    def __init__(self, process_index: int, num_processes: int, port: int,
                 host: str = "127.0.0.1", timeout: float = 120.0) -> None:
        self.process_index = process_index
        self.num_processes = num_processes
        self.leader = process_index == 0
        self.timings = deque(maxlen=4096)
        if self.num_processes == 1:
            self._conns: List[Any] = []
            return
        if self.leader:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((host, port))
            srv.listen(num_processes - 1)
            srv.settimeout(timeout)
            self._conns = [
                srv.accept()[0] for _ in range(num_processes - 1)
            ]
            srv.close()  # sublint: allow[lifecycle]: listener past its final accept; no thread blocks on it
        else:
            deadline = time.monotonic() + timeout
            while True:
                try:
                    conn = socket.create_connection((host, port), timeout=5)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            conn.settimeout(timeout)
            self._conns = [conn]
        for c in self._conns:
            c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _broadcast(self, payload: Optional[bytes]) -> bytes:
        payload = payload or b""
        if self.leader:
            msg = struct_pack_u32(len(payload)) + payload
            for c in self._conns:
                c.sendall(msg)
            return payload
        conn = self._conns[0]

        def recv_exact(n: int) -> bytes:
            chunks = []
            while n:
                chunk = conn.recv(n)
                if not chunk:
                    raise ConnectionError("leader closed the sync stream")
                chunks.append(chunk)
                n -= len(chunk)
            return b"".join(chunks)

        n = int(np.frombuffer(recv_exact(4), np.dtype("<u4"))[0])
        return recv_exact(n)

    def close(self) -> None:
        for c in self._conns:
            # shutdown() before close(), the serve/disagg.py discipline:
            # a follower blocked in _broadcast's recv on another thread
            # would neither wake nor see FIN from a bare close().
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


def encode_events(
    reqs: List[Any], cancels: List[int], stop: bool,
    swap: Optional[int] = None,
) -> bytes:
    """Iteration events -> wire bytes. `reqs` carry every field admission
    reads, so a follower's mirror Request behaves identically. `swap` is
    the hot weight-swap barrier: the leader's target weights_version for
    THIS iteration (None = no swap) — every process installs its locally
    staged params when it sees one (Engine._sync_iterate)."""
    return json.dumps(
        {
            "stop": stop,
            "cancels": cancels,
            "swap": swap,
            "reqs": [
                {
                    "sid": r.sync_id,
                    "p": list(r.prompt_tokens),
                    "m": r.max_tokens,
                    "t": r.temperature,
                    "tp": r.top_p,
                    "e": r.eos_token_id,
                    "id": r.id,
                    "ad": r.adapter,
                }
                for r in reqs
            ],
        }
    ).encode()


def decode_events(payload: bytes) -> Dict[str, Any]:
    return json.loads(payload.decode())
