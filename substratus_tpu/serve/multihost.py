"""Multi-host serving lockstep (leader/follower request broadcast).

A multi-host TPU slice runs one engine process per host, all of them
jointly executing every jitted computation over the global mesh (JAX's
multi-controller SPMD model). That only works if every process issues
IDENTICAL jit calls in IDENTICAL order — but only process 0 has the HTTP
server and therefore knows which requests exist. This module closes the
gap with a *replicated scheduler*:

  * process 0 (the leader) owns HTTP + the request queue. At the top of
    every scheduler iteration it serializes the iteration's events — new
    requests (full admission parameters), cancellation latches, shutdown
    — and broadcasts them to all processes;
  * every process (leader included) then applies those events to an
    identical local scheduler state and runs the exact same iteration
    code. All scheduler decisions (slot choice, paging, preemption,
    speculation accept/reject) are deterministic functions of the event
    stream plus device values that the engine pins to a fully-replicated
    layout (engine._replicated), so the processes cannot diverge;
  * followers attach a null token sink where the leader has the HTTP
    response queue: they compute everything and deliver nothing.

The broadcast rides `multihost_utils.broadcast_one_to_all` — an XLA
collective over ICI/DCN, the same fabric the decode collectives use, so
the control plane needs no extra network plumbing (the reference's
serving images were single-pod and never faced this problem; SURVEY.md
§2.2, reference internal/controller/server_controller.go).

Cost: ONE fixed-size collective per scheduler iteration for the common
case — the message rides a fixed buffer with its length in the first
four bytes — and a second, bucket-padded collective only when a burst of
long prompts overflows it. Fixed buffer sizes mean each shape compiles
once.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np


class NullSink:
    """Follower-side stand-in for Request.out: accepts and drops tokens.
    Followers mirror the full scheduler, so _emit runs on them too — the
    tokens just have nowhere to go (the leader answers the HTTP call)."""

    def put(self, item) -> None:  # queue.Queue interface subset
        pass


def _bucket_bytes(n: int, lo: int = 256) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def struct_pack_u32(n: int) -> bytes:
    import struct

    return struct.pack("<I", n)


class StepSync:
    """Per-iteration event broadcast for lockstep multi-host serving."""

    def __init__(self) -> None:
        import jax

        self.process_index = jax.process_index()
        self.num_processes = jax.process_count()
        self.leader = self.process_index == 0

    # Inline buffer: 4-byte length prefix + payload. Sized so a typical
    # iteration (a few requests, cancels, or the idle heartbeat) is one
    # collective.
    INLINE = 1024

    def broadcast(self, payload: Optional[bytes]) -> bytes:
        """Leader sends `payload`; every process returns it. The message
        rides one fixed-size collective (length embedded in the first 4
        bytes); only payloads overflowing the inline buffer pay a second,
        bucket-padded collective — every process derives the same
        collective count from the first buffer, so the gang stays in
        lockstep."""
        if self.num_processes == 1:
            return payload or b""
        from jax.experimental import multihost_utils

        payload = payload or b""
        n = len(payload)
        cap = self.INLINE - 4
        buf = np.zeros((self.INLINE,), np.uint8)
        if self.leader:
            buf[:4] = np.frombuffer(struct_pack_u32(n), np.uint8)
            buf[4 : 4 + min(n, cap)] = np.frombuffer(
                payload[:cap], np.uint8
            )
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        n = int(out[:4].view(np.uint32)[0])
        if n <= cap:
            return bytes(out[4 : 4 + n].tobytes())
        size = _bucket_bytes(n)
        big = np.zeros((size,), np.uint8)
        if self.leader:
            big[:n] = np.frombuffer(payload, np.uint8)
        out2 = np.asarray(multihost_utils.broadcast_one_to_all(big))
        return bytes(out2[:n].tobytes())


def encode_events(reqs: List[Any], cancels: List[int], stop: bool) -> bytes:
    """Iteration events -> wire bytes. `reqs` carry every field admission
    reads, so a follower's mirror Request behaves identically."""
    return json.dumps(
        {
            "stop": stop,
            "cancels": cancels,
            "reqs": [
                {
                    "sid": r.sync_id,
                    "p": list(r.prompt_tokens),
                    "m": r.max_tokens,
                    "t": r.temperature,
                    "tp": r.top_p,
                    "e": r.eos_token_id,
                    "id": r.id,
                }
                for r in reqs
            ],
        }
    ).encode()


def decode_events(payload: bytes) -> Dict[str, Any]:
    return json.loads(payload.decode())
