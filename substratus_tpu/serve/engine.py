"""Continuous-batching inference engine (prefill -> insert -> decode).

The reference served models through external images (basaran / llama.cpp,
SURVEY.md §2.2) with static batching; this engine is the in-repo TPU-native
replacement, following the orchestrator pattern that works well on TPUs
(fixed shapes, no dynamic batch):

  * the decode batch is a fixed-size slot array; every jitted function sees
    static shapes, so there is exactly one decode executable;
  * prefill runs per-request at bucketed (power-of-two) lengths — a handful
    of prefill executables — then the resulting KV fragment is INSERTed into
    the decode cache at a free slot;
  * decode advances every active slot one token per step, sampling on device
    (ops/sampling.py); finished slots are freed and refilled between steps;
  * weights may be int8 QTensors (ops/quant.py) for ~2x decode throughput.

Threading model: callers enqueue Requests (thread-safe); one background
scheduler thread owns all device state — no locks around jax values.
"""
from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from substratus_tpu.models import llama
from substratus_tpu.models.llama import LlamaConfig, Params
from substratus_tpu.ops.sampling import sample


@dataclass
class EngineConfig:
    max_batch: int = 8  # decode slots
    max_seq_len: int = 1024  # cache length per slot
    max_prefill_len: int = 512
    top_k: int = 0  # static top-k (0 = disabled)
    eos_token_id: int = 2
    # "model" keeps the cache in the model dtype; "int8" stores entries
    # quantized per-vector (llama family) — decode cache reads halve.
    kv_cache_dtype: str = "model"


@dataclass
class Request:
    prompt_tokens: List[int]
    max_tokens: int = 64
    temperature: float = 0.0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    # Each generated token id is put on this queue; None marks completion.
    out: "queue.Queue[Optional[int]]" = field(default_factory=queue.Queue)
    id: str = ""
    # Set by the engine before the terminal None: "stop" (eos) or "length"
    # (max_tokens / context-window cap).
    finish_reason: str = "stop"
    # Cooperative cancellation: a consumer (e.g. the HTTP layer on a stop-
    # sequence match) sets this; the scheduler frees the slot at the next
    # emit instead of decoding to max_tokens.
    cancelled: bool = False


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _pad_to_bucket(tokens, cap: int):
    """Right-pad a token list to its power-of-two bucket (capped): the one
    padding rule both the single-shot and chunked prefill paths share."""
    true_len = len(tokens)
    bucket = min(_bucket(true_len), cap)
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :true_len] = tokens
    return jnp.asarray(padded), true_len


class Engine:
    def __init__(
        self,
        cfg: LlamaConfig,
        params: Params,
        ec: Optional[EngineConfig] = None,
        mesh=None,
        model=llama,
    ):
        """model: the model-family module (models.llama, models.opt, ...)
        implementing forward/init_cache/param_logical_axes/cache_logical_axes.

        mesh: optional jax Mesh for sharded serving. Params are laid out
        by parallel.sharding.SERVE_RULES (tensor-parallel heads/mlp/vocab,
        data-parallel batch); the KV cache shards the same way, so decode
        collectives ride ICI. Constraint: the tensor axis must divide
        n_kv_heads (llama2-70b: KH=8 => tensor<=8 per data replica)."""
        import dataclasses as _dc

        # Copy the config before clamping: mutating a caller's (or the
        # default) EngineConfig instance would leak between engines.
        ec = _dc.replace(ec) if ec is not None else EngineConfig()
        self.cfg, self.params, self.ec = cfg, params, ec
        self.model = model
        # The cache may never outrun the model's position space (learned
        # position embeddings silently clamp on OOB lookups), and a prefill
        # fragment must fit in the cache.
        ec.max_seq_len = min(ec.max_seq_len, cfg.max_seq_len)
        ec.max_prefill_len = min(ec.max_prefill_len, ec.max_seq_len)
        B, S = ec.max_batch, ec.max_seq_len

        if ec.kv_cache_dtype not in ("model", "int8"):
            raise ValueError(
                f"kv_cache_dtype {ec.kv_cache_dtype!r} invalid "
                "(expected 'model' or 'int8')"
            )
        if ec.max_prefill_len < 1 or ec.max_batch < 1 or ec.max_seq_len < 2:
            raise ValueError(
                f"invalid engine config: max_prefill_len={ec.max_prefill_len} "
                f"max_batch={ec.max_batch} max_seq_len={ec.max_seq_len}"
            )
        kv_int8 = ec.kv_cache_dtype == "int8"
        if kv_int8 and not getattr(model, "SUPPORTS_INT8_KV", False):
            raise ValueError(
                f"kv_cache_dtype=int8 unsupported for {model.__name__}"
            )
        cache_dtype = jnp.int8 if kv_int8 else None

        self.mesh = mesh
        if mesh is not None:
            from substratus_tpu.parallel.sharding import SERVE_RULES, shard_tree

            self.params = shard_tree(
                params, mesh, model.param_logical_axes(cfg), SERVE_RULES
            )
            self.cache = shard_tree(
                model.init_cache(cfg, B, S, dtype=cache_dtype),
                mesh,
                model.cache_logical_axes(cfg, quantized=kv_int8),
                SERVE_RULES,
            )
        else:
            self.cache = model.init_cache(cfg, B, S, dtype=cache_dtype)
        self.tokens = jnp.zeros((B,), jnp.int32)
        self.positions = jnp.zeros((B,), jnp.int32)
        self.temps = jnp.zeros((B,), jnp.float32)
        self.top_ps = jnp.ones((B,), jnp.float32)
        self.key = jax.random.key(0)

        # Host-side slot bookkeeping (scheduler thread only). host_positions
        # mirrors the device positions array so per-token checks never force
        # a device->host scalar read.
        self.slot_req: List[Optional[Request]] = [None] * B
        self.slot_generated: List[int] = [0] * B
        self.active = np.zeros(B, dtype=bool)
        self.host_positions = np.zeros(B, dtype=np.int64)

        self.queue: "queue.Queue[Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        self._admitting: Optional[Request] = None

        self._decode_fn = self._build_decode()
        self._prefill_fn = partial(self._prefill_jit, self.model, self.cfg)
        self._chunk_fn = partial(self._chunk_prefill_jit, self.model, self.cfg)
        self._insert_fn = self._build_insert()
        self._extract_slot, self._restore_slot = self._build_slot_io()

    # --- jitted device functions -----------------------------------------

    @staticmethod
    @partial(jax.jit, static_argnums=(0, 1))
    def _prefill_jit(model, cfg, params, tokens, true_len):
        """tokens [1, Sbucket] (right-padded); returns kv fragment + last
        real token's logits."""
        s = tokens.shape[1]
        positions = jnp.arange(s, dtype=jnp.int32)[None, :]
        logits, kv = model.forward(params, tokens, cfg, positions=positions)
        last = logits[0, true_len - 1]
        return last, kv

    @staticmethod
    @partial(jax.jit, static_argnums=(0, 1), donate_argnums=(3,))
    def _chunk_prefill_jit(model, cfg, params, slot_cache, tokens, offset,
                           true_len):
        """One chunk of a long prefill: tokens [1, C] (right-padded) written
        into the single-slot cache at absolute positions offset..offset+C-1.
        Returns (logits of the last real token, updated slot cache)."""
        c = tokens.shape[1]
        positions = offset + jnp.arange(c, dtype=jnp.int32)[None, :]
        # Padded tail positions all clamp onto the single slot one past the
        # prompt: real queries never attend it (causal mask), and the first
        # decode step writes that exact slot before reading it. The caller
        # keeps prompts <= max_seq_len - 1 so the slot exists.
        positions = jnp.minimum(positions, offset + true_len)
        logits, slot_cache = model.forward(
            params, tokens, cfg, positions=positions, cache=slot_cache
        )
        return logits[0, true_len - 1], slot_cache

    def _build_slot_io(self):
        @jax.jit
        def extract(cache, slot):
            return jax.tree.map(
                lambda x: jax.lax.dynamic_slice_in_dim(x, slot, 1, axis=1),
                cache,
            )

        @partial(jax.jit, donate_argnums=(0,))
        def restore(cache, slot_cache, slot):
            return jax.tree.map(
                lambda full, one: jax.lax.dynamic_update_slice_in_dim(
                    full, one, slot, axis=1
                ),
                cache,
                slot_cache,
            )

        return extract, restore

    def _build_insert(self):
        @partial(jax.jit, donate_argnums=(0,))
        def insert(cache, kv, slot):
            # kv: {k, v} fragment [L, 1, Sb, KH, hd] (bf16 from prefill) ->
            # write into cache[:, slot, :Sb], quantizing when the cache is
            # int8.
            if "k_scale" in cache:
                from substratus_tpu.ops.quant import quantize_kv

                kq, ks = quantize_kv(kv["k"])
                vq, vs = quantize_kv(kv["v"])
                frag = {"k": kq, "k_scale": ks, "v": vq, "v_scale": vs}
            else:
                frag = {
                    "k": kv["k"].astype(cache["k"].dtype),
                    "v": kv["v"].astype(cache["v"].dtype),
                }
            return {
                key: jax.lax.dynamic_update_slice(
                    cache[key], frag[key], (0, slot, 0, 0, 0)
                )
                for key in cache
            }

        return insert

    def _build_decode(self):
        cfg, ec, model = self.cfg, self.ec, self.model

        @partial(jax.jit, donate_argnums=(1,))
        def decode(params, cache, tokens, positions, temps, top_ps, key):
            logits, cache = model.forward(
                params,
                tokens[:, None],
                cfg,
                positions=positions[:, None],
                cache=cache,
            )
            key, subkey = jax.random.split(key)
            next_tokens = sample(
                logits[:, 0], subkey, temps, top_k=ec.top_k, top_p=top_ps
            )
            return next_tokens, cache, key

        return decode

    # --- scheduler --------------------------------------------------------

    def submit(self, req: Request) -> Request:
        if self.error is not None:
            req.out.put(None)  # engine is dead; never strand the caller
            return req
        self.queue.put(req)
        return req

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)

    def _admit(self):
        """Fill free slots from the request queue (prefill + insert).

        Admission is capped per scheduler iteration so a burst of arrivals
        can't starve in-flight decodes: each loop admits a few prefills,
        then every active slot advances a token."""
        admitted = 0
        # No in-flight decodes -> nothing to starve: fill freely (decode
        # steps cost the same at any occupancy, so boarding everyone first
        # is strictly better for TTFT).
        cap = (
            max(1, self.ec.max_batch // 4)
            if self.active.any()
            else self.ec.max_batch
        )
        while (
            admitted < cap
            and not self.queue.empty()
            and not self.active.all()
        ):
            admitted += 1
            try:
                req = self.queue.get_nowait()
            except queue.Empty:
                return
            self._admitting = req
            slot = int(np.flatnonzero(~self.active)[0])
            # Keep the newest tokens that fit the cache (minus one slot for
            # generation); prompts longer than one prefill bucket run as a
            # sequence of chunked prefills against the slot's cache.
            keep = self.ec.max_seq_len - 1
            prompt = req.prompt_tokens[-keep:]
            true_len = len(prompt)
            if true_len <= self.ec.max_prefill_len:
                padded, true_len = _pad_to_bucket(
                    prompt, self.ec.max_prefill_len
                )
                last_logits, kv = self._prefill_fn(
                    self.params, padded, true_len
                )
                self.cache = self._insert_fn(self.cache, kv, slot)
            else:
                last_logits = self._chunked_prefill(prompt, slot)
            # Sample the first generated token from the prefill logits.
            self.key, subkey = jax.random.split(self.key)
            first = sample(
                last_logits[None, :],
                subkey,
                jnp.array([req.temperature], jnp.float32),
                top_k=self.ec.top_k,
                top_p=jnp.array([req.top_p], jnp.float32),
            )
            first_id = int(first[0])

            self.slot_req[slot] = req
            self.slot_generated[slot] = 0
            self.active[slot] = True
            self.host_positions[slot] = true_len
            self.tokens = self.tokens.at[slot].set(first_id)
            self.positions = self.positions.at[slot].set(true_len)
            self.temps = self.temps.at[slot].set(req.temperature)
            self.top_ps = self.top_ps.at[slot].set(req.top_p)
            self._admitting = None
            self._emit(slot, first_id)

    def _chunked_prefill(self, prompt, slot: int):
        """Prefill a prompt longer than one bucket: run bucket-sized chunks
        against the slot's cache (each chunk attends everything before it),
        then restore the slot into the decode cache."""
        chunk = self.ec.max_prefill_len
        slot_cache = self._extract_slot(self.cache, slot)
        last_logits = None
        offset = 0
        while offset < len(prompt):
            padded, true_len = _pad_to_bucket(
                prompt[offset : offset + chunk], chunk
            )
            last_logits, slot_cache = self._chunk_fn(
                self.params, slot_cache, padded, offset, true_len
            )
            offset += true_len
        self.cache = self._restore_slot(self.cache, slot_cache, slot)
        return last_logits

    def _emit(self, slot: int, token_id: int):
        req = self.slot_req[slot]
        eos = req.eos_token_id if req.eos_token_id is not None else self.ec.eos_token_id
        self.slot_generated[slot] += 1
        hit_eos = token_id == eos
        hit_budget = self.slot_generated[slot] >= req.max_tokens
        hit_window = int(self.host_positions[slot]) + 1 >= self.ec.max_seq_len
        if not hit_eos and not req.cancelled:
            req.out.put(token_id)
        if hit_eos or hit_budget or hit_window or req.cancelled:
            # eos/cancel are natural stops; running out of budget or context
            # is a truncation ("length") clients may want to continue from.
            req.finish_reason = (
                "stop" if (hit_eos or req.cancelled) else "length"
            )
            req.out.put(None)
            self.active[slot] = False
            self.slot_req[slot] = None

    def _loop(self):
        try:
            while not self._stop.is_set():
                self._admit()
                if not self.active.any():
                    time.sleep(0.002)
                    continue
                next_tokens, self.cache, self.key = self._decode_fn(
                    self.params,
                    self.cache,
                    self.tokens,
                    self.positions,
                    self.temps,
                    self.top_ps,
                    self.key,
                )
                self.positions = self.positions + 1
                self.host_positions += 1
                self.tokens = next_tokens
                host_tokens = np.asarray(next_tokens)
                for slot in np.flatnonzero(self.active):
                    self._emit(int(slot), int(host_tokens[slot]))
        except BaseException as e:  # propagate to waiting callers
            self.error = e
            if self._admitting is not None:
                self._admitting.out.put(None)
            for req in self.slot_req:
                if req is not None:
                    req.out.put(None)
            while not self.queue.empty():
                try:
                    self.queue.get_nowait().out.put(None)
                except queue.Empty:
                    break
            raise

    # --- synchronous helper (tests / bench) -------------------------------

    def generate(
        self, prompt_tokens: List[int], max_tokens: int = 32, **kw
    ) -> List[int]:
        """Blocking single-request generation (engine must be started)."""
        req = self.submit(Request(prompt_tokens, max_tokens=max_tokens, **kw))
        out: List[int] = []
        while True:
            tok = req.out.get(timeout=120)
            if tok is None:
                return out
            out.append(tok)
