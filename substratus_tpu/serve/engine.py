"""Continuous-batching inference engine (prefill -> insert -> decode).

The reference served models through external images (basaran / llama.cpp,
SURVEY.md §2.2) with static batching; this engine is the in-repo TPU-native
replacement, following the orchestrator pattern that works well on TPUs
(fixed shapes, no dynamic batch):

  * the decode batch is a fixed-size slot array; every jitted function sees
    static shapes, so there is exactly one decode executable;
  * prefill runs per-request at bucketed (power-of-two) lengths — a handful
    of prefill executables — then the resulting KV fragment is INSERTed into
    the decode cache at a free slot;
  * decode advances every active slot one token per step, sampling on device
    (ops/sampling.py); finished slots are freed and refilled between steps;
  * weights may be int8 QTensors (ops/quant.py) for ~2x decode throughput.

Threading model: callers enqueue Requests (thread-safe); one background
scheduler thread owns all device state — no locks around jax values.
"""
from __future__ import annotations

import itertools
import logging
import math
import queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from substratus_tpu.models import llama
from substratus_tpu.models.llama import LlamaConfig, Params
from substratus_tpu.observability.journey import (
    JourneyLog,
    RequestJourney,
    SlowRing,
)
from substratus_tpu.observability.metrics import METRICS, RATIO_BUCKETS
from substratus_tpu.observability.sketch import SLOTracker
from substratus_tpu.observability.timeline import StepTimeline
from substratus_tpu.observability.tracing import (
    SpanContext,
    current_trace_id,
    tracer,
)
from substratus_tpu.ops.sampling import sample

# Serving latency/utilization histograms (docs/observability.md). Declared
# once at import so /metrics carries the HELP/TYPE headers even before the
# first request arrives.
METRICS.histogram(
    "substratus_serve_ttft_seconds",
    "Time from request submission to its first generated token (seconds).",
)
METRICS.histogram(
    "substratus_serve_inter_token_seconds",
    "Gap between consecutive generated tokens of one request (seconds).",
)
METRICS.histogram(
    "substratus_serve_queue_wait_seconds",
    "Time from request submission to the start of its prefill (seconds).",
)
METRICS.histogram(
    "substratus_serve_batch_occupancy_ratio",
    "Active decode slots / max_batch, sampled once per scheduler iteration.",
    buckets=RATIO_BUCKETS,
)
METRICS.histogram(
    "substratus_serve_kv_page_utilization_ratio",
    "Allocated KV pages / pool size, sampled once per scheduler iteration "
    "(paged layout only).",
    buckets=RATIO_BUCKETS,
)
METRICS.histogram(
    "substratus_serve_phase_seconds",
    "Wall time of one scheduler phase (seconds), labeled by phase: "
    "broadcast (lockstep event sync, serve/multihost.py), admission "
    "(queue -> slots, prefill included), prefill (device prefill inside "
    "admission), sample (first-token sampling + host read), decode (the "
    "batched decode/verify dispatch of one iteration).",
)
METRICS.describe(
    "substratus_serve_first_compile_seconds",
    "Wall time of the first decode iteration (executable compile "
    "dominates; steady-state decode is substratus_serve_phase_seconds"
    '{phase="decode"}).',
    type="gauge",
)
METRICS.histogram(
    "substratus_serve_host_overlap_seconds",
    "Host-side work (the deferred token read, emits, stop handling) "
    "hidden under the in-flight decode step by the overlapped scheduler "
    "(seconds; docs/performance.md \"Overlapped scheduling\").",
)
METRICS.describe(
    "substratus_serve_pipeline_flushes_total",
    "Overlapped-scheduler pipeline flushes by reason (gang|handoff|"
    "drain|preempt|swap): points where the engine must observe a "
    "settled batch before proceeding. The historical reason=\"spec\" is "
    "retired — speculative rounds chain on-device and hold it at zero.",
    type="counter",
)
# True counters (monotonic, rate()-able) for prefix-cache effectiveness —
# the scrape-time substratus_serve_<stat> gauges mirror the same numbers
# but only when a server is attached; these increment at admission.
METRICS.describe(
    "substratus_serve_prefill_tokens_total",
    "Prompt tokens actually prefilled through the model (prefix-cache "
    "misses; the cold-work half of the reuse ratio).",
    type="counter",
)
METRICS.describe(
    "substratus_serve_prefix_hit_tokens_total",
    "Prompt tokens satisfied from shared prefix pages instead of "
    "recompute (paged layout, serve/paged_kv.py).",
    type="counter",
)
# Speculative-decoding effectiveness as true counters (rate()-able): the
# acceptance ratio accepted/proposed is the lever the adaptive per-stream
# draft length steers on (docs/performance.md "Speculative decoding").
METRICS.describe(
    "substratus_serve_spec_proposed_tokens_total",
    "Draft tokens proposed to speculative verify rounds (greedy streams "
    "only; placeholder rows and degraded streams do not count).",
    type="counter",
)
METRICS.describe(
    "substratus_serve_spec_accepted_tokens_total",
    "Proposed draft tokens the target model accepted (longest matching "
    "prefix of each verify round).",
    type="counter",
)
# Hot weight-swap (docs/serving.md "Zero-downtime rollout"): in-place
# param replacement on a live engine. Same shapes/dtypes/treedef means
# the compiled prefill/decode/verify executables are all kept.
METRICS.describe(
    "substratus_serve_weight_swaps_total",
    "Hot weight-swaps by outcome: applied (params replaced in place, "
    "compiled programs kept) or rejected (treedef/shape/dtype mismatch "
    "— the engine keeps serving the old weights).",
    type="counter",
)
METRICS.describe(
    "substratus_serve_weights_version",
    "Version of the parameter tree the engine is currently serving "
    "(bumped by Engine.swap_params; also on load_snapshot()/ /loadz).",
    type="gauge",
)


class EngineOverloaded(RuntimeError):
    """submit() rejected: the waiting queue is at its configured bound.

    Raised instead of queueing so callers can shed (HTTP 429 +
    Retry-After) — an unbounded queue converts overload into unbounded
    tail latency, which every client experiences as an outage anyway.
    `retry_after` estimates when a slot's worth of work will drain."""

    def __init__(self, queue_depth: int, retry_after: float = 1.0):
        super().__init__(
            f"engine overloaded: {queue_depth} requests already waiting"
        )
        self.queue_depth = queue_depth
        self.retry_after = retry_after


class _StagedSwap:
    """One pending hot weight-swap, staged by swap_params() from any
    thread and applied by the scheduler thread at its next
    _sync_iterate. The caller parks on `done`; `applied`/`error` carry
    the outcome back across the thread boundary (write-then-set
    ordering, same contract as Request.out)."""

    __slots__ = ("params", "version", "source", "done", "applied", "error")

    def __init__(self, params, version: Optional[int], source: str):
        self.params = params
        self.version = version
        self.source = source
        self.done = threading.Event()
        self.applied: Optional[int] = None
        self.error: Optional[BaseException] = None


@dataclass
class EngineConfig:
    max_batch: int = 8  # decode slots
    max_seq_len: int = 1024  # cache length per slot
    max_prefill_len: int = 512
    # Waiting-queue bound: submit() raises EngineOverloaded instead of
    # queueing beyond this many waiters. None = unbounded (legacy
    # behavior; serve.main defaults it to 4x max_batch).
    max_queue: Optional[int] = None
    # Bench/smoke knob: minimum wall time per decode iteration AND per
    # prefill chunk dispatch, simulating accelerator step latency on CPU
    # hosts where the tiny model's math is instant (the control-plane
    # analogue of multihost.TcpSync). With it, a CPU gateway bench
    # measures what the routing tier controls — keeping N replicas
    # concurrently busy — instead of the host's core count; the prefill
    # floor makes prompt-vs-decode contention measurable (the effect
    # disaggregation removes). 0 = off (production).
    step_floor_s: float = 0.0
    # Disaggregated serving role (serve/disagg.py, ROADMAP item 3):
    # "both" = the monolithic engine (default); "prefill" = run chunked
    # prefill + first-token sampling, then export the request's KV pages
    # to a decode engine (requires a HandoffManager and the paged
    # layout); "decode" = accept migrated KV pages via submit_migration
    # and continue decoding (external submit() is rejected).
    role: str = "both"
    top_k: int = 0  # static top-k (0 = disabled)
    eos_token_id: int = 2
    # "model" keeps the cache in the model dtype; "int8" stores entries
    # quantized per-vector (llama family) — decode cache reads halve.
    kv_cache_dtype: str = "model"
    # KV memory layout: "paged" (block pool + per-slot block tables,
    # ops/kvcache.py — memory bounded by actual tokens, prefix sharing,
    # preempt-and-resume under pressure), "dense" (one max_seq_len region
    # per slot), or "auto" (paged when the model family supports it).
    kv_layout: str = "auto"
    page_size: int = 16  # tokens per KV page (paged layout)
    # Total pool size in tokens (paged). None = max_batch * max_seq_len
    # (the dense footprint); set lower to oversubscribe slots against real
    # usage — the scheduler preempts (and later resumes) the youngest slot
    # if the pool runs dry mid-decode.
    kv_pool_tokens: Optional[int] = None
    prefix_cache: bool = True  # share full prompt-prefix pages across requests
    # Speculative decoding: a proposer guesses spec_k greedy tokens per
    # iteration and ONE target forward verifies all of
    # them — decode is HBM-bound, so accepted tokens amortize the weight
    # stream. With draft=(cfg, params) at Engine construction the proposer
    # is the draft model (paged layout only — the draft shares the
    # target's page tables); WITHOUT one it is prompt-lookup decoding
    # (layout-agnostic, so it stacks with the dense-only fused kernel;
    # the
    # continuation after the most recent match of the context's trailing
    # n-gram — zero extra model cost, wins on repetitive outputs:
    # summarization, RAG, code edits). Greedy slots stay token-exact
    # (longest matching prefix + correction); sampling slots take the
    # verify pass's position-0 sample (one token, plain-decode semantics).
    # 0 = off.
    spec_k: int = 0
    # Adaptive per-stream speculation (spec_k > 0): every greedy stream
    # carries an EWMA of its acceptance rate (accepted/k per verify
    # round, decay spec_ewma_decay); the stream's next draft length is
    # k = ceil(ewma * spec_k) in {1..spec_k} while the estimate holds
    # >= spec_threshold, and the stream degrades to a plain decode row
    # inside the same batch (k = 0: no proposals, it rides the verify's
    # position-0 greedy choice) when the estimate falls below — low-
    # acceptance traffic stops paying the (k+1)-wide verify tax.
    # Degraded streams re-probe with k = 1 every spec_probe_every
    # rounds so a stream whose output turns predictable again recovers.
    # spec_threshold 0 disables degradation (always propose spec_k).
    spec_threshold: float = 0.35
    spec_probe_every: int = 8
    spec_ewma_decay: float = 0.8
    # Overlapped decode scheduling (docs/performance.md "Overlapped
    # scheduling"): dispatch decode step N+1 — with step N's sampled
    # tokens fed back on-device — BEFORE reading step N's tokens to the
    # host, so the per-token host work (the read, emits, detokenize
    # downstream, EOS/window release, admission bookkeeping) runs while
    # the device computes. Steady-state inter-token latency becomes
    # max(device_step, host_work) instead of their sum. Speculative
    # rounds pipeline the same way: round N+1's proposal + verify
    # dispatch from round N's device-resident output (the accept-mask
    # advance), and the acceptance walk rides the deferred drain. None
    # = auto: on for single-host role=both/decode engines; off under
    # lockstep sync (the leader must emit host tokens before encoding
    # the gang's event broadcast — gangs run flush-per-step).
    # False forces the synchronous scheduler — the escape hatch.
    overlap: Optional[bool] = None
    # SLO thresholds (observability/sketch.py): emits over budget
    # increment substratus_slo_burn_total{slo=...}, and the mergeable
    # percentile sketches ride load_snapshot() so the gateway's fleet
    # aggregator (gateway/fleet.py) rolls them up fleet-wide.
    slo_ttft_s: float = 2.0
    slo_inter_token_s: float = 0.25
    # Request-journey forensics (observability/journey.py): per-request
    # lifecycle event ring size and the /debug/slowz exemplar ring of
    # SLO-breaching journeys. Recording is pure host work on the
    # scheduler thread (dispatch events stamp at drain), so it stays on
    # in production.
    journey_events: int = 256
    slow_journeys: int = 32


@dataclass
class Request:
    prompt_tokens: List[int]
    max_tokens: int = 64
    temperature: float = 0.0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    # Multi-tenant serving (serve/adapters.py): the LoRA adapter id this
    # request decodes under; None = the base model (identity slot 0).
    # `adapter_slot` is engine bookkeeping — the store slot pinned for
    # this request between admission and release.
    adapter: Optional[str] = None
    adapter_slot: int = 0
    # Each generated token id is put on this queue; None marks completion.
    out: "queue.Queue[Optional[int]]" = field(default_factory=queue.Queue)
    id: str = ""
    # Set by the engine before the terminal None: "stop" (eos) or "length"
    # (max_tokens / context-window cap).
    finish_reason: str = "stop"
    # Cooperative cancellation: a consumer (e.g. the HTTP layer on a stop-
    # sequence match) sets this; the scheduler frees the slot at the next
    # emit instead of decoding to max_tokens.
    cancelled: bool = False
    # Multi-host lockstep bookkeeping (serve/multihost.py): the leader
    # latches `cancelled` into `cancel_latched` at an iteration boundary
    # and broadcasts the latch, so every process observes the
    # cancellation at the same step; `sync_id` names the request across
    # processes.
    cancel_latched: bool = False
    sync_id: Optional[int] = None
    # Telemetry (set by submit()/the scheduler): submission timestamp for
    # queue-wait/TTFT, previous-emit timestamp for inter-token latency, and
    # the submitter's span context so engine-side spans join the request's
    # trace. Followers in lockstep mode leave submit_ts at 0 (the wall
    # clocks aren't comparable across hosts) — their observations skip.
    submit_ts: float = 0.0
    last_emit_ts: float = 0.0
    trace_ctx: Optional[SpanContext] = None
    # Lifecycle event timeline (observability/journey.py): created at
    # submit (or KV-install on a decode-role engine) under the request's
    # trace id; the engine copies it into its JourneyLog at terminal.
    journey: Optional[RequestJourney] = None


@dataclass
class _InFlightStep:
    """Bookkeeping for one dispatched decode step whose host read is
    deferred (the overlapped scheduler's one-deep pipeline). `slots`
    pins the (slot, Request) pairs active at dispatch: a slot released
    before the drain (EOS/budget/cancel at the previous drain, or
    preemption) fails the identity check and its in-flight token — the
    pipeline's one wasted token per finished stream — is masked out
    before emit. `pos_next` snapshots host_positions as of THIS step so
    the context-window release check stays token-exact even after a
    further dispatch has advanced the live array."""

    tokens: Any  # device [B] int32 — this step's sampled tokens
    slots: List[tuple]  # [(slot, Request)] active at dispatch
    pos_next: np.ndarray  # host_positions after this step's increment
    t_dispatch: float = 0.0  # host perf_counter at launch (journey drain latency)


@dataclass
class _InFlightSpecStep:
    """Bookkeeping for one dispatched speculative round whose host read
    is deferred (the pipelined spec scheduler). The verify output stays
    device-resident: round N+1's dispatch chains its inputs off
    `choices`/`sampled` through the jitted accept-mask advance
    (_build_spec_advance) — a device-side data dependency, never a host
    round trip — and `_spec_drain` performs the round's ONE deferred
    read for the host acceptance walk + emits. Same one-step
    slot-release lag and identity-mask semantics as _InFlightStep.
    host_positions is advanced only by the drain, so at drain time it
    IS this round's base position (the emit snapshot)."""

    choices: Any  # device [B, width] int32 — per-position greedy argmax
    sampled: Any  # device [B] int32 — position-0 samples (sampling rows)
    props: Any  # [B, width-1] int32 proposals (device in draft mode,
    #   host numpy in lookup mode; width-1 may be 0 for a plain round)
    positions: Any  # this round's input positions (device when chained)
    k_eff: np.ndarray  # host [B] — per-stream draft length this round
    tried: np.ndarray  # host [B] bool — planned a proposal (EWMA decays
    #   on a lookup no-match even though k_eff was zeroed)
    greedy: np.ndarray  # host [B] bool — acceptance-walk rows
    slots: List[tuple]  # [(slot, Request)] active at dispatch
    t_dispatch: float = 0.0  # host perf_counter at launch (journey drain latency)


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _pad_to_bucket(tokens, cap: int):
    """Right-pad a token list to its power-of-two bucket (capped): the one
    padding rule both the single-shot and chunked prefill paths share.
    Returns host numpy — jit converts, and under a multi-host mesh a
    numpy input is the one form every process can feed identically."""
    true_len = len(tokens)
    bucket = min(_bucket(true_len), cap)
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :true_len] = tokens
    return padded, true_len


class Engine:
    def __init__(
        self,
        cfg: LlamaConfig,
        params: Params,
        ec: Optional[EngineConfig] = None,
        mesh=None,
        model=llama,
        draft: Optional[tuple] = None,  # (draft_cfg, draft_params)
        sync=None,  # serve.multihost.StepSync for multi-host lockstep
        adapters=None,  # serve.adapters.AdapterStore for multi-tenant LoRA
        handoff=None,  # serve.disagg.HandoffManager for role="prefill"
    ):
        """model: the model-family module (models.llama, models.opt, ...)
        implementing forward/init_cache/param_logical_axes/cache_logical_axes.

        adapters: an AdapterStore packing N tenants' LoRA adapters into
        one engine — every jitted function gains (lora_tree, adapter_ids)
        inputs and each batch row gathers its own adapter by slot index,
        so a mixed-tenant batch runs in the single compiled program.

        mesh: optional jax Mesh for sharded serving. Params are laid out
        by parallel.sharding.serve_rules_for(mesh) (tensor-parallel
        heads/mlp/vocab, data-parallel batch, and — when the mesh has a
        "sequence" axis — the dense KV cache's length dim for serving-
        side context parallelism); the KV cache shards the same way, so
        decode collectives ride ICI. Constraint: the tensor axis must
        divide n_kv_heads (llama2-70b: KH=8 => tensor<=8 per replica).

        sync: serve.multihost.StepSync for multi-host lockstep serving —
        process 0 owns HTTP + the queue and broadcasts per-iteration
        events; followers mirror the scheduler (see serve/multihost.py)."""
        import dataclasses as _dc

        # Copy the config before clamping: mutating a caller's (or the
        # default) EngineConfig instance would leak between engines.
        ec = _dc.replace(ec) if ec is not None else EngineConfig()
        self.cfg, self.params, self.ec = cfg, params, ec
        self.model = model
        # The cache may never outrun the model's position space (learned
        # position embeddings silently clamp on OOB lookups), and a prefill
        # fragment must fit in the cache.
        ec.max_seq_len = min(ec.max_seq_len, cfg.max_seq_len)
        ec.max_prefill_len = min(ec.max_prefill_len, ec.max_seq_len)
        B, S = ec.max_batch, ec.max_seq_len

        if ec.kv_cache_dtype not in ("model", "int8"):
            raise ValueError(
                f"kv_cache_dtype {ec.kv_cache_dtype!r} invalid "
                "(expected 'model' or 'int8')"
            )
        if ec.role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role {ec.role!r} invalid (both|prefill|decode)"
            )
        if ec.role != "both" and sync is not None:
            raise ValueError(
                "disaggregated roles are incompatible with lockstep sync "
                "(a gang engine is one replica; split pools across gangs)"
            )
        if ec.max_prefill_len < 1 or ec.max_batch < 1 or ec.max_seq_len < 2:
            raise ValueError(
                f"invalid engine config: max_prefill_len={ec.max_prefill_len} "
                f"max_batch={ec.max_batch} max_seq_len={ec.max_seq_len}"
            )
        self.adapters = adapters
        if adapters is not None and not getattr(
            model, "SUPPORTS_INDEXED_LORA", False
        ):
            raise ValueError(
                f"multi-tenant adapters unsupported for {model.__name__}"
            )

        kv_int8 = ec.kv_cache_dtype == "int8"
        if kv_int8 and not getattr(model, "SUPPORTS_INT8_KV", False):
            raise ValueError(
                f"kv_cache_dtype=int8 unsupported for {model.__name__}"
            )
        cache_dtype = jnp.int8 if kv_int8 else None

        layout = ec.kv_layout
        if layout == "auto":
            layout = (
                "paged" if getattr(model, "SUPPORTS_PAGED", False) else "dense"
            )
        if layout not in ("paged", "dense"):
            raise ValueError(f"kv_layout {layout!r} invalid")
        if layout == "paged" and not getattr(model, "SUPPORTS_PAGED", False):
            raise ValueError(
                f"kv_layout=paged unsupported for {model.__name__}"
            )
        self.paged = layout == "paged"
        if ec.role != "both" and not self.paged:
            # The handoff ships pool pages; the dense slot cache has no
            # page-granular export.
            raise ValueError(
                f"role={ec.role!r} requires the paged kv layout"
            )
        self.handoff = handoff
        if ec.role == "prefill":
            if handoff is None:
                raise ValueError(
                    "role='prefill' needs a serve.disagg.HandoffManager"
                )
            handoff.bind_engine(self)

        self.mesh = mesh
        if mesh is not None:
            from substratus_tpu.parallel.sharding import (
                serve_rules_for, shard_tree,
            )

            self._serve_rules = serve_rules_for(mesh)
            self.params = shard_tree(
                params, mesh, model.param_logical_axes(cfg), self._serve_rules
            )

        if self.paged:
            from substratus_tpu.serve.paged_kv import (
                PageAllocator,
                PrefixRegistry,
                SlotPages,
            )

            bs = ec.page_size
            if bs < 1:
                raise ValueError(f"page_size {bs} invalid")
            if ec.kv_pool_tokens is not None and ec.kv_pool_tokens < 1:
                raise ValueError(
                    f"kv_pool_tokens {ec.kv_pool_tokens} invalid"
                )
            # A single full-length sequence (+ its pad slot) must always fit.
            pool_tokens = (
                B * S if ec.kv_pool_tokens is None else ec.kv_pool_tokens
            )
            pool_tokens = max(pool_tokens, S + bs)
            self.page_size = bs
            self.n_pages = -(-pool_tokens // bs)
            self.max_pages = -(-S // bs)  # block-table width per slot
            # Physical page 0 is the trash page: idle slots' decode writes
            # land there (their block-table rows are zero), never in a live
            # page. The allocator hands out ids 1..n_pages.
            pool = model.init_paged_cache(
                cfg, self.n_pages + 1, bs, dtype=cache_dtype
            )
            if mesh is not None:
                pool = shard_tree(
                    pool,
                    mesh,
                    model.paged_cache_logical_axes(cfg, quantized=kv_int8),
                    self._serve_rules,
                )
            self.cache = pool
            self.block_table = np.zeros((B, self.max_pages), np.int32)
            self.alloc = PageAllocator(self.n_pages, first_page=1)
            self.prefix = (
                PrefixRegistry(self.alloc) if ec.prefix_cache else None
            )
            self.slot_pages = SlotPages(B)
        elif mesh is not None:
            self.cache = shard_tree(
                model.init_cache(cfg, B, S, dtype=cache_dtype),
                mesh,
                model.cache_logical_axes(cfg, quantized=kv_int8),
                self._serve_rules,
            )
        else:
            self.cache = model.init_cache(cfg, B, S, dtype=cache_dtype)
        # Small per-step state lives as HOST numpy and is fed into the
        # jitted functions each call (jit treats numpy inputs as
        # replicated — in multi-host lockstep serving every process feeds
        # the identical value, which is exactly the contract). The RNG key
        # is carried as raw key data for the same reason; the jitted fns
        # wrap/unwrap it at the boundary.
        self.tokens = np.zeros((B,), np.int32)
        self.positions = np.zeros((B,), np.int32)
        self.temps = np.zeros((B,), np.float32)
        self.top_ps = np.ones((B,), np.float32)
        # Per-row adapter slot fed into every jitted call (0 = identity);
        # slot_adapter mirrors the pins so release can unpin.
        self.adapter_ids = np.zeros((B,), np.int32)
        self.slot_adapter: List[int] = [0] * B
        self.key = np.asarray(jax.random.key_data(jax.random.key(0)))

        # Host-side slot bookkeeping (scheduler thread only). host_positions
        # mirrors the device positions array so per-token checks never force
        # a device->host scalar read.
        self.slot_req: List[Optional[Request]] = [None] * B
        self.slot_generated: List[int] = [0] * B
        self.active = np.zeros(B, dtype=bool)
        self.host_positions = np.zeros(B, dtype=np.int64)
        # Emitted tokens per slot (paged preempt-and-resume rebuilds the
        # prompt from these) and admission order (preemption picks the
        # youngest victim, vLLM-style LIFO).
        self.slot_tokens: List[List[int]] = [[] for _ in range(B)]
        self.slot_admit_seq: List[int] = [0] * B
        self._admit_counter = 0
        # Requests to re-admit before the queue: preempted slots (front)
        # and admission backpressure (pool dry at prefill time).
        self._resume: List[Request] = []
        self.stats: Dict[str, int] = {
            "prefill_tokens": 0,
            "prefix_hit_tokens": 0,
            "preemptions": 0,
            "truncated_by_pool": 0,
            "max_active": 0,
            "verify_passes": 0,
            "spec_proposed": 0,
            "spec_accepted": 0,
            "adapter_requests": 0,
            "handoffs": 0,
            "migrations_in": 0,
        }

        # Speculative decoding state. The draft pool shares the target's
        # block tables and page allocation: identical page ids index both
        # pools, and prefix-shared pages hold identical draft KV because
        # shared prefixes are identical prompts (draft prefill always runs
        # over the full prompt, so reused target pages regain their draft
        # entries too).
        if ec.spec_k < 0:
            raise ValueError(f"spec_k {ec.spec_k} invalid")
        self.spec = bool(ec.spec_k)
        # Adaptive per-stream draft length (EngineConfig.spec_threshold):
        # per-slot acceptance-rate EWMA (optimistic 1.0 at admission so
        # new streams start at full spec_k) and the degraded-round
        # counter that paces re-probes. Scheduler-thread state; the
        # load_snapshot read races benignly (torn floats, never torn
        # structure).
        self._spec_ewma = np.ones((B,), np.float64)
        self._spec_degraded = np.zeros((B,), np.int64)
        # draft model proposer, or prompt-lookup when no draft is given
        self.spec_draft = self.spec and draft is not None
        if self.spec_draft and not self.paged:
            # The draft shares the target's page tables; a dense draft
            # cache has no insert path. Prompt-lookup speculation is
            # layout-agnostic (host-side proposals + a multi-token
            # verify), which is what lets it stack with the dense-only
            # fused decode kernel.
            raise ValueError("draft-model spec_k requires the paged kv layout")
        if self.spec_draft:
            self.draft_cfg, draft_params = draft
            self.draft_params = draft_params
            if mesh is not None:
                from substratus_tpu.parallel.sharding import shard_tree

                self.draft_params = shard_tree(
                    draft_params, mesh,
                    model.param_logical_axes(self.draft_cfg), self._serve_rules,
                )
            # Same KV dtype as the target pool: an int8 configuration means
            # int8 for the draft's (larger-per-token-count) traffic too.
            draft_pool = model.init_paged_cache(
                self.draft_cfg, self.n_pages + 1, self.page_size,
                dtype=cache_dtype,
            )
            if mesh is not None:
                draft_pool = shard_tree(
                    draft_pool, mesh,
                    model.paged_cache_logical_axes(
                        self.draft_cfg, quantized=kv_int8
                    ),
                    self._serve_rules,
                )
            self.draft_cache = draft_pool

        self.queue: "queue.Queue[Request]" = queue.Queue()
        # Pull-based admission fast-path (serve/batchgen.py): when set,
        # the scheduler thread pulls the next request DIRECTLY from the
        # source the moment a slot frees — no submit() thread handoff,
        # no queue-wait round trip — which is what keeps an offline
        # batch-generation run's decode batch permanently full. The
        # queue path stays live alongside it (sources only top up).
        self.source = None
        # Migrated-request admission (serve/disagg.py): the HandoffServer
        # enqueues from its connection threads; only the scheduler thread
        # consumes. Held-back migrations (pool dry / adapter pinned) wait
        # in _resume_migrations, in front of fresh ones.
        self._migrations: "queue.Queue" = queue.Queue()
        self._resume_migrations: List = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        self._admitting: Optional[Request] = None
        self._first_decode_done = False
        # Hot weight-swap (docs/serving.md "Zero-downtime rollout"):
        # swap_params() stages _StagedSwap objects here from any thread;
        # only the scheduler thread installs them (at _sync_iterate, on
        # a settled pipeline), so self.params keeps its single-writer
        # contract. weights_version is scheduler-written, snapshot-read.
        self._swap_q: "queue.Queue[_StagedSwap]" = queue.Queue()
        self.weights_version = 0

        # Multi-host lockstep (serve/multihost.py). The sync'd request
        # list replaces the thread-safe queue as the scheduler's source:
        # requests enter it only through _sync_iterate, identically on
        # every process.
        self.sync = sync if (sync is not None and sync.num_processes > 1) else None
        self._sync_seq = 0
        self._sync_reqs: Dict[int, Request] = {}
        self._synced: List[Request] = []

        # Overlapped decode scheduling (one-step-ahead dispatch; see
        # EngineConfig.overlap). Resolution order matters: lockstep
        # gangs run flush-per-step regardless of the config — the event
        # broadcast must observe a settled batch — and a prefill-role
        # engine never decodes at all. Speculative engines DO overlap:
        # the verify round chains on-device through the accept-mask
        # advance, so the two levers multiply instead of cancelling.
        overlap = ec.overlap if ec.overlap is not None else True
        self.overlap = bool(
            overlap
            and ec.role != "prefill"
            and self.sync is None
        )
        # One in-flight step (plain _InFlightStep or _InFlightSpecStep),
        # the pipeline's one-deep queue.
        self._pending = None
        # Device-resident copy of the last dispatched step's sampled
        # tokens (the on-device feedback path) and the per-slot "the
        # host value is newer" mask: admission writes a first token the
        # device hasn't seen, so the next dispatch merges host values
        # for fresh slots over device values for continuing ones.
        self._dev_tokens = None
        self._token_fresh = np.ones((B,), bool)
        self._merge_tokens = jax.jit(
            lambda dev, host, fresh: jnp.where(fresh, host, dev)
        )
        # Idle wake-up: submit()/resubmit()/submit_migration()/
        # set_source()/stop() set this so an idle scheduler admits
        # immediately instead of on the next poll tick; _idle_wait_s is
        # the safety-net re-check period (tests stretch it to prove the
        # event path carries first-token latency).
        self._wake = threading.Event()
        self._idle_wait_s = 0.05

        # Step timeline + SLO telemetry (observability/timeline.py,
        # observability/sketch.py): one bounded flight recorder per
        # engine (written only by the scheduler thread; /debug/stepz
        # and the bench read it), one SLO tracker fed from _emit whose
        # sketches ride load_snapshot() to the gateway's fleet
        # aggregator. The per-iteration accumulators below are
        # scheduler-thread-only scratch, reset at each loop top.
        self.timeline = StepTimeline()
        self.slo = SLOTracker({
            "ttft": ec.slo_ttft_s,
            "inter_token": ec.slo_inter_token_s,
        })
        # Request-journey retention (observability/journey.py): completed
        # journeys for /debug/requestz?id= and the SLO-breach exemplar
        # ring for /debug/slowz. Both lock-guarded: the scheduler (and,
        # for prefill engines, the handoff manager's reader thread) add
        # while HTTP handler threads search.
        self.journey_log = JourneyLog()
        self.slow = SlowRing(ec.slow_journeys)
        # Per-replica monotonic load-report sequence (gateway dedupe of
        # hedged/retried report deliveries): itertools.count is
        # atomic under the GIL, and load_snapshot() is called from
        # HTTP handler threads concurrently.
        self._load_seq = itertools.count(1)
        self._tl_flush_s = 0.0
        self._tl_flush_reasons: List[str] = []
        self._tl_dispatch_s = 0.0
        self._tl_drain_s = 0.0
        self._tl_drain_off_s = 0.0
        self._tl_pool_dry = False
        self._tl_iter_t0 = 0.0

        self._decode_fn = self._build_decode()
        self._sample1_fn = self._build_first_sample()
        self._chunk_fn = partial(self._chunk_prefill_jit, self.model, self.cfg)
        if self.spec_draft:
            self._draft_chunk_fn = partial(
                self._chunk_prefill_jit, self.model, self.draft_cfg
            )
            self._propose_fn = self._build_propose(ec.spec_k)
            # Width-1 rounds (every stream degraded/sampling) still run
            # one draft step so the draft cache stays hole-free — the
            # next wide round's proposal history needs every position
            # below its start written (the proposals are discarded).
            self._propose1_fn = self._build_propose(1)
        if self.spec:
            self._verify_fn = self._build_verify()
            self._spec_advance = self._build_spec_advance()
        if not self.paged:
            self._prefill_fn = partial(self._prefill_jit, self.model, self.cfg)
            self._insert_fn = self._build_insert()
            self._extract_slot, self._restore_slot = self._build_slot_io()
        else:
            self._export_fn, self._import_fn = self._build_page_io()

    # --- jitted device functions -----------------------------------------

    @staticmethod
    def _lora_kw(lora, adapter_ids) -> dict:
        """forward() kwargs for the multi-tenant adapter gather — empty
        when adapters are off, so families without the lora/adapter_ids
        kwargs (and engines without a store) trace exactly as before."""
        if lora is None:
            return {}
        return {"lora": lora, "adapter_ids": adapter_ids}

    @staticmethod
    @partial(jax.jit, static_argnums=(0, 1))
    def _prefill_jit(model, cfg, params, tokens, true_len, lora=None,
                     adapter_ids=None):
        """tokens [1, Sbucket] (right-padded); returns kv fragment + last
        real token's logits."""
        s = tokens.shape[1]
        positions = jnp.arange(s, dtype=jnp.int32)[None, :]
        logits, kv = model.forward(
            params, tokens, cfg, positions=positions,
            **Engine._lora_kw(lora, adapter_ids),
        )
        last = logits[0, true_len - 1]
        return last, kv

    @staticmethod
    @partial(jax.jit, static_argnums=(0, 1), donate_argnums=(3,))
    def _chunk_prefill_jit(model, cfg, params, slot_cache, tokens, offset,
                           true_len, block_table=None, lora=None,
                           adapter_ids=None):
        """One chunk of a long prefill: tokens [1, C] (right-padded) written
        at absolute positions offset..offset+C-1 — into a single-slot dense
        cache, or through a block-table row [1, M] into the paged pool.
        Returns (logits of the last real token, updated cache)."""
        c = tokens.shape[1]
        positions = offset + jnp.arange(c, dtype=jnp.int32)[None, :]
        # Padded tail positions all clamp onto the single slot one past the
        # prompt: real queries never attend it (causal mask), and the first
        # decode step writes that exact slot before reading it. The caller
        # keeps prompts <= max_seq_len - 1 so the slot exists (paged: and
        # allocates pages through that slot).
        positions = jnp.minimum(positions, offset + true_len)
        kw = {} if block_table is None else {"block_table": block_table}
        kw.update(Engine._lora_kw(lora, adapter_ids))
        logits, slot_cache = model.forward(
            params, tokens, cfg, positions=positions, cache=slot_cache, **kw
        )
        return logits[0, true_len - 1], slot_cache

    def _build_propose(self, k: int):
        model, cfg = self.model, self.draft_cfg

        @partial(jax.jit, donate_argnums=(1,))
        def propose(params, cache, block_table, tokens, positions):
            """Draft k greedy tokens for the whole batch: k cheap decode
            steps through the draft's paged pool. Returns (proposals
            [B, k] replicated for the host read, cache)."""

            def step(carry, _):
                cache, tok, pos = carry
                logits, cache = model.forward(
                    params, tok[:, None], cfg, positions=pos[:, None],
                    cache=cache, block_table=block_table,
                )
                nxt = logits[:, 0].argmax(-1).astype(jnp.int32)
                return (cache, nxt, pos + 1), nxt

            (cache, _, _), props = jax.lax.scan(
                step, (cache, tokens, positions), None, length=k
            )
            return self._replicated(jnp.swapaxes(props, 0, 1)), cache

        return propose

    def _replicated(self, *xs):
        """Pin small outputs that the scheduler reads back to host to a
        fully-replicated layout. Under a (multi-host) mesh the compiler is
        otherwise free to leave them sharded, which would make
        np.asarray() on them non-addressable on some process; without a
        mesh this is a no-op constraint."""
        if self.mesh is None:
            return xs if len(xs) > 1 else xs[0]
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        out = tuple(jax.lax.with_sharding_constraint(x, rep) for x in xs)
        return out if len(out) > 1 else out[0]

    def _build_verify(self):
        cfg, ec, model, paged = self.cfg, self.ec, self.model, self.paged

        @partial(jax.jit, donate_argnums=(1,))
        def verify(params, cache, block_table, tokens, props, positions0,
                   temps, top_ps, key_data, lora=None, adapter_ids=None):
            """ONE target forward over [last, d1..dk] per slot
            ([B, k+1]); `tokens` and `props` arrive separately (tokens
            may be the previous round's device-resident output — the
            concat is a device op, never a host round trip). A width-1
            call (props [B, 0]) IS a plain decode step: one position,
            choices[:, 0] the greedy token — which is what lets
            degraded/sampling rounds share this code path with no
            pipeline flush. Returns (greedy choices [B, k+1],
            position-0 samples [B] for sampling slots, cache, key
            data)."""
            block_tokens = jnp.concatenate(
                [tokens[:, None], props.astype(jnp.int32)], axis=1
            )
            s = block_tokens.shape[1]
            positions = (
                positions0[:, None]
                + jnp.arange(s, dtype=jnp.int32)[None, :]
            )
            logits, cache = model.forward(
                params, block_tokens, cfg, positions=positions, cache=cache,
                **({"block_table": block_table} if paged else {}),
                **Engine._lora_kw(lora, adapter_ids),
            )
            choices = logits.argmax(-1).astype(jnp.int32)
            key, subkey = jax.random.split(jax.random.wrap_key_data(key_data))
            sampled = sample(
                logits[:, 0], subkey, temps, top_k=ec.top_k, top_p=top_ps
            )
            choices, sampled, kd = self._replicated(
                choices, sampled, jax.random.key_data(key)
            )
            return choices, sampled, cache, kd

        return verify

    def _build_spec_advance(self):
        """The pipelined spec scheduler's on-device token feedback: from
        an UNDRAINED verify round's device outputs, compute the next
        round's (tokens, positions) without reading anything back — the
        accept-mask analogue of _merge_tokens. Replays the host
        acceptance walk as vectorized device ops: per greedy row the
        longest matching proposal prefix, full acceptance advancing
        k_eff with the last proposal as the seed (no bonus token — the
        draft never wrote its kv), a mismatch advancing accepted+1 with
        the verify's correction; sampling and degraded rows advance one
        position. Freshly admitted rows take the host values admission
        wrote (same `jnp.where(fresh, host, dev)` idiom as plain
        overlap). Shapes are static per verify width, so each width
        traces once."""
        max_pos = self.ec.max_seq_len - 1

        @jax.jit
        def advance(choices, sampled, props, k_eff, greedy, pos0,
                    host_tokens, host_positions, fresh):
            kmax = props.shape[1]
            if kmax > 0:
                m = props == choices[:, :-1]
                valid = (
                    jnp.arange(kmax, dtype=jnp.int32)[None, :]
                    < k_eff[:, None]
                )
                run = jnp.cumprod(
                    (m & valid).astype(jnp.int32), axis=1
                )
                accepted = run.sum(axis=1).astype(jnp.int32)
                full = (accepted == k_eff) & (k_eff > 0)
                last_prop = jnp.take_along_axis(
                    props, jnp.maximum(k_eff - 1, 0)[:, None], axis=1
                )[:, 0]
                corr = jnp.take_along_axis(
                    choices, accepted[:, None], axis=1
                )[:, 0]
                adv_greedy = jnp.where(full, k_eff, accepted + 1)
                tok_greedy = jnp.where(full, last_prop, corr)
            else:
                # Width-1 round: nothing proposed anywhere — every row
                # is a plain decode row this round.
                adv_greedy = jnp.ones_like(k_eff)
                tok_greedy = choices[:, 0]
            adv = jnp.where(greedy, adv_greedy, 1)
            tok = jnp.where(greedy, tok_greedy, sampled).astype(jnp.int32)
            nxt = jnp.minimum(pos0 + adv, max_pos).astype(jnp.int32)
            tok = jnp.where(fresh, host_tokens, tok)
            nxt = jnp.where(fresh, host_positions, nxt)
            return tok, nxt

        return advance

    def _build_slot_io(self):
        @jax.jit
        def extract(cache, slot):
            return jax.tree.map(
                lambda x: jax.lax.dynamic_slice_in_dim(x, slot, 1, axis=1),
                cache,
            )

        @partial(jax.jit, donate_argnums=(0,))
        def restore(cache, slot_cache, slot):
            return jax.tree.map(
                lambda full, one: jax.lax.dynamic_update_slice_in_dim(
                    full, one, slot, axis=1
                ),
                cache,
                slot_cache,
            )

        return extract, restore

    def _build_page_io(self):
        """Page-granular pool I/O for the disaggregated handoff
        (serve/disagg.py): export gathers a request's pages out of the
        pool, import scatters transferred pages into freshly allocated
        ones. `ids` is bucket-padded by the caller (padding ids point at
        the trash page, physical page 0) so each power-of-two page count
        compiles once."""
        from substratus_tpu.ops.quant import dequantize_kv, quantize_kv

        @jax.jit
        def export(cache, ids):
            return {
                key: self._replicated(jnp.take(cache[key], ids, axis=1))
                for key in cache
            }

        @partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
        def import_(convert, cache, ids, frag):
            out = dict(cache)
            if convert == "quantize":
                # Model-dtype pages arriving at an int8 pool: the same
                # per-vector quantization the pool's own writes use.
                for name in ("k", "v"):
                    q, s = quantize_kv(frag[name])
                    out[name] = cache[name].at[:, ids].set(q)
                    out[f"{name}_scale"] = (
                        cache[f"{name}_scale"].at[:, ids].set(s)
                    )
            elif convert == "dequantize":
                for name in ("k", "v"):
                    vals = dequantize_kv(
                        frag[name], frag[f"{name}_scale"],
                        cache[name].dtype,
                    )
                    out[name] = cache[name].at[:, ids].set(vals)
            else:
                for name in cache:
                    out[name] = cache[name].at[:, ids].set(
                        frag[name].astype(cache[name].dtype)
                    )
            return out

        return export, import_

    def _build_insert(self):
        @partial(jax.jit, donate_argnums=(0,))
        def insert(cache, kv, slot):
            # kv: {k, v} fragment [L, 1, Sb, KH, hd] (activation layout,
            # bf16 from prefill) -> cache layout (quantized when int8),
            # written into cache[:, slot, :, :Sb].
            from substratus_tpu.ops.decode_attention import pack_fragment

            frag = pack_fragment(cache, kv)
            return {
                key: jax.lax.dynamic_update_slice(
                    cache[key], frag[key],
                    (0, slot) + (0,) * (cache[key].ndim - 2),
                )
                for key in cache
            }

        return insert

    def _build_decode(self):
        cfg, ec, model, paged = self.cfg, self.ec, self.model, self.paged

        @partial(jax.jit, donate_argnums=(1,))
        def decode(params, cache, block_table, tokens, positions, temps,
                   top_ps, key_data, lora=None, adapter_ids=None):
            logits, cache = model.forward(
                params,
                tokens[:, None],
                cfg,
                positions=positions[:, None],
                cache=cache,
                **({"block_table": block_table} if paged else {}),
                **Engine._lora_kw(lora, adapter_ids),
            )
            key, subkey = jax.random.split(jax.random.wrap_key_data(key_data))
            next_tokens = sample(
                logits[:, 0], subkey, temps, top_k=ec.top_k, top_p=top_ps
            )
            next_tokens, kd = self._replicated(
                next_tokens, jax.random.key_data(key)
            )
            return next_tokens, cache, kd

        return decode

    def _build_first_sample(self):
        ec = self.ec

        @jax.jit
        def first_sample(last_logits, key_data, temp, top_p):
            """Sample the first generated token from prefill logits;
            returns (token [1], new key data), both replicated for the
            scheduler's host read."""
            key, subkey = jax.random.split(
                jax.random.wrap_key_data(key_data)
            )
            first = sample(
                last_logits[None, :], subkey, temp, top_k=ec.top_k,
                top_p=top_p,
            )
            return self._replicated(first, jax.random.key_data(key))

        return first_sample

    # --- scheduler --------------------------------------------------------

    def _lora_inputs(self):
        """(lora_tree, adapter_ids) for the jitted batch calls — (None,
        None) when multi-tenant serving is off, so legacy engines trace
        the exact pre-adapter signature."""
        if self.adapters is None:
            return None, None
        return self.adapters.device_tree(self.mesh), self.adapter_ids

    def submit(self, req: Request) -> Request:
        if self.sync is not None and not self.sync.leader:
            raise RuntimeError(
                "follower engine: requests arrive via the leader broadcast"
            )
        if self.ec.role == "decode":
            raise RuntimeError(
                "decode-role engine: requests arrive as KV migrations "
                "from the prefill tier (serve/disagg.py)"
            )
        if req.adapter is not None:
            from substratus_tpu.serve.adapters import UnknownAdapter

            # Reject unservable adapters in the CALLER's thread so the
            # HTTP layer can 404 before anything queues; actual loading
            # and pinning happen at admission on the scheduler thread.
            if self.adapters is None or not self.adapters.known(req.adapter):
                raise UnknownAdapter(req.adapter)
        if self.error is not None:
            req.finish_reason = "error"
            req.out.put(None)  # engine is dead; never strand the caller
            return req
        if self.ec.max_queue is not None:
            # Approximate (another submitter may race the read) but the
            # bound only needs to hold the queue near its limit, not
            # exactly at it — overload control, not a semaphore.
            depth = self.queue.qsize()
            if depth >= self.ec.max_queue:
                raise EngineOverloaded(depth)
        req.submit_ts = time.perf_counter()
        if req.trace_ctx is None:
            req.trace_ctx = tracer.current_context()
        if req.journey is None:
            req.journey = RequestJourney(
                trace_id=(
                    req.trace_ctx.trace_id if req.trace_ctx else None
                ),
                rid=req.id or None, origin=self.ec.role,
                cap=self.ec.journey_events,
            )
        req.journey.record(
            "submit", queue=self.queue.qsize(),
            prompt_tokens=len(req.prompt_tokens),
        )
        self.queue.put(req)
        self._wake.set()
        if self.error is not None:
            # The scheduler may have died between the check above and the
            # put — its one-time queue drain could have run before the put,
            # stranding the request. error is always set BEFORE the drain,
            # so re-checking here guarantees a terminal marker either way
            # (a duplicate None in a dead request's queue is harmless).
            req.finish_reason = "error"
            req.out.put(None)
        return req

    def resubmit(self, req: Request) -> None:
        """Re-board a request that already passed admission control once
        (handoff requeue after a decode-worker loss, serve/disagg.py):
        bypasses the max_queue bound — shedding an accepted request
        halfway through its stream would convert a worker failure into
        a client-visible 429."""
        if self.error is not None:
            req.finish_reason = "error"
            req.out.put(None)
            return
        if req.journey is not None:
            req.journey.record("requeue", queue=self.queue.qsize())
        self.queue.put(req)
        self._wake.set()
        if self.error is not None:  # same submit() race: never strand it
            req.finish_reason = "error"
            req.out.put(None)

    def submit_migration(self, mig) -> None:
        """Board a migrated request (serve.disagg.Migration): KV pages
        already computed by a prefill engine — admission installs them
        without recompute. Called from HandoffServer connection threads;
        the scheduler thread is the only consumer."""
        if self.ec.role != "decode":
            raise RuntimeError(
                f"role={self.ec.role!r} engine cannot accept migrations"
            )
        if self.error is not None:
            mig.req.finish_reason = "error"
            mig.req.out.put(None)
            return
        self._migrations.put(mig)
        self._wake.set()
        if self.error is not None:
            mig.req.finish_reason = "error"
            mig.req.out.put(None)

    def set_source(self, source) -> None:
        """Attach (or detach, with None) a pull-based request source —
        the batch-generation admission fast-path. The source's pull()
        runs on the SCHEDULER thread (on the lockstep leader: inside
        _sync_iterate, so pulled requests broadcast like submitted
        ones); it must return a fully-formed Request (with an out sink)
        or None, and pending() must say whether pull() could yield.
        Sources are consulted after the resume list and the submit()
        queue, so interactive traffic always boards first."""
        if source is not None and self.ec.role == "decode":
            raise RuntimeError(
                "decode-role engine: requests arrive as KV migrations, "
                "not from a pull source"
            )
        if source is not None and self.sync is not None and not self.sync.leader:
            raise RuntimeError(
                "follower engine: the leader owns the source; followers "
                "receive pulled requests via the broadcast"
            )
        self.source = source
        self._wake.set()

    def swap_params(
        self,
        new_params,
        version: Optional[int] = None,
        *,
        source: str = "swap",
        wait: bool = True,
        timeout_s: float = 120.0,
    ) -> Optional[int]:
        """Hot weight-swap: replace the served parameter tree in place on
        a live engine (docs/serving.md "Zero-downtime rollout").

        Callable from any thread. The new tree must match the served one
        in treedef, shapes, and dtypes — that is what keeps every
        compiled prefill/decode/verify executable (identical avals, no
        recompile); a mismatch is rejected here and the engine keeps
        serving the old weights. Accepted swaps are staged for the
        scheduler thread, which installs them at its next iteration top
        on a settled pipeline (``_flush("swap")``), bumps
        ``weights_version`` (``version``, or current+1 when None), and
        records a journey event of type ``source`` ("swap" |
        "rollout") on every in-flight request. In-flight streams keep
        their KV caches, positions, and RNG state: a swap to
        value-identical weights is token-exact across the boundary.

        On a lockstep gang the LEADER's staged swap sets the barrier:
        its version rides the per-iteration event broadcast and every
        process installs its own locally staged params on that same
        iteration (stage with ``wait=False`` on followers first; a
        follower with nothing staged within 60s errors the gang). The
        broadcast version wins over a follower's ``version`` argument.

        With ``wait`` (default) blocks until the scheduler applied the
        swap and returns the new version; ``wait=False`` returns None
        immediately (gang followers, fire-and-forget rollouts).
        """
        if source not in ("swap", "rollout"):
            raise ValueError(f"swap source {source!r} invalid (swap|rollout)")
        if self.error is not None:
            raise RuntimeError("engine is dead") from self.error
        if self._thread is None or self._stop.is_set():
            raise RuntimeError("swap_params needs a running engine")
        cur_leaves, cur_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_params)
        mismatch = None
        if new_def != cur_def:
            mismatch = f"treedef differs ({new_def} vs served {cur_def})"
        else:
            for i, (cur, new) in enumerate(zip(cur_leaves, new_leaves)):
                if cur.shape != new.shape or cur.dtype != new.dtype:
                    mismatch = (
                        f"leaf {i}: {new.shape}/{new.dtype} vs served "
                        f"{cur.shape}/{cur.dtype}"
                    )
                    break
        if mismatch is not None:
            METRICS.inc(
                "substratus_serve_weight_swaps_total",
                {"outcome": "rejected"},
            )
            raise ValueError(
                f"swap_params rejected: {mismatch} — matching structure "
                "is the no-recompile contract; load a checkpoint of the "
                "served architecture (or drain and restart for a "
                "different one)"
            )
        sw = _StagedSwap(new_params, version, source)
        self._swap_q.put(sw)
        self._wake.set()
        if not wait:
            return None
        if not sw.done.wait(timeout=timeout_s):
            raise TimeoutError(
                f"swap_params: scheduler did not apply the swap within "
                f"{timeout_s}s (engine error: {self.error!r})"
            )
        if sw.error is not None:
            raise sw.error
        return sw.applied

    def _apply_swap(self, sw: _StagedSwap, version: int) -> None:
        """Install one staged swap (scheduler thread only). The flush
        settles the one-step-ahead pipeline first so no in-flight step
        mixes two weight versions; structure was validated at staging,
        so every executable keyed on these avals is reused."""
        self._flush("swap")
        new = sw.params
        if self.mesh is not None:
            from substratus_tpu.parallel.sharding import shard_tree

            new = shard_tree(
                new, self.mesh, self.model.param_logical_axes(self.cfg),
                self._serve_rules,
            )
        else:
            # Host-resident trees (snapshot_params, checkpoint loads)
            # transfer once here, not on every decode dispatch; device
            # trees pass through unchanged on the same default device.
            new = jax.device_put(new)
        self.params = new
        self.weights_version = version
        METRICS.inc(
            "substratus_serve_weight_swaps_total", {"outcome": "applied"}
        )
        METRICS.set("substratus_serve_weights_version", version)
        for req in self.slot_req:
            if req is not None and req.journey is not None:
                req.journey.record(sw.source, version=version)
        sw.applied = version
        sw.done.set()

    def _apply_staged_swaps(self) -> None:
        """Drain and install every staged swap (single-process path;
        gangs go through the _sync_iterate barrier instead)."""
        while True:
            try:
                sw = self._swap_q.get_nowait()
            except queue.Empty:
                return
            self._apply_swap(
                sw,
                sw.version if sw.version is not None
                else self.weights_version + 1,
            )

    def _fail_staged_swaps(self, exc: BaseException) -> None:
        """Unblock swap_params() waiters when the scheduler exits with
        their swap still staged (stop or crash)."""
        while True:
            try:
                sw = self._swap_q.get_nowait()
            except queue.Empty:
                return
            sw.error = exc
            sw.done.set()

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=30)

    def _next_request(self) -> Optional[Request]:
        """Resumed/held-back requests board before the public queue."""
        if self._resume:
            return self._resume.pop(0)
        if self.sync is not None:
            # Lockstep mode: the queue is drained only at _sync_iterate;
            # admission pulls from the broadcast-ordered list so every
            # process admits the same requests at the same iteration.
            return self._synced.pop(0) if self._synced else None
        try:
            return self.queue.get_nowait()
        except queue.Empty:
            pass
        if self.source is not None:
            # Continuous refill: the freed slot's replacement boards in
            # this same scheduler iteration, straight off the source.
            return self.source.pull()
        return None

    def _has_pending(self) -> bool:
        if self.sync is not None:
            return bool(self._resume) or bool(self._synced)
        return (
            bool(self._resume)
            or not self.queue.empty()
            or (self.source is not None and self.source.pending())
        )

    def _is_cancelled(self, req: Request) -> bool:
        """Lockstep mode reads the broadcast latch (identical on every
        process at a given iteration); single-process reads the live flag."""
        return req.cancel_latched if self.sync is not None else req.cancelled

    def _sync_iterate(self) -> bool:
        """Top-of-iteration synchronization point. Returns False when the
        engine should stop. In lockstep mode the leader drains its queue
        and broadcasts this iteration's events; every process then applies
        them identically."""
        if self.sync is None:
            self._apply_staged_swaps()
            return not self._stop.is_set()
        # Gangs run flush-per-step: the event broadcast encodes
        # decisions (admissions, cancel latches, stop) every process
        # applies to a settled batch, and the leader's emits feed the
        # consumers whose cancellations the broadcast latches — a
        # pipelined step would tear both. Engine.overlap resolves off
        # under sync; this drains any stray pipeline state and keeps
        # today's lockstep semantics bit-for-bit.
        self._flush("gang")
        from substratus_tpu.serve.multihost import (
            NullSink, decode_events, encode_events,
        )

        if self.sync.leader:
            new: List[Request] = []
            while True:
                try:
                    new.append(self.queue.get_nowait())
                except queue.Empty:
                    break
            if self.source is not None:
                # Pull-source refill rides the same broadcast as
                # submitted requests: the leader tops the gang up to its
                # free slot budget and every process admits identically.
                budget = (
                    self.ec.max_batch
                    - int(self.active.sum())  # sublint: allow[hostsync]: host numpy mirror of the active mask, no device read
                    - len(self._synced)
                    - len(self._resume)
                    - len(new)
                )
                while budget > 0:
                    r = self.source.pull()
                    if r is None:
                        break
                    new.append(r)
                    budget -= 1
            for r in new:
                self._sync_seq += 1
                r.sync_id = self._sync_seq
            cancels = [
                i for i, r in self._sync_reqs.items()
                if r.cancelled and not r.cancel_latched
            ]
            stop = self._stop.is_set()
            # Swap barrier: one staged swap per iteration rides the
            # broadcast as its target version; every process installs
            # its OWN locally staged params at this same iteration
            # (below), so the gang changes weights in lockstep. Not
            # popped when stopping — the loop's exit path fails the
            # waiter instead of stranding it.
            leader_sw = None
            if not stop:
                try:
                    leader_sw = self._swap_q.get_nowait()
                except queue.Empty:
                    pass
            swap_version = None
            if leader_sw is not None:
                swap_version = (
                    leader_sw.version if leader_sw.version is not None
                    else self.weights_version + 1
                )
            self.sync.broadcast(
                encode_events(new, cancels, stop, swap=swap_version)
            )
            msg = {"cancels": cancels, "stop": stop, "swap": swap_version}
        else:
            leader_sw = None
            msg = decode_events(self.sync.broadcast(None))
            new = []
            for d in msg["reqs"]:
                self._sync_seq += 1  # mirrors the leader's numbering
                new.append(
                    Request(
                        prompt_tokens=d["p"],
                        max_tokens=d["m"],
                        temperature=d["t"],
                        top_p=d["tp"],
                        eos_token_id=d["e"],
                        id=d["id"],
                        adapter=d.get("ad"),
                        out=NullSink(),
                        sync_id=d["sid"],
                    )
                )
        for r in new:
            self._sync_reqs[r.sync_id] = r
            self._synced.append(r)
        for cid in msg["cancels"]:
            r = self._sync_reqs.get(cid)
            if r is not None:
                r.cancel_latched = True
        if msg["stop"]:
            self._stop.set()
            return False
        swap_version = msg.get("swap")
        if swap_version is not None:
            if self.sync.leader:
                sw = leader_sw
            else:
                # The leader committed the gang to swap on THIS
                # iteration; this process's params arrive through its own
                # control plane's swap_params(wait=False) call. A bounded
                # wait keeps a misconfigured rollout from wedging the
                # gang silently — timing out errors the engine (the
                # JobSet failurePolicy restarts the gang, docs/rl.md
                # "Failure semantics").
                try:
                    sw = self._swap_q.get(timeout=60.0)
                except queue.Empty:
                    raise RuntimeError(
                        "gang swap barrier: leader swapped to "
                        f"weights_version {swap_version} but no params "
                        "were staged on this process within 60s — call "
                        "swap_params(..., wait=False) on every process"
                    )
            # The broadcast version wins over a follower's own argument:
            # the whole gang must agree on what it now serves.
            self._apply_swap(sw, int(swap_version))
        return True

    def _admit(self) -> int:
        """Fill free slots from the request queue (prefill + insert);
        returns how many requests boarded this iteration.

        Admission is capped per scheduler iteration so a burst of arrivals
        can't starve in-flight decodes: each loop admits a few prefills,
        then every active slot advances a token."""
        admitted = self._admit_migrations()
        # No in-flight decodes -> nothing to starve: fill freely (decode
        # steps cost the same at any occupancy, so boarding everyone first
        # is strictly better for TTFT). A pull source (batch generation,
        # serve/batchgen.py) also fills freely: the cap exists to protect
        # in-flight streams' inter-token latency, and an offline run's
        # only objective is keeping every slot busy — throttling refill
        # to one slot per iteration just leaves slots idle for a step
        # after a synchronized completion wave.
        cap = (
            max(1, self.ec.max_batch // 4)
            if self.active.any() and self.source is None
            else self.ec.max_batch
        )
        while (
            admitted < cap
            and self._has_pending()
            and not self.active.all()
        ):
            req = self._next_request()
            if req is None:
                break
            self._admitting = req
            verdict = self._acquire_adapter(req)
            if verdict == "dead":
                self._admitting = None
                continue
            if verdict == "wait":
                # Transient: every adapter slot is pinned by an active
                # request. Hold at the front; decoding slots will unpin.
                if req.journey is not None:
                    req.journey.record_once("adapter_wait")
                self._admitting = None
                self._resume.insert(0, req)
                break
            slot = int(np.flatnonzero(~self.active)[0])
            # Queue wait is submission -> first prefill; a preempted
            # request re-boarding (last_emit_ts set) already paid it.
            if req.submit_ts and not req.last_emit_ts:
                METRICS.observe(
                    "substratus_serve_queue_wait_seconds",
                    time.perf_counter() - req.submit_ts,
                )
            if req.journey is not None:
                wait_us = (
                    int((time.perf_counter() - req.submit_ts) * 1e6)
                    if req.submit_ts and not req.last_emit_ts else 0
                )
                req.journey.record("admit", slot=slot, wait_us=wait_us)
            t_prefill = time.perf_counter()
            with tracer.span(
                "engine.prefill", parent=req.trace_ctx,
                request_id=req.id, slot=slot,
                prompt_tokens=len(req.prompt_tokens),
            ):
                if self.paged:
                    ok = self._admit_paged(req, slot)
                else:
                    ok = self._admit_dense(req, slot)
            METRICS.observe(
                "substratus_serve_phase_seconds",
                time.perf_counter() - t_prefill,
                {"phase": "prefill"},
            )
            self._admitting = None
            if not ok:
                # Pool dry even after eviction: hold the request at the
                # front of the line; decoding slots will free pages. The
                # adapter pin drops too — re-admission re-acquires.
                if req.journey is not None:
                    req.journey.record_once("pool_wait")
                self._release_adapter_pin(req)
                self._resume.insert(0, req)
                # Timeline: this iteration's admission time was spent
                # waiting on pages, not prefilling — attribute the
                # bubble to capacity (pool_dry), not host speed.
                self._tl_pool_dry = True
                break
            admitted += 1
        self.stats["max_active"] = max(
            self.stats["max_active"], int(self.active.sum())  # sublint: allow[hostsync]: self.active is a host numpy mirror, no device read
        )
        return admitted

    def _admit_migrations(self) -> int:
        """Board migrated requests (decode role, serve/disagg.py): pages
        arrive precomputed, so admission is an allocation + one scatter —
        no model forward, no starvation concern, hence no per-iteration
        cap beyond free slots. Pool-dry migrations hold at the front
        (decoding slots will free pages); they are never preempted FOR —
        a migration is cheaper to delay than a decode is to evict."""
        admitted = 0
        while (
            (self._resume_migrations or not self._migrations.empty())
            and not self.active.all()
        ):
            if self._resume_migrations:
                mig = self._resume_migrations.pop(0)
            else:
                try:
                    mig = self._migrations.get_nowait()
                except queue.Empty:
                    break
            verdict = self._acquire_adapter(mig.req)
            if verdict == "dead":
                continue
            if verdict == "wait":
                self._resume_migrations.insert(0, mig)
                break
            if not self._install_migration(mig):
                self._release_adapter_pin(mig.req)
                self._resume_migrations.insert(0, mig)
                self._tl_pool_dry = True  # held for pages, same bubble
                break
            admitted += 1
        return admitted

    def _install_migration(self, mig) -> bool:
        """Allocate pages for one migration and scatter its transferred
        KV in; False = pool dry (hold the migration, nothing leaked)."""
        req = mig.req
        n = mig.pages["k"].shape[1]
        owned = self._try_alloc(n)
        if owned is None:
            return False
        slot = int(np.flatnonzero(~self.active)[0])
        self.slot_pages.assign(slot, [], owned)
        row = np.zeros((self.max_pages,), np.int32)
        row[:n] = owned
        self.block_table[slot] = row
        cap = _bucket(n, 1)
        ids = np.zeros((cap,), np.int32)  # padding scatters to trash page 0
        ids[:n] = owned
        frag = {}
        for name, a in mig.pages.items():
            if cap != n:
                pad = np.zeros((a.shape[0], cap - n) + a.shape[2:], a.dtype)
                a = np.concatenate([a, pad], axis=1)
            frag[name] = a
        self.cache = self._import_fn(mig.convert, self.cache, ids, frag)
        self.stats["migrations_in"] += 1

        true_len = mig.true_len
        self.slot_req[slot] = req
        self.slot_generated[slot] = 0
        self.slot_adapter[slot] = req.adapter_slot
        self.adapter_ids[slot] = req.adapter_slot
        self.active[slot] = True
        self.host_positions[slot] = true_len
        self.slot_tokens[slot] = []
        self._admit_counter += 1
        self.slot_admit_seq[slot] = self._admit_counter
        self.tokens[slot] = mig.first_token
        self._token_fresh[slot] = True  # next dispatch feeds the host value
        self.positions[slot] = true_len
        self.temps[slot] = req.temperature
        self.top_ps[slot] = req.top_p
        if req.journey is not None:
            req.journey.record(
                "install", slot=slot, pages=n, tokens=true_len
            )
        # The first token was sampled on the prefill engine but never
        # delivered — this emit is its delivery (the whole stream flows
        # from the decode tier).
        self._emit(slot, mig.first_token)
        return True

    def _handoff_request(self, req: Request, slot: int, first_id: int,
                         true_len: int) -> None:
        """Prefill role: export the admitted slot's pages, free the slot,
        and hand (pages + first token + sampling state) to the transfer
        layer. The slot never activates — the decode tier owns the rest
        of the request's lifecycle. The page export gathers from the
        live pool, so it must observe a settled batch — a prefill-role
        engine never decodes (overlap resolves off), making this flush a
        no-op guard that pins the invariant."""
        self._flush("handoff")
        pages = list(self.slot_pages.pages[slot])
        n = len(pages)
        cap = _bucket(n, 1)
        ids = np.zeros((cap,), np.int32)
        ids[:n] = pages
        frag = self._export_fn(self.cache, ids)
        host = {
            key: np.asarray(v)[:, :n]  # sublint: allow[hostsync]: the handoff IS a device->host transfer — one gather read per migrated request
            for key, v in frag.items()
        }
        self.slot_pages.release(slot, self.alloc)
        self.block_table[slot] = 0
        self._release_adapter_pin(req)
        self.stats["handoffs"] += 1
        if req.journey is not None:
            req.journey.record("ship", tokens=true_len, pages=n)
        self.handoff.ship(req, host, true_len, first_id)

    def _acquire_adapter(self, req: Request) -> str:
        """Resolve + pin the request's adapter before prefill. Returns
        'ok' (adapter_slot set; 0 = base), 'wait' (every store slot is
        pinned — transient, hold the request), or 'dead' (adapter
        unknown/unloadable — request finished with an error marker)."""
        req.adapter_slot = 0
        if req.adapter is None:
            return "ok"
        from substratus_tpu.serve.adapters import (
            AdapterCapacityError,
            UnknownAdapter,
        )

        try:
            if self.adapters is None:
                raise UnknownAdapter(req.adapter)
            req.adapter_slot = self.adapters.acquire(req.adapter)
            self.stats["adapter_requests"] += 1
            return "ok"
        except AdapterCapacityError:
            return "wait"
        except (UnknownAdapter, OSError, ValueError) as e:
            # The artifact vanished (or corrupted) between submit()'s
            # known() check and admission: fail THIS request, not the
            # engine.
            logging.getLogger(__name__).warning(
                "adapter %r failed to load for request %s: %s",
                req.adapter, req.id, e,
            )
            req.finish_reason = "error"
            self._journey_end(req, "error", cause="adapter")
            req.out.put(None)
            if req.sync_id is not None:
                self._sync_reqs.pop(req.sync_id, None)
            return "dead"

    def _release_adapter_pin(self, req: Request) -> None:
        if self.adapters is not None and req.adapter_slot:
            self.adapters.release(req.adapter_slot)
        req.adapter_slot = 0

    def _prefill_lora(self, req: Request):
        """(lora_tree, [1]-shaped adapter id) for one request's prefill
        dispatch; (None, None) when multi-tenant serving is off."""
        if self.adapters is None:
            return None, None
        return (
            self.adapters.device_tree(self.mesh),
            np.array([req.adapter_slot], np.int32),
        )

    def _admit_dense(self, req: Request, slot: int) -> bool:
        # Keep the newest tokens that fit the cache (minus one slot for
        # generation); prompts longer than one prefill bucket run as a
        # sequence of chunked prefills against the slot's cache.
        keep = self.ec.max_seq_len - 1
        prompt = req.prompt_tokens[-keep:]
        true_len = len(prompt)
        lora, ids1 = self._prefill_lora(req)
        if true_len <= self.ec.max_prefill_len:
            padded, true_len = _pad_to_bucket(
                prompt, self.ec.max_prefill_len
            )
            last_logits, kv = self._prefill_fn(
                self.params, padded, true_len, lora, ids1
            )
            self.cache = self._insert_fn(self.cache, kv, slot)
        else:
            last_logits = self._chunked_prefill(prompt, slot, lora, ids1)
        self.stats["prefill_tokens"] += true_len
        METRICS.inc("substratus_serve_prefill_tokens_total", by=true_len)
        if req.journey is not None:
            req.journey.record(
                "prefill", tokens=true_len,
                chunks=max(1, -(-true_len // self.ec.max_prefill_len)),
            )
        self._finalize_admit(req, slot, last_logits, true_len)
        return True

    def _admit_paged(self, req: Request, slot: int) -> bool:
        """Paged admission: match shared prefix pages, allocate the rest,
        chunk-prefill only the unshared remainder through the slot's
        block-table row, then publish this prompt's full pages."""
        from substratus_tpu.serve.paged_kv import chain_entries

        bs = self.page_size
        keep = self.ec.max_seq_len - 1
        # Degenerate empty prompt: run one pad token through the model so
        # first-token logits exist (same tolerance as the dense path).
        prompt = req.prompt_tokens[-keep:] or [0]
        true_len = len(prompt)

        # Prefix chains are salted with the adapter id: K/V written
        # under one tenant's wk/wv deltas must never seed another
        # tenant's (or the base model's) prompt.
        entries = (
            chain_entries(prompt, bs, salt=req.adapter)
            if self.prefix is not None
            else []
        )
        # Reuse at most the pages strictly before the last prompt token:
        # the last token must run through the model for its logits.
        max_shared = (true_len - 1) // bs
        shared = (
            self.prefix.match(entries[:max_shared])
            if self.prefix is not None
            else []
        )
        reuse = len(shared) * bs
        # Claim the shared pages BEFORE allocating owned ones: _try_alloc
        # may evict registry entries under pressure, and an unclaimed
        # matched page could be evicted-then-reallocated into `owned`,
        # aliasing one physical page as both prefix and tail.
        if shared:
            self.prefix.claim(shared)
        # Own pages covering slot-local tokens reuse..true_len (inclusive:
        # bucket-padding clamps one write onto the one-past-prompt slot).
        need = -(-(true_len + 1) // bs) - len(shared)
        owned = self._try_alloc(need)
        if owned is None:
            for pid in shared:
                self.alloc.decref(pid)
            return False
        self.slot_pages.assign(slot, shared, owned)
        pages = self.slot_pages.pages[slot]
        row = np.zeros((self.max_pages,), np.int32)
        row[: len(pages)] = pages
        self.block_table[slot] = row
        bt_row = self.block_table[slot : slot + 1].copy()

        lora, ids1 = self._prefill_lora(req)
        last_logits, self.cache = self._run_chunks(
            self._chunk_fn, self.params, self.cache, prompt, reuse, bt_row,
            lora=lora, adapter_ids=ids1,
        )
        self.stats["prefill_tokens"] += true_len - reuse
        self.stats["prefix_hit_tokens"] += reuse
        METRICS.inc(
            "substratus_serve_prefill_tokens_total", by=true_len - reuse
        )
        if reuse:
            METRICS.inc(
                "substratus_serve_prefix_hit_tokens_total", by=reuse
            )
        if req.journey is not None:
            if reuse:
                req.journey.record("prefix_hit", tokens=reuse)
            req.journey.record(
                "prefill", tokens=true_len - reuse,
                chunks=max(
                    1, -(-(true_len - reuse) // self.ec.max_prefill_len)
                ),
            )

        if self.spec_draft:
            # Draft prefill also starts at `reuse`: the draft pool indexes
            # through the same block table, and shared pages already hold
            # valid draft KV — registered pages are only ever written during
            # the admission that created them (decode/propose writes land at
            # positions >= true_len, past every registered full page), so
            # the invariant holds inductively from the first admission.
            _, self.draft_cache = self._run_chunks(
                self._draft_chunk_fn, self.draft_params, self.draft_cache,
                prompt, reuse, bt_row,
            )

        n_full = true_len // bs
        if self.prefix is not None and n_full:
            self.prefix.register(entries[:n_full], pages[:n_full])
        self._finalize_admit(req, slot, last_logits, true_len)
        return True

    def _run_chunks(self, fn, params, cache, prompt, start: int, bt_row,
                    lora=None, adapter_ids=None):
        """Chunked prefill of prompt[start:] through a block-table row;
        returns (last real token's logits, updated cache)."""
        chunk = self.ec.max_prefill_len
        offset, last_logits = start, None
        while offset < len(prompt):
            t0 = time.perf_counter()
            padded, clen = _pad_to_bucket(
                prompt[offset : offset + chunk], chunk
            )
            last_logits, cache = fn(
                params, cache, padded, offset, clen, block_table=bt_row,
                lora=lora, adapter_ids=adapter_ids,
            )
            offset += clen
            dt = time.perf_counter() - t0
            if self.ec.step_floor_s > dt:
                # Simulated device-step latency applies to prefill chunks
                # too: on a real accelerator every chunk occupies the
                # device, which is exactly the decode-stalling contention
                # the disaggregated split removes (see EngineConfig).
                time.sleep(self.ec.step_floor_s - dt)
        return last_logits, cache

    def _finalize_admit(self, req: Request, slot: int, last_logits,
                        true_len: int) -> None:
        # Sample the first generated token from the prefill logits.
        t_sample = time.perf_counter()
        first, key_out = self._sample1_fn(
            last_logits,
            self.key,
            np.array([req.temperature], np.float32),
            np.array([req.top_p], np.float32),
        )
        self.key = np.asarray(key_out)  # sublint: allow[hostsync]: first-token sample + key readback, once per admission (the "sample" phase)
        first_id = int(first[0])
        METRICS.observe(
            "substratus_serve_phase_seconds",
            time.perf_counter() - t_sample,
            {"phase": "sample"},
        )

        if self.ec.role == "prefill":
            self._handoff_request(req, slot, first_id, true_len)
            return

        self.slot_req[slot] = req
        self.slot_generated[slot] = 0
        self.slot_adapter[slot] = req.adapter_slot
        self.adapter_ids[slot] = req.adapter_slot
        self.active[slot] = True
        self.host_positions[slot] = true_len
        self.slot_tokens[slot] = []
        self._admit_counter += 1
        self.slot_admit_seq[slot] = self._admit_counter
        self.tokens[slot] = first_id
        # The device token array predates this admission: the next
        # dispatch must take this slot's first token from the host.
        self._token_fresh[slot] = True
        # Adaptive speculation starts optimistic for every new stream:
        # the previous tenant's acceptance history must not leak.
        self._spec_ewma[slot] = 1.0
        self._spec_degraded[slot] = 0
        self.positions[slot] = true_len
        self.temps[slot] = req.temperature
        self.top_ps[slot] = req.top_p
        self._emit(slot, first_id)

    # --- paged pool management -------------------------------------------

    def _try_alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages, evicting LRU prefix-registry entries under
        pressure; None (nothing leaked) when the pool is truly dry."""
        got: List[int] = []
        while len(got) < n:
            pid = self.alloc.alloc()
            if pid is not None:
                got.append(pid)
                continue
            if self.prefix is not None and self.prefix.evict_lru():
                continue
            for p in got:
                self.alloc.decref(p)
            return None
        return got

    def _pick_victim(self, exclude: int) -> Optional[int]:
        """Youngest active slot (LIFO preemption preserves the oldest
        requests' progress)."""
        best, best_seq = None, -1
        for slot in np.flatnonzero(self.active):
            slot = int(slot)
            if slot == exclude:
                continue
            if self.slot_admit_seq[slot] > best_seq:
                best, best_seq = slot, self.slot_admit_seq[slot]
        return best

    def _preempt(self, victim: int) -> None:
        """Evict a slot mid-decode: its pages free now; the request (same
        object — cancellation flags stay live) re-boards at the front with
        prompt := prompt + generated-so-far, so re-prefill reconstructs the
        exact state and generation continues seamlessly."""
        req = self.slot_req[victim]
        gen = self.slot_tokens[victim]
        req.prompt_tokens = list(req.prompt_tokens) + gen
        req.max_tokens -= len(gen)
        if req.journey is not None:
            req.journey.record("preempt", generated=len(gen))
        self._release_slot(victim)
        self._resume.insert(0, req)
        self.stats["preemptions"] += 1

    def _ensure_capacity(self, slot: int, upto_pos: Optional[int] = None) -> None:
        """Before this iteration writes at positions up to `upto_pos`
        (default: the next decode write, host_positions[slot]), make sure
        the pages backing them exist — allocating, evicting prefix entries,
        then preempting the youngest other slot, in that order. Last resort
        (single survivor, pool exhausted): finish the request as truncated.
        Writes beyond max_seq_len never need pages (the paged kernel
        redirects past-the-table writes to the trash page)."""
        if not self.active[slot]:
            return  # preempted earlier in this same pass
        if upto_pos is None:
            upto_pos = int(self.host_positions[slot])
        upto_pos = min(upto_pos, self.ec.max_seq_len - 1)
        while upto_pos // self.page_size >= len(self.slot_pages.pages[slot]):
            pn = len(self.slot_pages.pages[slot])
            got = self._try_alloc(1)
            while got is None:
                if self._pending is not None:
                    # Preemption (and the truncation fallback below)
                    # must observe a settled batch: the in-flight step's
                    # drain may release slots and free pages on its own,
                    # and a victim's resume prompt needs every token it
                    # generated. Flush, then retry allocation before
                    # evicting anyone.
                    self._flush("preempt")
                    if not self.active[slot]:
                        return  # the flush released this very slot
                    got = self._try_alloc(1)
                    continue
                victim = self._pick_victim(exclude=slot)
                if victim is None:
                    req = self.slot_req[slot]
                    req.finish_reason = "length"
                    self._journey_end(req, "length", cause="pool")
                    req.out.put(None)
                    if req.sync_id is not None:
                        self._sync_reqs.pop(req.sync_id, None)
                    self._release_slot(slot)
                    self.stats["truncated_by_pool"] += 1
                    return
                self._preempt(victim)
                got = self._try_alloc(1)
            self.slot_pages.append(slot, got[0])
            self.block_table[slot, pn] = got[0]

    def _dispatch(self) -> Optional[_InFlightStep]:
        """Device-only half of one decode step: grow paged capacity from
        the host_positions mirror, feed the previous step's sampled
        tokens back ON-DEVICE (merged with host-side first tokens for
        slots admitted since the last dispatch), launch the jitted step,
        and return the in-flight bookkeeping WITHOUT reading anything
        back. Everything host-blocking belongs in _drain() — under the
        overlapped scheduler it runs one full step later, while this
        step occupies the device. Returns None when capacity handling
        emptied the batch."""
        if self.paged:
            # Grow every slot that will cross a page boundary this step
            # (may flush + preempt or, at the limit, truncate).
            for slot in np.flatnonzero(self.active):
                self._ensure_capacity(int(slot))
            if not self.active.any():
                return None
        lora, adapter_ids = self._lora_inputs()
        if self._dev_tokens is None:
            tok_in = self.tokens
        else:
            # Continuing slots chain the in-flight step's sampled token
            # straight from its device output (JAX async dispatch makes
            # this a device-side data dependency, never a host round
            # trip); freshly (re)admitted slots take their first token
            # from the host array admission wrote.
            tok_in = self._merge_tokens(
                self._dev_tokens, self.tokens, self._token_fresh
            )
        next_tokens, self.cache, key_out = self._decode_fn(
            self.params,
            self.cache,
            self.block_table if self.paged else None,
            tok_in,
            self.positions,
            self.temps,
            self.top_ps,
            self.key,
            lora,
            adapter_ids,
        )
        if self.overlap:
            # The RNG key stays device-resident between steps: reading
            # it back here would block on the step just launched and
            # re-serialize the pipeline. Single-host only — lockstep
            # gangs (overlap off) need the host copy below.
            self.key = key_out
        else:
            self.key = np.asarray(key_out)  # sublint: allow[hostsync]: overlap-off (lockstep) fallback only — the key rides host-side so every gang process feeds identical replicated inputs; the overlapped path above keeps it on device
        self._dev_tokens = next_tokens
        self._token_fresh[:] = False
        # Clamp at the last cache row: active slots are released at the
        # window before reaching it (_emit's hit_window), so the clamp only
        # catches INACTIVE slots, whose positions otherwise drift past the
        # cache every step they sit idle — with the fused decode kernel
        # that drift would become out-of-bounds HBM writes (XLA scatter
        # silently dropped OOB updates; the Pallas DMA does not).
        last = self.ec.max_seq_len - 1
        self.positions = np.minimum(self.positions + 1, last)
        self.host_positions = np.minimum(self.host_positions + 1, last)
        return _InFlightStep(
            tokens=next_tokens,
            slots=[
                (int(s), self.slot_req[int(s)])
                for s in np.flatnonzero(self.active)
            ],
            pos_next=self.host_positions.copy(),
            t_dispatch=time.perf_counter(),
        )

    def _drain(self, step: _InFlightStep) -> None:
        """Host half of one decode step: THE deferred host read, then
        per-slot emits, EOS/budget/window release, and cancellation
        handling for the slots that were active at dispatch. A slot
        whose request was released after that dispatch (EOS at the
        previous drain, preemption, kill) fails the identity check and
        its in-flight token — the pipeline's one wasted token per
        finished stream — never reaches a consumer."""
        host_tokens = np.asarray(step.tokens)  # sublint: allow[hostsync]: THE one host read per decode step — deferred to drain() so under overlap it lands after the NEXT dispatch, hiding every emit under device compute
        t_drained = time.perf_counter()
        for slot, req in step.slots:
            if self.slot_req[slot] is not req:
                continue  # EOS-lag mask: released or re-admitted slot
            if req.journey is not None:
                # Journey events for a dispatch are stamped at drain —
                # the overlap pipeline never stalls for forensics.
                req.journey.record(
                    "drain",
                    lat_us=int((t_drained - step.t_dispatch) * 1e6),
                )
            self.tokens[slot] = host_tokens[slot]
            self._emit(
                slot, int(host_tokens[slot]),
                pos_next=int(step.pos_next[slot]),
            )
        if not self.overlap:
            # Synchronous path (gangs, forced-sync): the next dispatch
            # must feed pure host-side numpy — in lockstep every process
            # replicates the identical input arrays, which is the whole
            # broadcast contract. Device token feedback is overlap-only.
            self._dev_tokens = None
            self._token_fresh[:] = True

    def _flush(self, reason: str) -> None:
        """Drain the in-flight step NOW. Required before anything that
        must observe a settled batch: the lockstep event broadcast
        (reason "gang"), a disaggregated KV handoff ("handoff"), engine
        stop/drain ("drain"), preemption or pool-pressure truncation
        ("preempt"), and a hot weight-swap ("swap" — no in-flight step
        may mix two weight versions). Speculative rounds no longer flush:
        they chain on-device through the accept-mask advance, so the
        historical "spec" reason is retired (steady-state spec traffic
        holds pipeline_flushes_total{reason="spec"} at zero by
        construction)."""
        pending, self._pending = self._pending, None
        if pending is None:
            return
        METRICS.inc(
            "substratus_serve_pipeline_flushes_total", {"reason": reason}
        )
        for slot, req in pending.slots:
            if self.slot_req[slot] is req and req.journey is not None:
                req.journey.record("flush", reason=reason)
        t_flush = time.perf_counter()
        self._drain_any(pending)
        # Timeline bubble accounting: a flush's drain is host work the
        # pipeline could NOT hide (the device sits settled through it).
        self._tl_flush_s += time.perf_counter() - t_flush
        self._tl_flush_reasons.append(reason)
        # The batch is settled; the next dispatch feeds host tokens for
        # every slot (on-device feedback resumes with the step after).
        self._dev_tokens = None
        self._token_fresh[:] = True

    def _dispatch_any(self):
        """The resolved dispatch half: a speculative round (propose +
        multi-token verify) for spec engines, the plain decode step
        otherwise. _decode_step/_step_overlapped/_flush route through
        these two so both step kinds share one pipeline skeleton."""
        return self._spec_dispatch() if self.spec else self._dispatch()

    def _drain_any(self, step) -> None:
        """The matching drain half, type-dispatched on the in-flight
        bookkeeping (a flush may drain either kind)."""
        if isinstance(step, _InFlightSpecStep):
            self._spec_drain(step)
        else:
            self._drain(step)

    def _decode_step(self) -> None:
        """One synchronous iteration: dispatch, model the device step's
        latency, then drain immediately (the overlap-off path —
        lockstep gangs and the forced-sync escape hatch). The simulated
        device-step floor lands BEFORE the host read and the emits: on a
        real accelerator tokens only exist once the device step
        finishes, so a slot freed by an emit is admissible in the very
        next iteration with no artificial dead time. _loop's own floor
        check then sees dt >= floor and never double-sleeps."""
        t_step = time.perf_counter()
        pending = self._dispatch_any()
        self._tl_dispatch_s = time.perf_counter() - t_step
        if pending is None:
            return
        dt_step = time.perf_counter() - t_step
        if self.ec.step_floor_s > dt_step:
            time.sleep(self.ec.step_floor_s - dt_step)
        t_drain = time.perf_counter()
        self._drain_any(pending)
        self._tl_drain_off_s = t_drain - self._tl_iter_t0
        self._tl_drain_s = time.perf_counter() - t_drain

    def _step_overlapped(self) -> None:
        """One pipelined iteration: launch step N, then run step N-1's
        host work while N occupies the device. On a real chip the
        deferred np.asarray overlaps the transfer with compute via JAX
        async dispatch; on CPU the step_floor_s sleep models the device
        window — the floor discounts whatever host work ran under it,
        so steady-state inter-token latency settles at
        max(device_step, host_work) instead of their sum."""
        t_step = time.perf_counter()
        # Dispatch FIRST, then pick up whatever is still pending: the
        # dispatch's capacity handling may _flush("preempt") the
        # previous step itself, and draining it again here would emit
        # duplicate tokens.
        launched = self._dispatch_any()
        self._tl_dispatch_s = time.perf_counter() - t_step
        prev, self._pending = self._pending, launched
        if prev is not None:
            t_drain = time.perf_counter()
            self._drain_any(prev)
            self._tl_drain_off_s = t_drain - self._tl_iter_t0
            self._tl_drain_s = time.perf_counter() - t_drain
            if self._pending is not None:
                # Host work actually hidden under an in-flight step —
                # the overlapped scheduler's win, exported so operators
                # can see how much host time the pipeline absorbs.
                METRICS.observe(
                    "substratus_serve_host_overlap_seconds",
                    time.perf_counter() - t_drain,
                )
        dt_step = time.perf_counter() - t_step
        if self.ec.step_floor_s > dt_step:
            time.sleep(self.ec.step_floor_s - dt_step)

    @staticmethod
    def _prompt_lookup(ctx, k: int, max_n: int = 3):
        """Prompt-lookup proposal: the continuation after the most recent
        earlier occurrence of the context's trailing n-gram (largest n
        first). Returns k tokens, or None when nothing matches — pure
        host work, no model involved; the scan is vectorized numpy so a
        max-context slot costs microseconds, not interpreter loops."""
        a = np.asarray(ctx, np.int32)  # sublint: allow[hostsync]: ctx is a python token list; pure host work by design
        L = a.size
        for n in range(min(max_n, L - 1), 0, -1):
            tgt = a[L - n:]
            # candidate starts j in [0, L-n-1]: the trailing n-gram itself
            # (j = L-n) is excluded by windowing over a[:L-1]
            win = np.lib.stride_tricks.sliding_window_view(a[: L - 1], n)
            hits = np.flatnonzero((win == tgt).all(axis=1))
            if hits.size:
                j = int(hits[-1])  # most recent occurrence
                cont = a[j + n: j + n + k]
                if cont.size:
                    out = np.full((k,), cont[-1], np.int32)
                    out[: cont.size] = cont
                    return out
        return None

    def _plan_spec_round(self):
        """Host-side adaptive-k policy for the next speculative round
        (EngineConfig.spec_threshold): per active slot, pick this
        round's draft length from the stream's acceptance-rate EWMA.
        Sampling slots never speculate (k draft steps + a wide verify
        to emit ONE sampled token is strictly worse than plain decode);
        greedy slots propose k = ceil(ewma * spec_k) while the estimate
        holds, degrade to k = 0 below the threshold, and re-probe with
        k = 1 every spec_probe_every degraded rounds. Returns host
        (k_eff [B], tried [B], greedy [B]); the lookup scan may still
        zero a planned k_eff when no n-gram matches."""
        ec = self.ec
        k_eff = np.zeros((ec.max_batch,), np.int64)
        tried = np.zeros((ec.max_batch,), bool)
        greedy = np.zeros((ec.max_batch,), bool)
        for slot in np.flatnonzero(self.active):
            slot = int(slot)
            if self.slot_req[slot].temperature != 0.0:
                continue
            greedy[slot] = True
            ewma = float(self._spec_ewma[slot])
            if ewma >= ec.spec_threshold:
                k_eff[slot] = min(ec.spec_k, max(1, math.ceil(ewma * ec.spec_k)))
                tried[slot] = True
                self._spec_degraded[slot] = 0
            else:
                self._spec_degraded[slot] += 1
                if self._spec_degraded[slot] >= ec.spec_probe_every:
                    # Probe: one cheap proposal so a stream whose output
                    # turned predictable again can climb back out.
                    self._spec_degraded[slot] = 0
                    k_eff[slot] = 1
                    tried[slot] = True
        return k_eff, tried, greedy

    def _spec_history(self, slot: int):
        """Token history for the lookup scan, extended OPTIMISTICALLY
        through the in-flight round — exact history lags one round
        under the pipeline, and correctness is proposal-independent
        (the verify rejects any mismatch), so the scan assumes the
        in-flight proposals fully accept. In-flight k_eff=0 rows (the
        degraded-probe case) have a genuinely unknown pending token;
        the scan's own 1-token guess stands in for it, and None (no
        guess either) skips speculation for this slot this round."""
        req = self.slot_req[slot]
        keep = self.ec.max_seq_len - 1
        ctx = list(req.prompt_tokens[-keep:] or [0]) + self.slot_tokens[slot]
        p = self._pending
        if p is None or self._token_fresh[slot]:
            # Settled batch, or a slot (re)admitted after the in-flight
            # dispatch: host history is exact.
            return ctx
        ke = int(p.k_eff[slot])
        if ke > 0:
            ctx += [int(x) for x in p.props[slot, :ke]]
        else:
            guess = self._prompt_lookup(ctx, 1)
            if guess is None:
                return None
            ctx.append(int(guess[0]))
        return ctx

    def _spec_dispatch(self) -> Optional[_InFlightSpecStep]:
        """Device-only half of one speculative round: plan per-stream
        draft lengths, run the (pure-numpy) lookup scans — under the
        pipeline this host work executes during the PREVIOUS round's
        device window, which is the point of the split — grow paged
        capacity, chain the previous round's accepted tokens back
        on-device through _build_spec_advance, launch the draft
        proposal and the width-wide verify, and return the in-flight
        bookkeeping WITHOUT reading anything back. The verify width is
        max(k_eff)+1; a round where nothing proposes is a width-1
        verify — exactly a plain decode step, one shared code path and
        NO pipeline flush on the spec<->plain boundary. The host
        acceptance walk belongs in _spec_drain(), one step later."""
        k_eff, tried, greedy = self._plan_spec_round()
        ec = self.ec
        lookup_props = None
        if not self.spec_draft:
            lookup_props = np.zeros((ec.max_batch, ec.spec_k), np.int32)
            for slot in np.flatnonzero(k_eff > 0):
                slot = int(slot)
                ctx = self._spec_history(slot)
                guess = (
                    None if ctx is None
                    else self._prompt_lookup(ctx, int(k_eff[slot]))
                )
                if guess is None:
                    # No n-gram match (or an unknowable in-flight
                    # token): a plain decode row this round. An actual
                    # failed scan decays the EWMA; an unknowable
                    # history does not — it says nothing about the
                    # stream.
                    tried[slot] = ctx is not None
                    k_eff[slot] = 0
                else:
                    lookup_props[slot, : guess.size] = guess
        km = k_eff.max()
        width = int(km) + 1
        if self.paged:
            # Grow every slot for this round's writes. The in-flight
            # round may still advance a slot by up to its own
            # max(1, k_eff) before this one lands, so that slack joins
            # the bound; _pending is re-read per slot because
            # _ensure_capacity may _flush("preempt") mid-loop (after
            # which host_positions is settled and the slack is 0).
            for slot in np.flatnonzero(self.active):
                slot = int(slot)
                p = self._pending
                slack = 0
                if p is not None and not self._token_fresh[slot]:
                    slack = max(1, int(p.k_eff[slot]))
                self._ensure_capacity(
                    slot,
                    int(self.host_positions[slot]) + slack + width - 1,
                )
            if not self.active.any():
                return None
        bt = self.block_table if self.paged else None
        p = self._pending
        if p is None:
            tok_in, pos_in = self.tokens, self.positions
        else:
            # Chain off the undrained round's device-resident verify
            # output (JAX async dispatch makes this a device-side data
            # dependency, never a host round trip); freshly (re)admitted
            # slots merge their host-written first token/position.
            tok_in, pos_in = self._spec_advance(
                p.choices, p.sampled, p.props,
                p.k_eff.astype(np.int32), p.greedy, p.positions,
                self.tokens, self.positions, self._token_fresh,
            )
        if self.spec_draft:
            if width > 1:
                proposals, self.draft_cache = self._propose_fn(
                    self.draft_params, self.draft_cache, bt,
                    tok_in, pos_in,
                )
                props = proposals[:, : width - 1]
            else:
                # Width-1 round: one draft step keeps the draft cache
                # hole-free for the next wide round (proposals
                # discarded; see _propose1_fn in __init__).
                warmed, self.draft_cache = self._propose1_fn(
                    self.draft_params, self.draft_cache, bt,
                    tok_in, pos_in,
                )
                props = warmed[:, :0]
        else:
            props = lookup_props[:, : width - 1]
        lora, adapter_ids = self._lora_inputs()
        choices, sampled, self.cache, key_out = self._verify_fn(
            self.params, self.cache, bt, tok_in, props,
            pos_in, self.temps, self.top_ps, self.key,
            lora, adapter_ids,
        )
        if self.overlap:
            # Key stays device-resident between rounds (reading it back
            # would block on the verify just launched).
            self.key = key_out
        else:
            self.key = np.asarray(key_out)  # sublint: allow[hostsync]: overlap-off fallback only — the key rides host-side so every lockstep process feeds identical replicated inputs; the overlapped path above keeps it on device
        if width > 1:
            # Width-1 rounds are plain decode steps, not verify passes —
            # tokens_per_verify must keep meaning "emitted per wide
            # verify forward".
            self.stats["verify_passes"] += 1
        self._token_fresh[:] = False
        return _InFlightSpecStep(
            choices=choices,
            sampled=sampled,
            props=props,
            positions=pos_in,
            k_eff=k_eff,
            tried=tried,
            greedy=greedy,
            slots=[
                (int(s), self.slot_req[int(s)])
                for s in np.flatnonzero(self.active)
            ],
            t_dispatch=time.perf_counter(),
        )

    def _spec_drain(self, step: _InFlightSpecStep) -> None:
        """Host half of one speculative round: THE deferred host read,
        the per-slot acceptance walk, emits, EOS/budget/window release,
        and the adaptive-k EWMA update. Greedy rows emit the longest
        matching proposal prefix (+ the target's correction on a
        mismatch; full acceptance emits k with no bonus token — the
        draft never wrote the last proposal's kv, so it seeds the next
        round and both caches stay hole-free) — token-exact vs plain
        decode; sampling rows emit the verify's position-0 sample.
        Cache staleness beyond the accepted point is safe: causal
        masking never reads past the query position, and the next round
        rewrites exactly those slots. host_positions is advanced only
        here, so on entry it IS this round's base position; each emit
        carries its own dispatch-time position snapshot (pos0 + i) so
        the context-window release stays token-exact even though the
        live arrays then jump by the whole accepted run."""
        chs = np.asarray(step.choices)  # sublint: allow[hostsync]: THE deferred per-spec-round host read — the acceptance walk + emits land here, under the next round's device window
        smp = np.asarray(step.sampled)  # sublint: allow[hostsync]: same deferred read as chs; one transfer per speculative round
        props = np.asarray(step.props)  # sublint: allow[hostsync]: draft proposals reach host with the round's one deferred read (lookup proposals are already host numpy — a no-op there)
        t_drained = time.perf_counter()
        d = self.ec.spec_ewma_decay
        for slot, req in step.slots:
            if self.slot_req[slot] is not req:
                continue  # EOS-lag mask: released or re-admitted slot
            if req.journey is not None:
                # Stamped at drain, same as the plain path: the round's
                # device window is never stalled for forensics.
                req.journey.record(
                    "drain",
                    lat_us=int((t_drained - step.t_dispatch) * 1e6),
                )
            ke = int(step.k_eff[slot])
            pos0 = int(self.host_positions[slot])
            if not step.greedy[slot]:
                emit_list = [int(smp[slot])]
            else:
                accepted = 0
                while (
                    accepted < ke
                    and props[slot, accepted] == chs[slot, accepted]
                ):
                    accepted += 1
                if ke > 0:
                    if req.journey is not None:
                        req.journey.record(
                            "spec_round", k=ke, accepted=accepted
                        )
                    self.stats["spec_proposed"] += ke
                    self.stats["spec_accepted"] += accepted
                    METRICS.inc(
                        "substratus_serve_spec_proposed_tokens_total",
                        by=ke,
                    )
                    METRICS.inc(
                        "substratus_serve_spec_accepted_tokens_total",
                        by=accepted,
                    )
                    self._spec_ewma[slot] = (
                        d * self._spec_ewma[slot]
                        + (1.0 - d) * (accepted / ke)
                    )
                elif step.tried[slot]:
                    # Planned a proposal but the lookup found nothing:
                    # a zero-acceptance observation (placeholder rows
                    # never skew the proposed/accepted counters).
                    self._spec_ewma[slot] = d * self._spec_ewma[slot]
                if ke > 0 and accepted == ke:
                    emit_list = [int(x) for x in props[slot, :ke]]
                else:
                    emit_list = [int(x) for x in props[slot, :accepted]]
                    emit_list.append(int(chs[slot, accepted]))
            self.tokens[slot] = emit_list[-1]
            for i, tok in enumerate(emit_list, start=1):
                self._emit(slot, tok, pos_next=pos0 + i)
                if self.slot_req[slot] is not req:
                    break  # EOS/budget/window/cancel landed mid-run
            npos = min(pos0 + len(emit_list), self.ec.max_seq_len - 1)
            self.host_positions[slot] = npos
            self.positions[slot] = npos
        if not self.overlap:
            # Synchronous path (gangs, forced-sync): the next dispatch
            # must feed pure host-side numpy — every lockstep process
            # replicates identical input arrays. Device chaining is
            # overlap-only.
            self._dev_tokens = None
            self._token_fresh[:] = True

    def _release_slot(self, slot: int) -> None:
        self.active[slot] = False
        self.slot_req[slot] = None
        self.slot_tokens[slot] = []
        if self.adapters is not None and self.slot_adapter[slot]:
            self.adapters.release(self.slot_adapter[slot])
        self.slot_adapter[slot] = 0
        # Idle rows gather the identity adapter: their decode writes
        # keep happening (static shapes) and must stay adapter-free.
        self.adapter_ids[slot] = 0
        if self.paged:
            self.slot_pages.release(slot, self.alloc)
            # Point the idle slot back at the trash page; its decode writes
            # keep happening (static shapes) and must never land in a page
            # the allocator may hand to someone else.
            self.block_table[slot] = 0

    def _chunked_prefill(self, prompt, slot: int, lora=None,
                         adapter_ids=None):
        """Prefill a prompt longer than one bucket: run bucket-sized chunks
        against the slot's cache (each chunk attends everything before it),
        then restore the slot into the decode cache."""
        slot_cache = self._extract_slot(self.cache, slot)
        last_logits, slot_cache = self._run_chunks(
            self._chunk_fn, self.params, slot_cache, prompt, 0, None,
            lora=lora, adapter_ids=adapter_ids,
        )
        self.cache = self._restore_slot(self.cache, slot_cache, slot)
        return last_logits

    def _journey_end(self, req: Request, reason: str, **data) -> None:
        """Terminal journey bookkeeping: stamp the "end" event exactly
        once, then copy the completed journey into the engine's rings —
        journey_log for /debug/requestz?id= lookups, the slow ring
        (served at /debug/slowz) when any SLO breached mid-flight. Must
        run BEFORE the terminal ``req.out.put(None)``: a disagg
        _RemoteSink ships the journey segment on its done frame."""
        j = req.journey
        if j is None or j.ended:
            return
        j.record("end", reason=reason, **data)
        snap = j.snapshot()
        self.journey_log.add(snap)
        if j.breaches:
            self.slow.add(snap)

    def _slo_exemplar(self, req: Request, slo: str, seconds: float) -> None:
        """One SLO breach observed for this request: mark the journey
        (it lands in the slow ring at terminal) and count the exemplar."""
        j = req.journey
        if j is None:
            return
        j.breach(slo, seconds, self.slo.thresholds.get(slo, 0.0))
        METRICS.inc("substratus_serve_slo_exemplars_total", {"slo": slo})

    def _emit(self, slot: int, token_id: int,
              pos_next: Optional[int] = None):
        """Deliver one token. `pos_next` is the slot's next-write
        position AS OF THE STEP THAT SAMPLED the token: _drain passes
        its dispatch-time snapshot because under overlap the live
        host_positions already advanced for the next in-flight step —
        reading it here would release window-bounded requests one token
        early and break token-exactness vs the synchronous scheduler."""
        req = self.slot_req[slot]
        eos = req.eos_token_id if req.eos_token_id is not None else self.ec.eos_token_id
        self.slot_generated[slot] += 1
        if pos_next is None:
            pos_next = int(self.host_positions[slot])
        hit_eos = token_id == eos
        hit_budget = self.slot_generated[slot] >= req.max_tokens
        hit_window = pos_next + 1 >= self.ec.max_seq_len
        cancelled = self._is_cancelled(req)
        j = req.journey
        if not hit_eos and not cancelled:
            now = time.perf_counter()
            if req.last_emit_ts:
                d = now - req.last_emit_ts
                breach = self.slo.observe("inter_token", d)
                METRICS.observe(
                    "substratus_serve_inter_token_seconds", d,
                    exemplar=(
                        j.trace_id if breach and j is not None else None
                    ),
                )
                if breach:
                    self._slo_exemplar(req, "inter_token", d)
            elif req.submit_ts:
                d = now - req.submit_ts
                breach = self.slo.observe("ttft", d)
                METRICS.observe(
                    "substratus_serve_ttft_seconds", d,
                    exemplar=(
                        j.trace_id if breach and j is not None else None
                    ),
                )
                if breach:
                    self._slo_exemplar(req, "ttft", d)
            req.last_emit_ts = now
            req.out.put(token_id)
            self.slot_tokens[slot].append(token_id)
            if j is not None:
                j.record("emit", t=token_id)
        if hit_eos or hit_budget or hit_window or cancelled:
            # eos/cancel are natural stops; running out of budget or context
            # is a truncation ("length") clients may want to continue from.
            req.finish_reason = (
                "stop" if (hit_eos or cancelled) else "length"
            )
            self._journey_end(
                req, "cancel" if cancelled else req.finish_reason,
                tokens=self.slot_generated[slot],
            )
            req.out.put(None)
            if req.sync_id is not None:
                self._sync_reqs.pop(req.sync_id, None)
            self._release_slot(slot)

    def _step(self) -> None:
        """One scheduler step on the resolved path: pipelined when
        overlap is on, synchronous otherwise — _dispatch_any/_drain_any
        route each iteration to the speculative or plain halves, so
        spec engines pipeline exactly like plain ones."""
        if self.overlap:
            self._step_overlapped()
        else:
            self._decode_step()

    def _loop(self):
        try:
            while self._sync_iterate():
                t_iter = time.perf_counter()
                # Reset the step-timeline accumulators (observability/
                # timeline.py): _flush/_dispatch/_drain/_admit fill
                # them during this iteration; record_iteration below
                # turns them into one flight-recorder entry with
                # bubble attribution.
                self._tl_iter_t0 = t_iter
                self._tl_flush_s = 0.0
                self._tl_flush_reasons = []
                self._tl_dispatch_s = 0.0
                self._tl_drain_s = 0.0
                self._tl_drain_off_s = 0.0
                self._tl_pool_dry = False
                t_admit = time.perf_counter()
                admitted = self._admit()
                admit_s = time.perf_counter() - t_admit
                if admitted:
                    # Only iterations that boarded someone observe the
                    # admission phase — an idle engine waking on its
                    # empty queue would otherwise flood the histogram
                    # with ~0 s samples.
                    METRICS.observe(
                        "substratus_serve_phase_seconds",
                        admit_s,
                        {"phase": "admission"},
                    )
                if not self.active.any():
                    # Nothing decoding implies nothing in flight either
                    # (pipelined slots stay active until drained). Block
                    # on the wake event instead of poll-spinning:
                    # submit()/resubmit()/submit_migration()/
                    # set_source()/stop() set it, so first-token
                    # admission latency is event-driven, not a poll-tick
                    # coin flip. Lockstep gangs keep the 20ms tick —
                    # every iteration pays a collective, and a
                    # follower's wake event never fires for leader-side
                    # submissions.
                    if self.sync is not None:
                        time.sleep(0.02)
                    else:
                        self._wake.wait(timeout=self._idle_wait_s)
                        self._wake.clear()
                    continue
                n_active = self.active.sum()  # host numpy mirror
                METRICS.observe(
                    "substratus_serve_batch_occupancy_ratio",
                    float(n_active) / self.ec.max_batch,
                )
                if self.paged:
                    METRICS.observe(
                        "substratus_serve_kv_page_utilization_ratio",
                        (self.n_pages - self.alloc.free_pages) / self.n_pages,
                    )
                t_decode = time.perf_counter()
                if not self._first_decode_done:
                    # The first decode iteration is dominated by the
                    # executable compile; record it separately so the
                    # steady-state decode histogram stays unpolluted.
                    with tracer.span("engine.first_compile") as span:
                        self._step()
                        dt = time.perf_counter() - t_decode
                        span.set_attribute("seconds", round(dt, 6))
                    self._first_decode_done = True
                    METRICS.set("substratus_serve_first_compile_seconds", dt)
                    continue
                self._step()
                dt_decode = time.perf_counter() - t_decode
                METRICS.observe(
                    "substratus_serve_phase_seconds",
                    dt_decode,
                    {"phase": "decode"},
                )
                if self.ec.step_floor_s > dt_decode:
                    # Simulated device-step latency (see EngineConfig).
                    time.sleep(self.ec.step_floor_s - dt_decode)
                self.timeline.record_iteration(
                    t_start=t_iter,
                    wall_s=time.perf_counter() - t_iter,
                    admit_s=admit_s,
                    admitted=admitted,
                    dispatch_s=self._tl_dispatch_s,
                    drain_s=self._tl_drain_s,
                    drain_off_s=self._tl_drain_off_s,
                    flush_s=self._tl_flush_s,
                    flush_reasons=self._tl_flush_reasons,
                    pool_dry=self._tl_pool_dry,
                    active_slots=n_active,
                    max_slots=self.ec.max_batch,
                    configured_floor_s=self.ec.step_floor_s,
                )
            # Clean stop with a step still in flight (stop() during
            # decode, a gang stop event, server drain): deliver its
            # tokens before the thread exits — consumers of in-flight
            # streams must see every sampled token, then their None.
            self._flush("drain")
            self._fail_staged_swaps(
                RuntimeError("engine stopped before the swap was applied")
            )
        except BaseException as e:  # propagate to waiting callers
            self.error = e
            if self.sync is not None and self.sync.leader:
                # Best-effort stop broadcast: without it every follower
                # blocks forever inside the next header collective and
                # the gang wedges with no pod failure for the JobSet
                # failurePolicy to act on.
                from substratus_tpu.serve.multihost import encode_events

                try:
                    self.sync.broadcast(encode_events([], [], True))
                except Exception:  # sublint: allow[broad-except]: the collective itself may be what broke; the original error is re-raised below
                    logging.getLogger(__name__).warning(
                        "stop broadcast failed after engine error "
                        "(trace_id=%s)", current_trace_id(), exc_info=True,
                    )

            def kill(req: Request) -> None:
                # "error", not the "stop" default: consumers must be able
                # to tell an engine crash from a clean EOS.
                req.finish_reason = "error"
                self._journey_end(req, "error", cause="engine")
                req.out.put(None)

            if self._admitting is not None:
                kill(self._admitting)
            for req in self.slot_req:
                if req is not None:
                    kill(req)
            for req in self._resume:
                kill(req)
            for mig in self._resume_migrations:
                kill(mig.req)
            while not self._migrations.empty():
                try:
                    kill(self._migrations.get_nowait().req)
                except queue.Empty:
                    break
            while not self.queue.empty():
                try:
                    kill(self.queue.get_nowait())
                except queue.Empty:
                    break
            self._fail_staged_swaps(e)
            raise

    def load_snapshot(self) -> Dict[str, object]:
        """Cheap load report for the gateway protocol (gateway/
        loadreport.py): host-side counters only, no device read, no
        lock — a slightly torn snapshot routes a request marginally
        suboptimally, which is fine. Served on /loadz and compacted
        into the x-substratus-load response header."""
        active = int(self.active.sum())
        if self.paged:
            kv_free = self.alloc.free_pages / max(1, self.n_pages)
        else:
            kv_free = (self.ec.max_batch - active) / self.ec.max_batch
        if self.ec.role == "prefill" and self.handoff is not None:
            transfer_q = self.handoff.depth()
        elif self.ec.role == "decode":
            transfer_q = self._migrations.qsize() + len(
                self._resume_migrations
            )
        else:
            transfer_q = 0
        snap = {
            "queue_depth": self.queue.qsize() + len(self._resume),
            "active_slots": active,
            "max_slots": self.ec.max_batch,
            "kv_free_frac": round(kv_free, 4),
            "max_queue": self.ec.max_queue,
            # Disaggregated serving (serve/disagg.py): which phase this
            # replica runs, and how deep its transfer/migration backlog
            # is — the gateway's role-aware routing reads both.
            "role": self.ec.role,
            "transfer_queue_depth": transfer_q,
            # Overlapped decode scheduling (resolved value, not the
            # config): whether this engine pipelines host work under the
            # in-flight device step (docs/performance.md).
            "overlap": self.overlap,
            # Hot weight-swap (docs/serving.md "Zero-downtime rollout"):
            # which parameter version this replica is serving — the
            # rollout coordinator polls this to confirm a swap landed.
            "weights_version": self.weights_version,
            # Prefix-cache effectiveness, mirrored for /loadz consumers
            # (also on /metrics as the *_total counters).
            "prefill_tokens": self.stats["prefill_tokens"],
            "prefix_hit_tokens": self.stats["prefix_hit_tokens"],
            # Report ordering (gateway/fleet.py): per-replica monotonic
            # sequence + wall clock, compacted to sq=/ts= on the
            # x-substratus-load header — the fleet aggregator drops
            # stale/out-of-order deliveries from hedged responses.
            "load_seq": next(self._load_seq),
            "load_ts": round(time.time(), 3),
            # SLO sketches + burn counters (observability/sketch.py):
            # mergeable fixed-bucket percentile state the gateway rolls
            # up fleet-wide on every /loadz poll.
            "slo": self.slo.snapshot(),
        }
        if self.spec:
            # Speculation effectiveness for /loadz consumers (mirrors
            # the substratus_serve_spec_*_tokens_total counters):
            # lifetime acceptance plus each active stream's RESOLVED
            # adaptive draft length — what the EWMA policy would plan
            # next round, 0 for degraded/sampling rows. Torn reads are
            # fine (same contract as the rest of this snapshot).
            prop = self.stats["spec_proposed"]
            acc = self.stats["spec_accepted"]
            ks = []
            for slot in np.flatnonzero(self.active):
                slot = int(slot)
                req = self.slot_req[slot]
                ewma = float(self._spec_ewma[slot])
                if (
                    req is None
                    or req.temperature != 0.0
                    or ewma < self.ec.spec_threshold
                ):
                    ks.append(0)
                else:
                    ks.append(
                        min(
                            self.ec.spec_k,
                            max(1, math.ceil(ewma * self.ec.spec_k)),
                        )
                    )
            snap["spec"] = {
                "proposed_tokens": prop,
                "accepted_tokens": acc,
                "acceptance": round(acc / prop, 4) if prop else None,
                "adaptive_k": ks,
            }
        src = self.source
        if src is not None and hasattr(src, "progress"):
            # Batch-generation progress (serve/batchgen.py): manifest
            # totals + done/in-flight counts, so /loadz answers for an
            # offline run when its progress server is enabled.
            snap["batchgen"] = src.progress()
        if self.adapters is not None:
            # Resident adapter ids + hit/miss/evict counters: the
            # gateway's affinity scoring reads `adapters` (loadreport.py
            # piggybacks it as `ad=` on x-substratus-load).
            a = self.adapters.snapshot()
            snap["adapters"] = a["loaded"]
            snap["adapter_capacity"] = a["capacity"]
            snap["adapter_hits"] = a["hits"]
            snap["adapter_misses"] = a["misses"]
            snap["adapter_evictions"] = a["evictions"]
        return snap

    # --- synchronous helper (tests / bench) -------------------------------

    def generate(
        self, prompt_tokens: List[int], max_tokens: int = 32, **kw
    ) -> List[int]:
        """Blocking single-request generation (engine must be started)."""
        req = self.submit(Request(prompt_tokens, max_tokens=max_tokens, **kw))
        out: List[int] = []
        while True:
            tok = req.out.get(timeout=120)
            if tok is None:
                return out
            out.append(tok)
