"""Multi-tenant LoRA adapter serving: one base model, many adapters.

`train/` produces LoRA adapter trees (train/lora.py) but serving a
finetune used to mean a whole dedicated engine — one compiled program,
one KV pool, one replica set per tenant. This module packs N tenants
into ONE engine: the `AdapterStore` hot-loads adapter artifacts into
stacked per-layer tensors (`a: [L, A, in, r]`, `b: [L, A, r, ...out]`,
adapter slot 0 = the all-zero "identity" adapter so baseless requests
pay nothing), and the engine gathers each batch row's adapter by index
inside the jitted prefill/decode functions (`jnp.take` along the
adapter axis feeding the lora_delta einsums, ops/basics.py::
lora_delta_indexed). Shapes are static — capacity, rank and targets are
fixed at store construction — so loading or evicting an adapter never
recompiles anything, and a mixed-adapter batch runs in the one decode
executable the engine already has.

Threading contract: the store is shared between the engine scheduler
thread (reads the device tree, pins/unpins slots at admission/release)
and HTTP handlers (`known()` checks, snapshots, explicit loads). All
shared state is mutated under `self._lock`; the device tree is rebuilt
lazily by whoever reads it after a mutation, also under the lock, so a
half-written adapter slot is never uploaded.

Artifact layout (docs/container-contract.md "Adapter artifacts"):

    <dir>/substratus.json   {"format": "substratus-tpu-adapter-v1",
                             "lora": {"rank", "alpha", "targets"}, ...}
    <dir>/adapters.npz      {name}.a / {name}.b per target projection

The container contract mounts adapter artifacts under
`/content/adapters/<id>/`; the store's `search_dir` makes every subdir
there loadable on demand — the cache-miss path IS the hot-load path.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.train.lora import DEFAULT_TARGETS

ADAPTER_META_FILE = "substratus.json"
ADAPTER_FORMAT = "substratus-tpu-adapter-v1"
ADAPTER_WEIGHTS_FILE = "adapters.npz"

# Adapter-serving metric catalog (docs/observability.md). Declared at
# import so /metrics carries HELP/TYPE before the first load.
METRICS.describe(
    "substratus_serve_adapters_loaded",
    "LoRA adapters currently resident in the engine's adapter slots "
    "(identity slot 0 excluded).",
    type="gauge",
)
METRICS.describe(
    "substratus_serve_adapter_evictions_total",
    "Adapters evicted from their slot to make room for another load.",
    type="counter",
)
METRICS.describe(
    "substratus_serve_adapter_cache_hits_total",
    "Requests whose adapter was already resident at admission.",
    type="counter",
)
METRICS.describe(
    "substratus_serve_adapter_cache_misses_total",
    "Requests whose adapter had to be hot-loaded from its artifact at "
    "admission.",
    type="counter",
)


class UnknownAdapter(KeyError):
    """The adapter id is neither loaded nor loadable from any known
    artifact path — the HTTP layer turns this into a 404."""

    def __init__(self, adapter_id: str):
        super().__init__(adapter_id)
        self.adapter_id = adapter_id

    def __str__(self) -> str:
        return f"unknown adapter {self.adapter_id!r}"


class AdapterCapacityError(RuntimeError):
    """Every adapter slot is pinned by an active request; transient —
    the scheduler holds the request until a decode slot frees one."""


def _target_shapes(cfg, targets: Sequence[str]) -> Dict[str, Tuple]:
    """(in_dim, out_shape) per target projection — the same layout map
    train/lora.py::init_lora uses, minus the expert-routed MoE leaves
    (per-row gather over an [L, A, E, ...] tree is not implemented; the
    attention and dense-MLP projections are)."""
    hd = cfg.head_size
    out_shape = {
        "wq": (cfg.n_heads, hd),
        "wk": (cfg.n_kv_heads, hd),
        "wv": (cfg.n_kv_heads, hd),
        "wo": (cfg.dim,),
        "w_gate": (cfg.hidden_dim,),
        "w_up": (cfg.hidden_dim,),
        "w_down": (cfg.dim,),
    }
    in_dim = {
        "wq": cfg.dim, "wk": cfg.dim, "wv": cfg.dim,
        "wo": cfg.n_heads * hd,
        "w_gate": cfg.dim, "w_up": cfg.dim,
        "w_down": cfg.hidden_dim,
    }
    moe = getattr(cfg, "n_experts", 0) > 0
    shapes = {}
    for name in targets:
        if name not in out_shape:
            raise ValueError(f"unknown adapter target {name!r}")
        if moe and name in ("w_gate", "w_up", "w_down"):
            raise ValueError(
                f"adapter target {name!r} is expert-routed under MoE "
                "configs; slot-indexed serving supports the attention "
                "and dense-MLP projections"
            )
        shapes[name] = (in_dim[name], out_shape[name])
    return shapes


def save_adapter_artifact(
    path: str,
    adapters: Dict[str, Any],  # {name: {"a": [L, in, r], "b": [L, r, ...]}}
    alpha: float,
    rank: int,
    extra_meta: Optional[Dict[str, Any]] = None,
) -> None:
    """Write a servable LoRA adapter artifact: npz weights + config
    sidecar (the adapter-sized sibling of checkpoints.save_artifact)."""
    os.makedirs(path, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    for name, ab in adapters.items():
        arrays[f"{name}.a"] = np.asarray(ab["a"], np.float32)
        arrays[f"{name}.b"] = np.asarray(ab["b"], np.float32)
    np.savez(os.path.join(path, ADAPTER_WEIGHTS_FILE), **arrays)
    meta = {
        "format": ADAPTER_FORMAT,
        "lora": {
            "rank": int(rank),
            "alpha": float(alpha),
            "targets": sorted(adapters),
        },
    }
    meta.update(extra_meta or {})
    with open(os.path.join(path, ADAPTER_META_FILE), "w") as f:
        json.dump(meta, f, indent=2)


def is_adapter_artifact(path: str) -> bool:
    meta_path = os.path.join(path, ADAPTER_META_FILE)
    if not os.path.exists(meta_path):
        return False
    try:
        with open(meta_path) as f:
            return json.load(f).get("format") == ADAPTER_FORMAT
    except (OSError, ValueError):
        return False


def load_adapter_artifact(path: str) -> Tuple[Dict[str, Any], float, dict]:
    """Read an adapter artifact dir; returns (layers_tree, scale, meta).
    scale = alpha / rank, the factor models.llama.forward applies."""
    with open(os.path.join(path, ADAPTER_META_FILE)) as f:
        meta = json.load(f)
    if meta.get("format") != ADAPTER_FORMAT:
        raise ValueError(
            f"{path}: not an adapter artifact "
            f"(format={meta.get('format')!r})"
        )
    lora = meta.get("lora") or {}
    rank = int(lora.get("rank", 0))
    alpha = float(lora.get("alpha", rank))
    if rank < 1:
        raise ValueError(f"{path}: adapter metadata missing a valid rank")
    with np.load(os.path.join(path, ADAPTER_WEIGHTS_FILE)) as z:
        layers: Dict[str, Any] = {}
        for key in z.files:
            name, _, leaf = key.rpartition(".")
            if leaf not in ("a", "b") or not name:
                raise ValueError(f"{path}: unexpected weight key {key!r}")
            layers.setdefault(name, {})[leaf] = np.asarray(z[key], np.float32)
    for name, ab in layers.items():
        if set(ab) != {"a", "b"}:
            raise ValueError(f"{path}: target {name!r} missing a/b pair")
    return layers, alpha / rank, meta


def infer_store_shape(
    paths: Sequence[str],
) -> Tuple[int, Tuple[str, ...]]:
    """(max rank, union of targets) across adapter artifacts — the store
    shape that can hold all of them (smaller ranks zero-pad exactly).
    Falls back to (8, DEFAULT_TARGETS) when nothing is readable."""
    rank, targets = 0, set()
    for path in paths:
        try:
            with open(os.path.join(path, ADAPTER_META_FILE)) as f:
                lora = json.load(f).get("lora") or {}
        except (OSError, ValueError):
            continue
        rank = max(rank, int(lora.get("rank", 0)))
        targets.update(lora.get("targets") or ())
    if rank < 1 or not targets:
        return 8, tuple(DEFAULT_TARGETS)
    return rank, tuple(sorted(targets))


class AdapterStore:
    """Stacked adapter slots for one engine.

    Slot 0 is the identity adapter (all zero): requests without an
    adapter gather zeros and pay only the (tiny) rank-r einsum, which
    is the price of keeping ONE decode executable for the whole mixed
    batch — no per-tenant recompilation, ever.

    `capacity` counts loadable tenant slots (identity slot excluded).
    The per-target host buffers are float32 with the adapter's
    alpha/rank scale folded into `b`, so the device tree carries a
    single scale of 1.0 for every slot regardless of each tenant's
    training hyperparameters.
    """

    def __init__(
        self,
        cfg,
        capacity: int = 8,
        rank: int = 8,
        targets: Sequence[str] = DEFAULT_TARGETS,
        dtype=None,
        search_dir: Optional[str] = None,
    ):
        if capacity < 1:
            raise ValueError(f"adapter capacity {capacity} invalid")
        if rank < 1:
            raise ValueError(f"adapter rank {rank} invalid")
        self.cfg = cfg
        self.capacity = capacity
        self.rank = rank
        self.targets = tuple(targets)
        self.dtype = dtype if dtype is not None else cfg.dtype
        self.search_dir = search_dir
        L = cfg.n_layers
        A = capacity + 1  # + identity slot 0
        self.n_slots = A
        self._shapes = _target_shapes(cfg, self.targets)
        self._lock = threading.Lock()
        # Everything below is shared between the engine thread and HTTP
        # handlers and only ever touched under self._lock.
        self._a = {
            name: np.zeros((L, A, ind, rank), np.float32)
            for name, (ind, _out) in self._shapes.items()
        }
        self._b = {
            name: np.zeros((L, A, rank) + out, np.float32)
            for name, (_ind, out) in self._shapes.items()
        }
        self._slot_id: List[Optional[str]] = [None] * A  # slot -> adapter id
        self._by_id: Dict[str, int] = {}
        self._paths: Dict[str, str] = {}  # id -> artifact dir (reloadable)
        self._refs = [0] * A  # active engine slots pinning this adapter
        self._last_used = [0.0] * A
        self._version = 1
        self._device: Tuple[int, Optional[dict]] = (0, None)
        self.stats: Dict[str, int] = {"hits": 0, "misses": 0, "evictions": 0}

    # -- registration / lookup (any thread) --------------------------------

    def register_path(self, adapter_id: str, path: str) -> None:
        """Make an adapter loadable by id without loading it yet."""
        with self._lock:
            self._paths[adapter_id] = path

    def scan_search_dir(self) -> List[str]:
        """Register every artifact subdir of search_dir; returns the ids
        found (the container-contract /content/adapters layout)."""
        if not self.search_dir or not os.path.isdir(self.search_dir):
            return []
        found = []
        for entry in sorted(os.listdir(self.search_dir)):
            path = os.path.join(self.search_dir, entry)
            if is_adapter_artifact(path):
                self.register_path(entry, path)
                found.append(entry)
        return found

    def _path_of(self, adapter_id: str) -> Optional[str]:
        # caller holds the lock
        path = self._paths.get(adapter_id)
        if path is None and self.search_dir:
            cand = os.path.join(self.search_dir, adapter_id)
            if is_adapter_artifact(cand):
                self._paths[adapter_id] = cand
                path = cand
        return path

    def known(self, adapter_id: str) -> bool:
        """Resident or loadable — the HTTP layer's pre-submit check."""
        with self._lock:
            return (
                adapter_id in self._by_id
                or self._path_of(adapter_id) is not None
            )

    def loaded_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._by_id)

    def available_ids(self) -> List[str]:
        """Resident + registered + discoverable adapters — what
        /v1/models advertises as servable."""
        self.scan_search_dir()
        with self._lock:
            return sorted(set(self._by_id) | set(self._paths))

    def snapshot(self) -> Dict[str, Any]:
        """/loadz block: what's resident plus the hit/miss/evict
        counters (mirrored from the metrics registry so a scrapeless
        poll still sees them)."""
        with self._lock:
            return {
                "loaded": sorted(self._by_id),
                "capacity": self.capacity,
                "hits": self.stats["hits"],
                "misses": self.stats["misses"],
                "evictions": self.stats["evictions"],
            }

    # -- load / evict -------------------------------------------------------

    def install(
        self, adapter_id: str, layers: Dict[str, Any], scale: float = 1.0
    ) -> int:
        """Install an in-memory adapter tree into a slot (evicting the
        LRU unpinned resident if full); returns the slot index.

        Accepts rank <= the store rank (zero-padded — exact, the extra
        rank columns contribute nothing) and any subset of the store's
        targets (missing targets stay zero)."""
        if not adapter_id:
            raise ValueError("adapter id must be non-empty")
        unknown = set(layers) - set(self._shapes)
        if unknown:
            raise ValueError(
                f"adapter {adapter_id!r} targets {sorted(unknown)} not in "
                f"the store's target set {sorted(self._shapes)}"
            )
        checked: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for name, (ind, out) in self._shapes.items():
            ab = layers.get(name)
            if ab is None:
                continue
            a = np.asarray(ab["a"], np.float32)
            b = np.asarray(ab["b"], np.float32)
            want_a = (self.cfg.n_layers, ind)
            if a.shape[:2] != want_a or a.shape[2] > self.rank:
                raise ValueError(
                    f"adapter {adapter_id!r} {name}.a shape {a.shape} "
                    f"incompatible with [L={want_a[0]}, in={want_a[1]}, "
                    f"r<={self.rank}]"
                )
            if (
                b.shape[0] != self.cfg.n_layers
                or b.shape[1] != a.shape[2]
                or b.shape[2:] != out
            ):
                raise ValueError(
                    f"adapter {adapter_id!r} {name}.b shape {b.shape} "
                    f"incompatible with [L, r={a.shape[2]}, {out}]"
                )
            checked[name] = (a, b)
        with self._lock:
            slot = self._by_id.get(adapter_id)
            if slot is None:
                slot = self._free_slot_locked()
            for name in self._shapes:
                self._a[name][:, slot] = 0.0
                self._b[name][:, slot] = 0.0
                if name not in checked:
                    continue
                a, b = checked[name]
                r = a.shape[2]
                self._a[name][:, slot, :, :r] = a
                # Fold the tenant's alpha/rank scale into b: the device
                # tree then carries one scale (1.0) for every slot.
                self._b[name][:, slot, :r] = b * scale
            self._slot_id[slot] = adapter_id
            self._by_id[adapter_id] = slot
            self._last_used[slot] = time.monotonic()
            self._version += 1
            METRICS.set(
                "substratus_serve_adapters_loaded", len(self._by_id)
            )
            return slot

    def load(self, adapter_id: str, path: Optional[str] = None) -> int:
        """Load an adapter artifact into a slot (hot-load path)."""
        with self._lock:
            path = path or self._path_of(adapter_id)
        if path is None:
            raise UnknownAdapter(adapter_id)
        layers, scale, _meta = load_adapter_artifact(path)
        slot = self.install(adapter_id, layers, scale)
        with self._lock:
            self._paths[adapter_id] = path
        return slot

    def _free_slot_locked(self) -> int:
        """A slot for a new adapter: an empty one, else evict the LRU
        unpinned resident. Caller holds the lock."""
        for slot in range(1, self.n_slots):
            if self._slot_id[slot] is None:
                return slot
        victim, oldest = 0, float("inf")
        for slot in range(1, self.n_slots):
            if self._refs[slot] == 0 and self._last_used[slot] < oldest:
                victim, oldest = slot, self._last_used[slot]
        if victim == 0:
            raise AdapterCapacityError(
                f"all {self.capacity} adapter slots are pinned by active "
                "requests"
            )
        evicted = self._slot_id[victim]
        del self._by_id[evicted]
        self._slot_id[victim] = None
        self.stats["evictions"] += 1
        METRICS.inc("substratus_serve_adapter_evictions_total")
        METRICS.set("substratus_serve_adapters_loaded", len(self._by_id))
        return victim

    # -- admission pinning (engine scheduler thread) ------------------------

    def acquire(self, adapter_id: str) -> int:
        """Resolve an adapter id to its slot for one boarding request,
        hot-loading from its artifact on a miss, and pin the slot so
        eviction can't pull the weights out from under an active decode.
        Raises UnknownAdapter (no artifact anywhere) or
        AdapterCapacityError (transient: every slot pinned)."""
        with self._lock:
            slot = self._by_id.get(adapter_id)
            if slot is not None:
                self.stats["hits"] += 1
                METRICS.inc("substratus_serve_adapter_cache_hits_total")
                self._refs[slot] += 1
                self._last_used[slot] = time.monotonic()
                return slot
        # Miss: load outside the resolve branch (file IO under the lock
        # only for the buffer writes inside install()).
        self.stats["misses"] += 1
        METRICS.inc("substratus_serve_adapter_cache_misses_total")
        slot = self.load(adapter_id)
        with self._lock:
            self._refs[slot] += 1
            self._last_used[slot] = time.monotonic()
            return slot

    def release(self, slot: int) -> None:
        if slot <= 0:
            return
        with self._lock:
            self._refs[slot] = max(0, self._refs[slot] - 1)

    # -- device tree (engine scheduler thread) ------------------------------

    def device_tree(self, mesh=None) -> Dict[str, Any]:
        """The stacked adapter tree as device arrays, shaped for the
        model's layer scan: {"layers": {name: {"a": [L, A, in, r],
        "b": [L, A, r, ...]}}, "scale": 1.0}. Rebuilt lazily after a
        mutation; shapes never change, so jitted callers never
        recompile. Under a mesh the (tiny) tree is replicated."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            version, cached = self._device
            if cached is not None and version == self._version:
                return cached
            layers = {
                name: {
                    "a": jnp.asarray(self._a[name], self.dtype),
                    "b": jnp.asarray(self._b[name], self.dtype),
                }
                for name in self._shapes
            }
            tree = {"layers": layers, "scale": 1.0}
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                tree = jax.device_put(
                    tree, NamedSharding(mesh, PartitionSpec())
                )
            self._device = (self._version, tree)
            return tree
