from substratus_tpu.serve.engine import Engine, EngineConfig, Request

__all__ = ["Engine", "EngineConfig", "Request"]
