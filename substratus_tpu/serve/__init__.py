from substratus_tpu.serve.engine import (
    Engine,
    EngineConfig,
    EngineOverloaded,
    Request,
)

__all__ = ["Engine", "EngineConfig", "EngineOverloaded", "Request"]
