"""Disaggregated prefill/decode serving: KV-page handoff between engines.

Prefill is compute-bound, decode is memory-bound — yet a monolithic
engine runs lockstep batches of both, so a burst of long prompts stalls
every in-flight decode for the duration of its chunked prefill
(ROADMAP item 3). This module splits the request lifecycle across two
engines (Podracer's worker-specialization insight, PAPERS.md):

  * a **prefill** engine (`EngineConfig.role="prefill"`) runs chunked
    prefill into its paged pool, samples the first token, exports the
    request's KV pages, and ships pages + first token + sampling state
    here;
  * a **decode** engine (`role="decode"`) imports the pages into its own
    pool (no recompute), and continues decoding; generated tokens stream
    BACK over the same connection, so the prefill-side `Request.out`
    queue behaves exactly like a local engine's — the HTTP server above
    it is unchanged.

Transport: plain TCP with the same length-prefixed framing discipline as
`serve/multihost.py`'s TcpSync (`struct_pack_u32` headers). Each frame is
`u32 header_len | header JSON | u32 payload_len | payload`; the payload
carries raw page bytes in the header-declared array order. One persistent
connection per (prefill, decode) pair, multiplexed by request id.

Negotiation: the connection opens with a `hello` exchange of PoolSpecs.
Structural dims (layers, page size, kv heads, head dim) must match; KV
dtype may differ — the RECEIVER converts on import (model-dtype pages
quantize into an int8 pool, int8 pages dequantize into a model-dtype
pool), so mixed fleets interoperate during a dtype migration.

Overlapped-scheduler interplay (docs/performance.md "Overlapped
scheduling"): the DECODE tier pipelines — migrations install while a
step is in flight (the scatter import chains behind it on the device
stream) and the first-token emit rides admission as before. The
PREFILL tier never decodes, so `Engine.overlap` resolves off there;
the page export in `_handoff_request` still runs behind an explicit
`_flush("handoff")` guard pinning the settled-batch invariant the
gather depends on.

Failure semantics (the contract the unit tests pin):

  * a truncated/garbled frame kills only that connection — partially
    read handoffs are discarded, nothing is submitted;
  * a dead decode worker never hangs the client: every request in
    flight on the lost connection is REQUEUED on the prefill engine
    with `prompt := prompt + tokens-already-streamed` (the preemption
    trick), so generation resumes token-exactly through another worker
    — or finishes with an error marker when no worker is left;
  * the transfer queue is bounded: a prefill engine outrunning its
    decode tier blocks briefly at ship() (backpressure), then fails the
    request loudly instead of queueing unboundedly.
"""
from __future__ import annotations

import json
import logging
import queue
import socket
import struct
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from substratus_tpu.observability.journey import RequestJourney
from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.observability.propagation import (
    format_traceparent,
    parse_traceparent,
)
from substratus_tpu.observability.tracing import SpanContext

log = logging.getLogger("substratus.serve.disagg")

# Handoff observability (docs/observability.md "Serving plane").
METRICS.histogram(
    "substratus_serve_kv_transfer_seconds",
    "Wall time of one KV-page handoff send (serialize + socket write), "
    "prefill side of disaggregated serving (serve/disagg.py).",
)
METRICS.describe(
    "substratus_serve_kv_transfer_queue_depth",
    "Handoffs waiting in the prefill engine's bounded transfer queue.",
    type="gauge",
)
METRICS.describe(
    "substratus_serve_kv_transfers_total",
    "KV-page handoffs completed, by outcome (sent, requeued, failed).",
    type="counter",
)

DEFAULT_TRANSFER_PORT = 8500


class NegotiationError(ValueError):
    """The two pools cannot exchange pages (structural mismatch)."""


@dataclass(frozen=True)
class PoolSpec:
    """The shape contract of one engine's paged KV pool — everything the
    peer needs to validate (and convert) incoming pages."""

    n_layers: int
    page_size: int
    kv_heads: int
    head_dim: int
    dtype: str  # numpy dtype name of the pool's k/v arrays
    quantized: bool  # int8 pool with per-vector f32 scales

    @classmethod
    def from_engine(cls, engine) -> "PoolSpec":
        if not getattr(engine, "paged", False):
            raise ValueError("disaggregated serving requires the paged layout")
        k = engine.cache["k"]
        L, _, bs, kh, hd = k.shape
        return cls(
            n_layers=int(L), page_size=int(bs), kv_heads=int(kh),
            head_dim=int(hd), dtype=np.dtype(k.dtype).name,
            quantized="k_scale" in engine.cache,
        )

    @classmethod
    def from_engine_config(cls, cfg, ec) -> "PoolSpec":
        """The spec an Engine(cfg, ec=ec) paged pool will have, computed
        BEFORE the engine exists — the HandoffManager is constructed
        first and handed into the Engine constructor."""
        quantized = ec.kv_cache_dtype == "int8"
        return cls(
            n_layers=int(cfg.n_layers), page_size=int(ec.page_size),
            kv_heads=int(cfg.n_kv_heads), head_dim=int(cfg.head_size),
            dtype="int8" if quantized else np.dtype(cfg.dtype).name,
            quantized=quantized,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "n_layers": self.n_layers, "page_size": self.page_size,
            "kv_heads": self.kv_heads, "head_dim": self.head_dim,
            "dtype": self.dtype, "quantized": self.quantized,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PoolSpec":
        return cls(
            n_layers=int(d["n_layers"]), page_size=int(d["page_size"]),
            kv_heads=int(d["kv_heads"]), head_dim=int(d["head_dim"]),
            dtype=str(d["dtype"]), quantized=bool(d["quantized"]),
        )

    def convert_mode(self, src: "PoolSpec") -> str:
        """How this (receiving) pool installs pages exported from `src`:
        'none' (same quantization; a plain cast covers bf16<->f32),
        'quantize' (model-dtype pages into an int8 pool), 'dequantize'
        (int8 pages into a model-dtype pool). Structural mismatches are
        a NegotiationError — pages from a different model shape or page
        size can never be reinterpreted."""
        for f in ("n_layers", "page_size", "kv_heads", "head_dim"):
            if getattr(self, f) != getattr(src, f):
                raise NegotiationError(
                    f"pool {f} mismatch: sender={getattr(src, f)} "
                    f"receiver={getattr(self, f)}"
                )
        if src.quantized == self.quantized:
            return "none"
        return "quantize" if self.quantized else "dequantize"


# --- framing --------------------------------------------------------------


def _pack_u32(n: int) -> bytes:
    return struct.pack("<I", n)


# A frame larger than this is a protocol violation (or an attack), not a
# big handoff: even a 70B-shaped page batch stays far under it.
MAX_FRAME = 1 << 31


def send_frame(sock, header: Dict[str, Any], payload: bytes = b"") -> None:
    hdr = json.dumps(header, separators=(",", ":")).encode()
    # One sendall of the whole frame: interleaving writers would corrupt
    # the stream, so callers hold the channel's send lock.
    sock.sendall(_pack_u32(len(hdr)) + hdr + _pack_u32(len(payload)) + payload)


def recv_exact(sock, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed the transfer stream")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock) -> Tuple[Dict[str, Any], bytes]:
    """One frame off the wire; raises ConnectionError on EOF/truncation
    and ValueError on garbage (both kill the connection, never the
    process — a truncated handoff is discarded, not half-applied)."""
    hlen = struct.unpack("<I", recv_exact(sock, 4))[0]
    if not 0 < hlen < MAX_FRAME:
        raise ValueError(f"bad header length {hlen}")
    header = json.loads(recv_exact(sock, hlen).decode())
    plen = struct.unpack("<I", recv_exact(sock, 4))[0]
    if plen >= MAX_FRAME:
        raise ValueError(f"bad payload length {plen}")
    payload = recv_exact(sock, plen) if plen else b""
    return header, payload


def encode_pages(pages: Dict[str, np.ndarray]) -> Tuple[List[dict], bytes]:
    """{name: array} -> (array manifest for the header, payload bytes)."""
    manifest, parts = [], []
    for name in sorted(pages):
        a = np.ascontiguousarray(pages[name])
        manifest.append(
            {"n": name, "s": list(a.shape), "d": np.dtype(a.dtype).name}
        )
        parts.append(a.tobytes())
    return manifest, b"".join(parts)


def decode_pages(manifest: List[dict], payload: bytes) -> Dict[str, np.ndarray]:
    """Inverse of encode_pages; raises ValueError when the payload length
    disagrees with the manifest (a truncated or corrupted frame)."""
    out: Dict[str, np.ndarray] = {}
    off = 0
    for m in manifest:
        dt = np.dtype(str(m["d"]))
        shape = tuple(int(x) for x in m["s"])
        nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        if off + nbytes > len(payload):
            raise ValueError("page payload shorter than its manifest")
        out[str(m["n"])] = np.frombuffer(
            payload, dt, count=nbytes // dt.itemsize, offset=off
        ).reshape(shape)
        off += nbytes
    if off != len(payload):
        raise ValueError("page payload longer than its manifest")
    return out


# --- prefill side ---------------------------------------------------------


@dataclass
class _Flight:
    """One handed-off request the prefill side is relaying."""

    req: Any  # serve.engine.Request
    peer: str
    emitted: List[int] = field(default_factory=list)
    cancel_sent: bool = False
    done: bool = False


class _Channel:
    """One negotiated connection to a decode worker: a send lock for
    frame atomicity and a reader thread for the token back-channel."""

    def __init__(self, peer: str, sock, remote_spec: PoolSpec):
        self.peer = peer
        self.sock = sock
        self.remote_spec = remote_spec
        self.send_lock = threading.Lock()
        self.dead = False

    def send(self, header: Dict[str, Any], payload: bytes = b"") -> None:
        with self.send_lock:
            send_frame(self.sock, header, payload)

    def close(self) -> None:
        self.dead = True
        # shutdown() before close(): a bare close() on a socket another
        # thread is blocked recv()ing neither wakes that thread nor
        # sends FIN on Linux — the peer would never observe the loss.
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class HandoffManager:
    """Prefill-side coordinator: owns the connections to the decode
    tier, the bounded transfer queue, and the token relay back into each
    request's `out` queue. The engine's scheduler thread calls ship();
    a sender thread serializes and writes; per-channel reader threads
    deliver tokens — `_lock` guards every structure they share."""

    def __init__(
        self,
        peers: List[str],
        spec: PoolSpec,
        max_queue: int = 8,
        connect_timeout: float = 10.0,
        ship_timeout: float = 30.0,
        io_timeout: float = 600.0,
    ):
        if not peers:
            raise ValueError("disaggregated prefill needs >=1 decode peer")
        self.peers = [p.strip() for p in peers if p.strip()]
        self.spec = spec
        self.connect_timeout = connect_timeout
        self.ship_timeout = ship_timeout
        self.io_timeout = io_timeout
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._lock = threading.Lock()
        self._channels: Dict[str, _Channel] = {}
        self._flights: Dict[str, _Flight] = {}
        self._rr = 0  # round-robin cursor over peers
        # Resolved peer cache: a headless Service DNS name expands to
        # one address per decode pod, re-resolved at most every few
        # seconds so scale-up/down flows in without a restart.
        self._peer_cache: Tuple[float, List[str]] = (0.0, [])
        self._stop = threading.Event()
        self.engine = None  # bound by bind_engine(); requeue target
        self._sender = threading.Thread(target=self._send_loop, daemon=True)
        self._sender.start()

    # -- engine-facing surface --------------------------------------------

    def bind_engine(self, engine) -> None:
        """The engine requeued requests re-enter (Engine.resubmit)."""
        self.engine = engine

    def depth(self) -> int:
        return self._queue.qsize()

    def ship(self, req, pages: Dict[str, np.ndarray], true_len: int,
             first_token: int) -> None:
        """Enqueue one handoff (scheduler thread). Blocks up to
        ship_timeout when the transfer queue is full — backpressure
        toward admission — then fails the request instead of queueing
        unboundedly."""
        if not req.id:
            # The flight registry and the wire protocol key on the
            # request id; engine-level callers (bench, tests) often
            # leave it empty — mint one rather than collide.
            import uuid

            req.id = uuid.uuid4().hex
        item = (req, pages, true_len, first_token)
        try:
            self._queue.put(item, timeout=self.ship_timeout)
        except queue.Full:
            log.warning(
                "transfer queue full for %.0fs; failing request %s",
                self.ship_timeout, req.id,
            )
            METRICS.inc(
                "substratus_serve_kv_transfers_total", {"outcome": "failed"}
            )
            self._fail(req)
            return
        METRICS.set(
            "substratus_serve_kv_transfer_queue_depth", self._queue.qsize()
        )

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            chans = list(self._channels.values())
            self._channels.clear()
        for ch in chans:
            ch.close()

    # -- sending -----------------------------------------------------------

    def _send_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            METRICS.set(
                "substratus_serve_kv_transfer_queue_depth",
                self._queue.qsize(),
            )
            req, pages, true_len, first_token = item
            t0 = time.perf_counter()
            if self._send_one(req, pages, true_len, first_token):
                METRICS.observe(
                    "substratus_serve_kv_transfer_seconds",
                    time.perf_counter() - t0,
                )
                METRICS.inc(
                    "substratus_serve_kv_transfers_total",
                    {"outcome": "sent"},
                )

    def _send_one(self, req, pages, true_len, first_token) -> bool:
        """Try every peer once; on total failure the request fails
        loudly (the no-worker-left case must not hang the client)."""
        manifest, payload = encode_pages(pages)
        # W3C trace context rides the handoff so the decode tier parents
        # its spans AND its journey segment under the same trace id —
        # without it every decode-side span is an orphan root. "tpar",
        # not "tp": this header already carries top_p under "tp".
        tpar = None
        if req.trace_ctx is not None:
            tpar = format_traceparent(req.trace_ctx)
        elif getattr(req, "journey", None) is not None:
            tpar = format_traceparent(
                SpanContext(req.journey.trace_id, uuid.uuid4().hex[:16])
            )
        header = {
            "t": "kv",
            "rid": req.id,
            "p": list(req.prompt_tokens),
            "tl": true_len,
            "first": first_token,
            "m": req.max_tokens,
            "temp": req.temperature,
            "tp": req.top_p,
            "eos": req.eos_token_id,
            "ad": req.adapter,
            "tpar": tpar,
            "arrays": manifest,
        }
        peers = self._resolved_peers()
        n = len(peers)
        for i in range(n):
            peer = peers[(self._rr + i) % n]
            ch = self._channel(peer)
            if ch is None:
                continue
            with self._lock:
                self._flights[req.id] = _Flight(req=req, peer=peer)
            try:
                ch.send(header, payload)
            except (OSError, ValueError) as e:
                log.warning("handoff send to %s failed: %r", peer, e)
                with self._lock:
                    self._flights.pop(req.id, None)
                self._drop_channel(peer, requeue=True)
                continue
            self._rr = (self._rr + i + 1) % n
            return True
        log.error("no decode worker reachable; failing request %s", req.id)
        METRICS.inc(
            "substratus_serve_kv_transfers_total", {"outcome": "failed"}
        )
        self._fail(req)
        return False

    def _resolved_peers(self) -> List[str]:
        """The configured peers with DNS names expanded to every
        address (a headless k8s Service answers one A record per decode
        pod). Sender-thread only; cached for a few seconds."""
        ts, cached = self._peer_cache
        now = time.monotonic()
        if cached and now - ts < 5.0:
            return cached
        out: List[str] = []
        for p in self.peers:
            host, _, port = p.rpartition(":")
            try:
                infos = socket.getaddrinfo(
                    host or "127.0.0.1", int(port),
                    type=socket.SOCK_STREAM,
                )
            except OSError:
                continue
            addrs = sorted({i[4][0] for i in infos})
            out.extend(f"{a}:{port}" for a in addrs)
        out = out or list(self.peers)
        self._peer_cache = (now, out)
        return out

    def _channel(self, peer: str) -> Optional[_Channel]:
        with self._lock:
            ch = self._channels.get(peer)
        if ch is not None and not ch.dead:
            return ch
        host, _, port = peer.rpartition(":")
        try:
            sock = socket.create_connection(
                (host or "127.0.0.1", int(port)),
                timeout=self.connect_timeout,
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(self.io_timeout)
            send_frame(sock, {"t": "hello", "spec": self.spec.to_dict()})
            reply, _ = recv_frame(sock)
            if reply.get("t") == "reject":
                raise NegotiationError(str(reply.get("reason")))
            if reply.get("t") != "hello":
                raise ValueError(f"unexpected reply {reply.get('t')!r}")
            remote = PoolSpec.from_dict(reply["spec"])
            # Both sides validate: a structural mismatch must fail the
            # CONNECTION (loud, at negotiation), never a request.
            remote.convert_mode(self.spec)
        except (OSError, ValueError, KeyError) as e:
            log.warning("decode peer %s unavailable: %r", peer, e)
            return None
        ch = _Channel(peer, sock, remote)
        with self._lock:
            old = self._channels.get(peer)
            self._channels[peer] = ch
        if old is not None:
            old.close()
        threading.Thread(
            target=self._read_loop, args=(ch,), daemon=True
        ).start()
        return ch

    # -- the token back-channel -------------------------------------------

    def _read_loop(self, ch: _Channel) -> None:
        try:
            while not ch.dead:
                header, _ = recv_frame(ch.sock)
                kind = header.get("t")
                if kind == "tok":
                    self._on_token(ch, str(header["rid"]), int(header["k"]))
                elif kind == "done":
                    self._on_done(
                        str(header["rid"]), str(header.get("fr", "stop")),
                        header.get("j"),
                    )
        except (OSError, ValueError) as e:
            if not ch.dead and not self._stop.is_set():
                log.warning("decode peer %s lost: %r", ch.peer, e)
        self._drop_channel(ch.peer, requeue=True)

    def _on_token(self, ch: _Channel, rid: str, tok: int) -> None:
        with self._lock:
            flight = self._flights.get(rid)
        if flight is None:
            return
        req = flight.req
        now = time.perf_counter()
        if req.last_emit_ts:
            METRICS.observe(
                "substratus_serve_inter_token_seconds", now - req.last_emit_ts
            )
        elif req.submit_ts:
            METRICS.observe(
                "substratus_serve_ttft_seconds", now - req.submit_ts
            )
        req.last_emit_ts = now
        flight.emitted.append(tok)
        req.out.put(tok)
        if req.cancelled and not flight.cancel_sent:
            flight.cancel_sent = True
            try:
                ch.send({"t": "cancel", "rid": rid})
            except OSError:
                pass  # the reader will notice the dead channel

    def _on_done(self, rid: str, finish_reason: str,
                 segment: Optional[dict] = None) -> None:
        with self._lock:
            flight = self._flights.pop(rid, None)
        if flight is None:
            return
        flight.done = True
        req = flight.req
        req.finish_reason = finish_reason
        # Stitch the decode tier's journey segment (the done frame's "j"
        # field) into the prefill-side journey BEFORE the terminal marker:
        # the merged journey — one trace id spanning both processes — is
        # what journey_log/slowz snapshot.
        j = getattr(req, "journey", None)
        if j is not None and segment:
            j.stitch(segment)
        eng = self.engine
        if eng is not None:
            eng._journey_end(req, finish_reason)
        elif j is not None and not j.ended:
            j.record("end", reason=finish_reason)
        req.out.put(None)

    # -- failure handling --------------------------------------------------

    def _drop_channel(self, peer: str, requeue: bool) -> None:
        with self._lock:
            ch = self._channels.pop(peer, None)
            orphans = [
                f for f in self._flights.values()
                if f.peer == peer and not f.done
            ]
            for f in orphans:
                self._flights.pop(f.req.id, None)
        if ch is not None:
            ch.close()
        if not requeue:
            return
        for f in orphans:
            self._requeue(f)

    def _requeue(self, flight: _Flight) -> None:
        """A request whose decode worker died resumes via re-prefill:
        prompt grows by the tokens already streamed (the engine's
        preemption trick), so the client's stream continues seamlessly
        through whichever worker takes the retry."""
        req = flight.req
        req.prompt_tokens = list(req.prompt_tokens) + flight.emitted
        req.max_tokens -= len(flight.emitted)
        if req.max_tokens <= 0 or req.cancelled:
            req.finish_reason = "length" if not req.cancelled else "stop"
            eng = self.engine
            j = getattr(req, "journey", None)
            if eng is not None:
                eng._journey_end(req, req.finish_reason, cause="requeue")
            elif j is not None and not j.ended:
                j.record("end", reason=req.finish_reason, cause="requeue")
            req.out.put(None)
            return
        if self.engine is None:
            self._fail(req)
            return
        METRICS.inc(
            "substratus_serve_kv_transfers_total", {"outcome": "requeued"}
        )
        # The SAME Request object re-enters admission: trace_ctx and the
        # journey ride along, so the re-prefill is visibly the same trace
        # in tracez/journeys — never a fresh root (resubmit stamps the
        # "requeue" journey event).
        log.info(
            "requeueing request %s after decode-worker loss (trace_id=%s)",
            req.id,
            getattr(req, "journey", None) and req.journey.trace_id,
        )
        self.engine.resubmit(req)

    def _fail(self, req) -> None:
        """Terminal error marker. Carries the original trace id into the
        log line and the journey ring so a dead-decode-worker failure is
        attributable to the request's trace, not an anonymous root."""
        req.finish_reason = "error"
        j = getattr(req, "journey", None)
        log.error(
            "handoff failed for request %s (trace_id=%s)",
            req.id, j.trace_id if j is not None else None,
        )
        eng = self.engine
        if eng is not None:
            eng._journey_end(req, "error", cause="handoff")
        elif j is not None and not j.ended:
            j.record("end", reason="error", cause="handoff")
        req.out.put(None)


# --- decode side ----------------------------------------------------------


@dataclass
class Migration:
    """One migrated request, ready for the decode engine's admission:
    KV pages already on the host, no recompute needed."""

    req: Any  # serve.engine.Request (out = _RemoteSink)
    pages: Dict[str, np.ndarray]  # each [L, n_pages, bs, KH, hd]-shaped
    true_len: int
    first_token: int
    convert: str  # "none" | "quantize" | "dequantize"


class _RemoteSink:
    """Decode-side stand-in for Request.out: frames every token back to
    the prefill worker. Sends run on the decode engine's scheduler
    thread; a dead peer marks the request cancelled so its slot frees at
    the next emit instead of wedging the scheduler."""

    def __init__(self, channel: _Channel, rid: str):
        self.channel = channel
        self.rid = rid
        self.req = None  # set right after the Request is constructed

    def put(self, item) -> None:
        if self.channel.dead:
            if self.req is not None:
                self.req.cancelled = True
            return
        try:
            if item is None:
                fr = self.req.finish_reason if self.req is not None else "stop"
                # Ship the decode-side journey segment back with the
                # terminal frame — the prefill side stitches it into ONE
                # merged journey spanning both processes. The engine's
                # _journey_end ran before this put(None), so the segment
                # carries its own "end" event.
                j = (
                    getattr(self.req, "journey", None)
                    if self.req is not None else None
                )
                if j is not None:
                    self.channel.send(
                        {"t": "done", "rid": self.rid, "fr": fr,
                         "j": j.to_wire()}
                    )
                else:
                    self.channel.send({"t": "done", "rid": self.rid, "fr": fr})
            else:
                self.channel.send(
                    {"t": "tok", "rid": self.rid, "k": int(item)}
                )
        except OSError:
            self.channel.dead = True
            if self.req is not None:
                self.req.cancelled = True


class HandoffServer:
    """Decode-side listener: accepts prefill-worker connections,
    negotiates the pool layout, turns kv frames into engine migrations,
    and relays cancellation. One accept thread + one reader thread per
    connection, all daemons; per-connection request registries are
    confined to their reader thread (cancel frames arrive on the same
    connection that created the request)."""

    def __init__(self, engine, host: str = "0.0.0.0", port: int = 0):
        from substratus_tpu.serve.engine import Request  # cycle-free import

        self._Request = Request
        self.engine = engine
        self.spec = PoolSpec.from_engine(engine)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._conns: List[Any] = []
        self._accept = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept.start()

    def close(self) -> None:
        """Stop accepting AND sever live connections — prefill peers
        must observe EOF (and requeue their flights) the moment this
        worker leaves, exactly as a process death would read."""
        self._stop.set()
        # shutdown() before close() throughout: close() alone neither
        # wakes a thread blocked in accept()/recv() on the same socket
        # nor sends FIN while one is, so peers (and our own reader
        # threads) would never observe this worker leaving.
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._srv.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn, addr), daemon=True
            ).start()

    def _serve_conn(self, conn, addr) -> None:
        peer = f"{addr[0]}:{addr[1]}"
        reqs: Dict[str, Any] = {}  # rid -> Request (this connection only)
        ch: Optional[_Channel] = None
        try:
            hello, _ = recv_frame(conn)
            if hello.get("t") != "hello":
                raise ValueError(f"expected hello, got {hello.get('t')!r}")
            src = PoolSpec.from_dict(hello["spec"])
            try:
                convert = self.spec.convert_mode(src)
            except NegotiationError as e:
                send_frame(conn, {"t": "reject", "reason": str(e)})
                return
            ch = _Channel(peer, conn, src)
            ch.send({"t": "hello", "spec": self.spec.to_dict()})
            while True:
                header, payload = recv_frame(conn)
                kind = header.get("t")
                if kind == "kv":
                    self._on_kv(ch, header, payload, convert, reqs)
                elif kind == "cancel":
                    req = reqs.get(str(header.get("rid")))
                    if req is not None:
                        req.cancelled = True
        except (OSError, ValueError, KeyError) as e:
            # Truncated stream / protocol garbage: this connection dies,
            # partially read handoffs are discarded un-submitted.
            if not self._stop.is_set():
                log.warning("transfer connection %s closed: %r", peer, e)
        finally:
            if ch is not None:
                ch.dead = True
            # shutdown() before close(), same as everywhere else in this
            # module: the decode engine's scheduler thread may be inside
            # a _RemoteSink sendall() on this socket right now — a bare
            # close() neither unblocks it nor sends FIN to the peer.
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            # Requests this connection fed have nowhere to stream:
            # cancel them so the engine frees their slots. The prefill
            # side requeues its flights when it notices the same loss.
            for req in reqs.values():
                req.cancelled = True

    def _on_kv(self, ch: _Channel, header: Dict[str, Any], payload: bytes,
               convert: str, reqs: Dict[str, Any]) -> None:
        pages = decode_pages(header["arrays"], payload)
        rid = str(header["rid"])
        sink = _RemoteSink(ch, rid)
        # Parent this tier's spans and journey under the prefill side's
        # trace context ("tpar" header): the decode half of the request
        # keeps the SAME trace id, so the prefill side can stitch the
        # returned segment into one merged journey.
        tctx = parse_traceparent(header.get("tpar") or "")
        journey = RequestJourney(
            trace_id=(tctx.trace_id if tctx is not None else None),
            rid=rid,
            origin="decode",
            cap=self.engine.ec.journey_events,
        )
        journey.record(
            "kv_recv",
            bytes=len(payload),
            prompt_tokens=len(header["p"]),
        )
        req = self._Request(
            prompt_tokens=[int(x) for x in header["p"]],
            max_tokens=int(header["m"]),
            temperature=float(header["temp"]),
            top_p=float(header["tp"]),
            eos_token_id=(
                None if header.get("eos") is None else int(header["eos"])
            ),
            adapter=header.get("ad"),
            id=rid,
            out=sink,
            trace_ctx=tctx,
            journey=journey,
        )
        sink.req = req
        reqs[rid] = req
        self.engine.submit_migration(
            Migration(
                req=req,
                pages=pages,
                true_len=int(header["tl"]),
                first_token=int(header["first"]),
                convert=convert,
            )
        )
