"""Offline batch generation: an actor gang that saturates chips with no
HTTP path (ROADMAP item 5; Podracer's Sebulba shape — generation actors
feeding a bulk sink in lockstep, utilization as the only objective).

The interactive stack measures latency under routing, shedding, and
bursts; this driver measures nothing but chips-saturated tokens/sec:

  * **manifest in** — a JSONL prompt manifest (load/manifest.py), RO at
    /content/data per the container contract; each record carries its
    own max_tokens/temperature/top_p and an optional `model` field that
    selects a LoRA adapter slot (serve/adapters.py), so mixed-tenant
    batches pack into the one compiled program;
  * **continuous refill** — the engines take requests through the pull
    source fast-path (Engine.set_source): the scheduler thread pulls the
    next prompt the moment a slot frees — no submit() thread handoff,
    no queue-wait round trip — which is what holds decode occupancy
    near 1.0 for the whole run. Under the overlapped scheduler (the
    default since round 10, docs/performance.md "Overlapped
    scheduling") a completion surfaces at the *drain* of its step, so
    the refill boards one iteration later than the old synchronous
    same-iteration refill — but that drain (and the sink handoff, and
    the prompt tokenization behind pull()) now runs WHILE the next
    device step is in flight, so the refill's host cost vanishes from
    the step cadence (measured: tok/s ratio unchanged, occupancy gauge
    ~0.94 vs 0.96 — the release-to-readmit gap became visible, the
    cadence did not stretch);
  * **double-buffered sink** — finished records land in a swap buffer on
    the scheduler thread (a list append, never I/O); a dedicated sink
    thread swaps it and does the host-side work (detokenize, JSON
    encode, shard write/flush) while the device steps the next batch;
  * **sharded, exactly-once output** — results are JSONL shards whose
    lines carry the record's manifest index. The output IS the resume
    ledger: a restarted driver scans the shards, skips every durable
    index, and regenerates the rest into fresh shards (torn tail lines
    from a kill are unparseable, ignored, and regenerated). No side
    state file, so there is nothing to drift;
  * **actor gangs** — N engines (actors) drain one shared cursor in one
    process, and a multi-host lockstep engine composes too: the leader's
    pulls ride the same per-iteration event broadcast as submitted
    requests (serve/multihost.py), so followers mirror the refill.

Controller shape: `params.batchGenerate` on a Server CR renders a Job
(single host) or JobSet gang (multi-host TPU slice) running this module
(controller/crs.py, docs/batch-generation.md).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from substratus_tpu.load.manifest import (
    completed_indices,
    iter_manifest,
    next_shard_index,
    record_prompt_tokens,
    shard_name,
)
from substratus_tpu.observability.metrics import METRICS

log = logging.getLogger(__name__)

METRICS.describe(
    "substratus_batchgen_records_total",
    "Batch-generation records written to output shards, labeled by "
    "outcome: ok (generated to stop/length), error (engine-side "
    "failure: unknown adapter, engine death), invalid (malformed "
    "manifest record — written once, never retried).",
    type="counter",
)
METRICS.describe(
    "substratus_batchgen_slot_occupancy",
    "Active decode slots / total slots across the run's actor engines, "
    "sampled by the sink thread each flush interval. The number the "
    "continuous-refill scheduler exists to keep at 1.0.",
    type="gauge",
)
METRICS.describe(
    "substratus_batchgen_manifest_progress_ratio",
    "Durably written manifest records (this run + resumed prior runs) "
    "/ total manifest records.",
    type="gauge",
)


class ShardWriter:
    """Sharded JSONL results writer. Owned by the sink thread (not
    thread-safe); rotation is internal, open_shard/close are the
    driver-visible lifecycle pair (analysis/lifecycle.py gates the
    balance). Resume NEVER appends to an existing shard: a tail line
    torn by a kill must stay inert, not have fresh JSON glued onto it."""

    def __init__(self, out_dir: str, records_per_shard: int = 10000):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.records_per_shard = max(1, int(records_per_shard))
        self._f = None
        self._in_shard = 0

    def open_shard(self) -> str:
        """Open the next free shard file; returns its path."""
        if self._f is not None:
            self._f.close()
        path = os.path.join(
            self.out_dir, shard_name(next_shard_index(self.out_dir))
        )
        self._f = open(path, "w")
        self._in_shard = 0
        return path

    def write(self, record: Dict[str, Any]) -> None:
        if self._f is None or self._in_shard >= self.records_per_shard:
            path = self.open_shard()
            log.info("batchgen: rotating to %s", path)
        self._f.write(json.dumps(record, sort_keys=True) + "\n")
        self._in_shard += 1

    def flush(self) -> None:
        """Push buffered lines to the OS so a killed PROCESS loses at
        most the in-flight swap buffer (whose records resume regenerates
        — they were never durable, so exactly-once holds)."""
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None


class _RecordSink:
    """Per-request stand-in for Request.out (queue interface subset).
    put() runs on the engine scheduler thread: tokens append to a plain
    list (single producer), and the terminal None hands the finished
    record to the driver's swap buffer — never I/O, never blocking."""

    __slots__ = ("driver", "index", "rec", "req", "tokens", "n_prompt",
                 "error")

    def __init__(self, driver: "BatchGenDriver", index: int,
                 rec: Dict[str, Any]):
        self.driver = driver
        self.index = index
        self.rec = rec
        self.req = None
        self.tokens: List[int] = []
        self.n_prompt = 0
        self.error: Optional[str] = None  # manifest-invalid records

    def put(self, item) -> None:
        if item is None:
            self.driver._complete(self)
        else:
            self.tokens.append(item)


class _EngineSource:
    """The engine-facing pull source (Engine.set_source): one per actor,
    all draining the driver's shared manifest cursor."""

    def __init__(self, driver: "BatchGenDriver"):
        self._driver = driver

    def pull(self):
        return self._driver._pull()

    def pending(self) -> bool:
        return self._driver._pending_refill()

    def progress(self) -> Dict[str, Any]:
        return self._driver.progress()


class BatchGenDriver:
    """Drives one or more actor engines through a prompt manifest.

    Threading: engine scheduler threads call _pull/_complete (tiny
    lock-guarded critical sections — a list pop/append); the sink thread
    (_sink_loop) owns all output I/O, the shard writer, and every
    counter; run() blocks the caller until the manifest drains. The
    pending-record list is materialized eagerly so malformed manifest
    LINES fail before any device work (malformed RECORDS — bad fields —
    become outcome=invalid output lines instead, written exactly once).
    """

    def __init__(
        self,
        engines: List[Any],
        manifest_path: str,
        out_dir: str,
        *,
        tokenizer=None,
        max_tokens: int = 64,
        temperature: float = 0.0,
        top_p: float = 1.0,
        records_per_shard: int = 10000,
        resume: bool = True,
        flush_interval_s: float = 0.05,
        sample_interval_s: float = 0.01,
        prefetch: Optional[int] = None,
        record_hook=None,
    ):
        if not engines:
            raise ValueError("batch generation needs at least one engine")
        for e in engines:
            if e.ec.role != "both":
                raise ValueError(
                    "batch generation drives monolithic engines "
                    f"(role={e.ec.role!r} given); split pools belong to "
                    "the interactive path"
                )
        self.engines = list(engines)
        self.tokenizer = tokenizer
        self.default_max_tokens = int(max_tokens)
        self.default_temperature = float(temperature)
        self.default_top_p = float(top_p)
        self.flush_interval_s = float(flush_interval_s)
        self.sample_interval_s = float(sample_interval_s)
        self.manifest_path = manifest_path
        # Called with each completed output record AFTER it is written
        # (sink thread — implementations must be thread-safe). The RL
        # actor loop (rl/loop.py) collects episodes through it without
        # re-reading the shards it just wrote.
        self.record_hook = record_hook
        self._writer = ShardWriter(out_dir, records_per_shard)
        self._slots_total = sum(e.ec.max_batch for e in self.engines)
        self._prefetch = (
            int(prefetch) if prefetch else max(2, 2 * self._slots_total)
        )

        all_records = list(iter_manifest(manifest_path))
        self.total = len(all_records)
        done = completed_indices(out_dir) if resume else set()
        self._records = deque(
            (i, rec) for i, rec in all_records if i not in done
        )
        self.resumed = self.total - len(self._records)

        self._lock = threading.Lock()
        self._ready: List[Any] = []  # prefetched Requests awaiting pull
        self._buf: List[_RecordSink] = []  # finished, awaiting write-out
        self._wake = threading.Event()
        self._in_flight = 0
        self._pulled = 0
        self._written = 0
        self._ok = 0
        self._errors = 0
        self._gen_tokens = 0
        self._occ_samples: List[float] = []
        self._abort: Optional[str] = None
        self._finished = threading.Event()

    # -- scheduler-thread side (via _EngineSource / _RecordSink) ----------

    def _build_request(self, index: int, rec: Dict[str, Any]):
        from substratus_tpu.serve.engine import Request

        sink = _RecordSink(self, index, rec)
        toks = record_prompt_tokens(rec, self.tokenizer)
        req = Request(
            prompt_tokens=toks,
            max_tokens=int(rec.get("max_tokens", self.default_max_tokens)),
            temperature=float(
                rec.get("temperature", self.default_temperature)
            ),
            top_p=float(rec.get("top_p", self.default_top_p)),
            adapter=rec.get("model"),
            out=sink,
            id=str(rec.get("id", index)),
        )
        sink.req = req
        sink.n_prompt = len(toks)
        return req

    def _fill_ready_locked(self) -> None:
        """Top the prefetch buffer up from the record cursor. Caller
        holds self._lock. Records whose fields don't validate become
        outcome=invalid completions (buffered like finished requests, so
        every counter write stays on the sink thread)."""
        while (
            self._records
            and self._abort is None
            and len(self._ready) < self._prefetch
        ):
            index, rec = self._records.popleft()
            try:
                self._ready.append(self._build_request(index, rec))
            except ValueError as e:
                bad = _RecordSink(self, index, rec)
                bad.error = f"invalid: {e}"
                self._buf.append(bad)
                self._wake.set()

    def _pull(self):
        """Next request for a freed slot — the engine scheduler thread's
        same-iteration refill. Pops a prefetched request; falls back to
        building one inline when the prefetcher is behind."""
        with self._lock:
            if self._abort is not None:
                return None
            if not self._ready:
                self._fill_ready_locked()
            if not self._ready:
                return None
            req = self._ready.pop(0)
            self._in_flight += 1
            self._pulled += 1
            return req

    def _pending_refill(self) -> bool:
        with self._lock:
            return bool(self._ready) or bool(self._records)

    def _complete(self, sink: _RecordSink) -> None:
        with self._lock:
            self._buf.append(sink)
            self._in_flight -= 1
        self._wake.set()

    # -- sink thread -------------------------------------------------------

    def _write_one(self, sink: _RecordSink) -> None:
        req = sink.req
        if sink.error is not None:
            outcome, finish = "invalid", sink.error
        elif req is not None and req.finish_reason == "error":
            outcome, finish = "error", "error"
        else:
            outcome, finish = "ok", req.finish_reason
        out: Dict[str, Any] = {
            "index": sink.index,
            "id": str(sink.rec.get("id", sink.index)),
            "tokens": list(sink.tokens),
            "finish_reason": finish,
            "prompt_tokens": sink.n_prompt,
            "gen_tokens": len(sink.tokens),
        }
        model = sink.rec.get("model")
        if model is not None:
            out["model"] = model
        if self.tokenizer is not None and sink.tokens:
            out["text"] = self.tokenizer.decode(list(sink.tokens))
        self._writer.write(out)
        self._written += 1
        self._gen_tokens += len(sink.tokens)
        if outcome == "ok":
            self._ok += 1
            if self.record_hook is not None:
                # Hook AFTER the durable write and only for ok records:
                # a consumer (the RL episode buffer) never sees a record
                # the resume ledger could replay differently. Prompt ids
                # ride along — the output record only stores their count.
                self.record_hook(
                    dict(out),
                    list(req.prompt_tokens) if req is not None else [],
                )
        else:
            self._errors += 1
        METRICS.inc(
            "substratus_batchgen_records_total", {"outcome": outcome}
        )

    def _sampler_loop(self) -> None:
        """Steady-cadence occupancy sampling on its own thread. The sink
        loop wakes on COMPLETIONS, so sampling there would land every
        sample right inside the refill window and bias the mean low;
        this thread's clock is independent of the scheduler's phase."""
        while not self._finished.wait(timeout=self.sample_interval_s):
            # Racy read of each engine's host-side active mask: a torn
            # snapshot skews one sample by one slot; the mean absorbs it.
            active = sum(int(e.active.sum()) for e in self.engines)
            occ = active / self._slots_total
            METRICS.set("substratus_batchgen_slot_occupancy", occ)
            with self._lock:
                refill_possible = bool(self._ready) or bool(self._records)
                warm = self._pulled >= self._slots_total
            done_frac = (self.resumed + self._written) / max(1, self.total)
            METRICS.set(
                "substratus_batchgen_manifest_progress_ratio", done_frac
            )
            if refill_possible and warm:
                # Steady state: the batch has filled once and refill is
                # still possible — ramp-up and the final drain (where
                # decay is inevitable, not a scheduling failure) don't
                # count.
                self._occ_samples.append(occ)

    def _sink_loop(self) -> None:
        while True:
            self._wake.wait(timeout=self.flush_interval_s)
            self._wake.clear()
            with self._lock:
                batch, self._buf = self._buf, []
                # Prefetch here too, so tokenize/Request construction
                # stays off the scheduler threads' fast path.
                self._fill_ready_locked()
                if self._abort is None:
                    dead = next(
                        (e for e in self.engines if e.error is not None),
                        None,
                    )
                    if dead is not None:
                        self._abort = f"engine died: {dead.error!r}"
                aborted = self._abort is not None
            for sink in batch:
                self._write_one(sink)
            if batch:
                self._writer.flush()
            if aborted:
                return
            with self._lock:
                if (
                    not self._records
                    and not self._ready
                    and self._in_flight == 0
                    and not self._buf
                ):
                    return

    # -- driver API --------------------------------------------------------

    def progress(self) -> Dict[str, Any]:
        """Manifest progress for load_snapshot()/loadz (read-only; torn
        reads across counters are fine for a progress report)."""
        with self._lock:
            return {
                "manifest_records": self.total,
                "resumed": self.resumed,
                "written": self._written,
                "errors": self._errors,
                "in_flight": self._in_flight,
                "pending": len(self._records) + len(self._ready),
            }

    def cancel(self, reason: str = "cancelled") -> None:
        with self._lock:
            self._abort = reason
        self._wake.set()

    def run(self) -> Dict[str, Any]:
        """Drive the manifest to completion; returns the run summary.
        Raises RuntimeError when an engine dies mid-run (already-written
        shards stay durable — a rerun resumes from them)."""
        t0 = time.perf_counter()
        if not self._records:
            self._writer.close()
            return self._summary(time.perf_counter() - t0)
        first = self._writer.open_shard()
        log.info(
            "batchgen: %d records (%d resumed) -> %s",
            len(self._records), self.resumed, first,
        )
        sink_thread = threading.Thread(target=self._sink_loop, daemon=True)
        sampler = threading.Thread(target=self._sampler_loop, daemon=True)
        for e in self.engines:
            e.set_source(_EngineSource(self))
        sink_thread.start()
        sampler.start()
        try:
            sink_thread.join()
        finally:
            self._finished.set()
            sampler.join(timeout=5)
            for e in self.engines:
                e.set_source(None)
            self._writer.close()
        if self._abort is not None:
            raise RuntimeError(f"batch generation aborted: {self._abort}")
        return self._summary(time.perf_counter() - t0)

    def _summary(self, wall: float) -> Dict[str, Any]:
        occ = (
            round(sum(self._occ_samples) / len(self._occ_samples), 4)
            if self._occ_samples else None
        )
        return {
            "manifest_records": self.total,
            "resumed": self.resumed,
            "written": self._written,
            "ok": self._ok,
            "errors": self._errors,
            "gen_tokens": self._gen_tokens,
            "wall_s": round(wall, 3),
            "gen_tok_s": (
                round(self._gen_tokens / wall, 1) if wall > 0 else 0.0
            ),
            "slot_occupancy": occ,
            "occupancy_samples": len(self._occ_samples),
            "actors": len(self.engines),
        }


class ProgressServer:
    """Optional observation endpoint for an offline run: /loadz (the
    engine load snapshot, which carries the driver's manifest progress
    once the source is attached) and /metrics (the shared registry).
    http.server on a daemon thread — no aiohttp, no serving stack; batch
    Jobs have no HTTP path by design and this one exists purely so
    `kubectl port-forward` can watch progress."""

    def __init__(self, engine, host: str = "0.0.0.0", port: int = 8080):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                if self.path == "/loadz":
                    body = json.dumps(engine.load_snapshot()).encode()
                    ctype = "application/json"
                elif self.path == "/metrics":
                    body = METRICS.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a) -> None:
                pass  # progress polls must not spam the job log

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="offline batch generation from a JSONL prompt manifest"
    )
    ap.add_argument("--manifest", default=None,
                    help="JSONL prompt manifest (default: params "
                         "batchGenerate.manifest, then "
                         "/content/data/prompts.jsonl)")
    ap.add_argument("--output", default=None,
                    help="output shard directory (default: params "
                         "batchGenerate.output, then "
                         "/content/artifacts/generations)")
    ap.add_argument("--model", default=None, help="checkpoint dir")
    ap.add_argument("--config", default=None,
                    help="named config for random-weight smoke runs")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument("--quantize", default=None,
                    choices=["int8", "w8a8", "int4", "none"])
    ap.add_argument("--max-tokens", type=int, default=None,
                    help="default generation budget for records without "
                         "their own max_tokens")
    ap.add_argument("--temperature", type=float, default=None)
    ap.add_argument("--records-per-shard", type=int, default=None)
    ap.add_argument("--no-resume", action="store_true",
                    help="ignore existing output shards (default: skip "
                         "every record already durably written)")
    ap.add_argument("--progress-port", type=int, default=None,
                    help="serve /loadz + /metrics on this port (0 = "
                         "ephemeral; default off — batch runs need no "
                         "HTTP path)")
    ap.add_argument("--step-floor-ms", type=float, default=0.0,
                    help="simulated device-step floor (bench/tests)")
    ap.add_argument("--params", default="/content/params.json")
    args = ap.parse_args(argv)

    from substratus_tpu.utils.jaxenv import honor_requested_platform

    honor_requested_platform()

    import jax

    from substratus_tpu.observability.propagation import context_from_env
    from substratus_tpu.observability.tracing import tracer
    from substratus_tpu.parallel.distributed import maybe_initialize
    from substratus_tpu.serve.engine import Engine, EngineConfig
    from substratus_tpu.serve.main import (
        build_adapter_store,
        load_checkpoint,
        load_params_json,
        resolve_kv_layout,
        _maybe_quantize,
    )
    from substratus_tpu.serve.tokenizer import load_tokenizer

    maybe_initialize()

    params_json = load_params_json(args.params)
    from substratus_tpu.utils.params import warn_unknown_keys

    bg = params_json.get("batchGenerate") or {}
    if not isinstance(bg, dict):
        bg = {}
    warn_unknown_keys(
        bg,
        ("manifest", "output", "maxTokens", "temperature",
         "recordsPerShard", "progressPort"),
        "batchgen.params.batchGenerate",
    )
    manifest = args.manifest or bg.get("manifest") or (
        "/content/data/prompts.jsonl"
    )
    output = args.output or bg.get("output") or (
        "/content/artifacts/generations"
    )
    if not os.path.exists(manifest):
        raise SystemExit(f"prompt manifest not found: {manifest}")

    from substratus_tpu.models import registry

    model_dir = args.model or params_json.get("model") or (
        "/content/model" if os.path.isdir("/content/model") else None
    )
    quantize = args.quantize or params_json.get("quantize", "none")
    if model_dir:
        cfg, params = load_checkpoint(model_dir)
        tokenizer = load_tokenizer(model_dir)
    else:
        name = args.config or params_json.get("config", "tiny")
        family, cfg = registry.find_named_config(name)
        tokenizer = load_tokenizer(None)
        if cfg.vocab_size < tokenizer.vocab_size:
            cfg = cfg.replace(vocab_size=tokenizer.vocab_size)
        params = family.init_params(cfg, jax.random.key(0))
    family = registry.module_of(cfg)
    cfg, params = _maybe_quantize(family, cfg, params, quantize)

    max_batch = args.max_batch or int(params_json.get("max_batch", 8))
    max_seq_len = args.max_seq_len or int(
        params_json.get("max_seq_len", 1024)
    )
    ec = EngineConfig(
        max_batch=max_batch,
        max_seq_len=min(max_seq_len, cfg.max_seq_len),
        max_prefill_len=int(
            params_json.get("max_prefill_len", EngineConfig.max_prefill_len)
        ),
        eos_token_id=(
            tokenizer.eos_id if tokenizer.eos_id is not None else 2
        ),
        kv_cache_dtype=params_json.get("kv_cache_dtype", "model"),
        kv_layout=resolve_kv_layout(params_json),
        step_floor_s=args.step_floor_ms / 1e3,
    )

    mesh = None
    n_dev = len(jax.devices())
    if n_dev > 1:
        from substratus_tpu.parallel.mesh import build_mesh

        # Offline generation wants throughput: tensor-parallel over the
        # kv heads, data-parallel the rest (same derivation as
        # serve.main, without the sequence-parallel serving knobs).
        tp = int(params_json.get("tensor", 0)) or min(n_dev, cfg.n_kv_heads)
        while n_dev % tp or cfg.n_kv_heads % tp:
            tp -= 1
        dp = n_dev // tp
        mesh = build_mesh(data=dp, tensor=tp)
        if max_batch % dp:
            ec.max_batch = ((max_batch // dp) + 1) * dp
        print(f"batchgen mesh: data={dp} tensor={tp}", flush=True)

    sync = None
    if jax.process_count() > 1:
        from substratus_tpu.serve.multihost import StepSync

        sync = StepSync()
        print(
            f"batchgen gang: process {sync.process_index}/"
            f"{sync.num_processes} "
            f"({'leader' if sync.leader else 'follower'})",
            flush=True,
        )

    adapters = build_adapter_store(family, cfg, params_json, None)

    engine = Engine(
        cfg, params, ec, mesh=mesh, model=family, sync=sync,
        adapters=adapters,
    )
    engine.start()

    if sync is not None and not sync.leader:
        # Follower: mirror the leader's scheduler (refill pulls arrive
        # via the broadcast) until the stop event. Exit nonzero on an
        # engine error so the JobSet failurePolicy restarts the gang.
        engine._thread.join()
        if engine.error is not None:
            print(f"follower engine died: {engine.error!r}", flush=True)
            return 1
        return 0

    progress_srv = None
    if args.progress_port is not None or bg.get("progressPort") is not None:
        port = (
            args.progress_port
            if args.progress_port is not None
            else int(bg["progressPort"])
        )
        progress_srv = ProgressServer(engine, port=port)
        print(f"batchgen progress on :{progress_srv.port}", flush=True)

    driver = BatchGenDriver(
        [engine],
        manifest,
        output,
        tokenizer=tokenizer,
        max_tokens=(
            args.max_tokens
            if args.max_tokens is not None
            else int(bg.get("maxTokens", 64))
        ),
        temperature=(
            args.temperature
            if args.temperature is not None
            else float(bg.get("temperature", 0.0))
        ),
        records_per_shard=(
            args.records_per_shard or int(bg.get("recordsPerShard", 10000))
        ),
        resume=not args.no_resume,
    )
    rc = 0
    try:
        with tracer.span(
            "batchgen.run", parent=context_from_env(),
            manifest=manifest, records=driver.total,
        ):
            summary = driver.run()
        print(json.dumps(summary), flush=True)
    except RuntimeError as e:
        print(json.dumps({"error": str(e)}), flush=True)
        rc = 1
    finally:
        if progress_srv is not None:
            progress_srv.close()
        # On a gang leader this also releases the followers: the stop
        # flag rides the next event broadcast (serve/multihost.py).
        engine.stop()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
