"""shardlint: static validation of sharding-spec construction sites.

The PR 3 axis-overlap bugs (ops/quant4.py, ops/kernel_partition.py) were
mesh-axis bookkeeping errors that only surfaced at runtime on a sharded
mesh. This check catches the statically-decidable slice of that bug
class at lint time, against the canonical mesh-axis registry
(parallel/mesh.py MESH_AXES — read from its AST, never imported):

  * every literal axis name in a PartitionSpec/P(...) construction must
    be a registered mesh axis (an axis absent from the registry is
    absent from every mesh build_mesh can produce);
  * one mesh axis may appear only once per spec — reuse across the
    dimensions of a single P(...) is flagged, with tuple entries
    flattened (P("data", ("data", "tensor")) collides on "data");
  * LogicalRules tables and .replace(...) updates: the mesh-axis side
    of every rule must be registered;
  * axis_name= / axis_names= keyword literals (psum, shard_map, ring /
    ulysses attention) and function defaults must be registered;
  * mesh.shape["..."] subscripts must name a registered axis.

Dynamic specs (P(*parts), P(m_axis, n_axis)) are skipped — the runtime
overlap checks in ops/ own those.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from substratus_tpu.analysis.core import (
    Check,
    Finding,
    SourceFile,
    call_name,
    const_str,
)

MESH_MODULE = "parallel/mesh.py"


def load_registry(files: Dict[str, SourceFile]) -> Optional[Tuple[str, ...]]:
    """Parse MESH_AXES out of parallel/mesh.py's AST — the registry is
    read from source so the lint never imports jax."""
    for rel, sf in files.items():
        if not rel.endswith(MESH_MODULE) or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "MESH_AXES":
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        axes = [const_str(e) for e in node.value.elts]
                        if all(a is not None for a in axes):
                            return tuple(axes)
    return None


def _flatten_spec_entry(node: ast.AST) -> Tuple[List[str], bool]:
    """(literal axis names, fully_literal) for one P(...) entry."""
    if isinstance(node, ast.Constant):
        if node.value is None:
            return [], True
        if isinstance(node.value, str):
            return [node.value], True
        return [], False
    if isinstance(node, (ast.Tuple, ast.List)):
        names: List[str] = []
        literal = True
        for e in node.elts:
            sub, lit = _flatten_spec_entry(e)
            names.extend(sub)
            literal = literal and lit
        return names, literal
    return [], False


class ShardCheck(Check):
    name = "shard"
    description = (
        "PartitionSpec / LogicalRules / axis-name literals validate "
        "against the canonical mesh-axis registry (parallel/mesh.py); "
        "no axis reuse within one spec"
    )

    def __init__(self, registry: Optional[Sequence[str]] = None):
        self.registry = tuple(registry) if registry is not None else None

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        registry = self.registry or load_registry(files)
        if registry is None:
            return [
                Finding(
                    check="shard", path=MESH_MODULE, line=1, col=1,
                    message=(
                        "mesh-axis registry not found: expected a literal "
                        "MESH_AXES = (...) in parallel/mesh.py"
                    ),
                )
            ]
        out: List[Finding] = []
        for sf in files.values():
            if sf.tree is not None:
                out.extend(self._run_module(sf, frozenset(registry), registry))
        return out

    def _run_module(
        self, sf: SourceFile, known: frozenset, registry: Tuple[str, ...]
    ) -> List[Finding]:
        out: List[Finding] = []
        pspec_names = {"PartitionSpec"}
        rules_names = set()

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    if alias.name == "PartitionSpec":
                        pspec_names.add(alias.asname or alias.name)
                    if node.module.endswith("parallel.sharding") and (
                        alias.name.isupper()
                    ):
                        rules_names.add(alias.asname or alias.name)
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                fn = call_name(node.value)
                derived = fn == "LogicalRules" or (
                    fn.endswith(".replace")
                    and isinstance(node.value.func, ast.Attribute)
                    and isinstance(node.value.func.value, ast.Name)
                    and node.value.func.value.id in rules_names
                )
                if derived:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            rules_names.add(tgt.id)

        def bad_axis(name: str, where: ast.AST, what: str) -> None:
            out.append(
                Finding(
                    check="shard", path=sf.rel, line=where.lineno,
                    col=where.col_offset + 1,
                    message=(
                        f"unknown mesh axis {name!r} in {what}: not in the "
                        f"registry {registry} (parallel/mesh.py MESH_AXES) — "
                        "no declared mesh carries it"
                    ),
                )
            )

        def check_rule_value(value: ast.AST, where: ast.AST, what: str) -> None:
            names, _ = _flatten_spec_entry(value)
            for n in names:
                if n not in known:
                    bad_axis(n, where, what)

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                self._check_call(
                    node, sf, known, pspec_names, rules_names,
                    bad_axis, check_rule_value, out,
                )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # def f(..., axis_name: str = "sequence")
                args = node.args
                all_args = args.args + args.kwonlyargs
                defaults = (
                    [None] * (len(args.args) - len(args.defaults))
                    + list(args.defaults)
                    + list(args.kw_defaults)
                )
                for a, d in zip(all_args, defaults):
                    if d is None or a.arg not in ("axis_name", "axis_names"):
                        continue
                    names, _ = _flatten_spec_entry(d)
                    for n in names:
                        if n not in known:
                            bad_axis(n, d, f"default of {a.arg!r}")
            elif isinstance(node, ast.Subscript):
                # mesh.shape["tensor"]
                if (
                    isinstance(node.value, ast.Attribute)
                    and node.value.attr == "shape"
                ):
                    key = node.slice
                    name = const_str(key)
                    if name is not None and name not in known:
                        bad_axis(name, node, "a mesh.shape[...] lookup")
        return out

    def _check_call(
        self, node, sf, known, pspec_names, rules_names,
        bad_axis, check_rule_value, out,
    ) -> None:
        fn = call_name(node)
        base = fn.rsplit(".", 1)[-1]

        # PartitionSpec construction: unknown axes + intra-spec reuse.
        if base in pspec_names or fn.endswith(".PartitionSpec"):
            if any(isinstance(a, ast.Starred) for a in node.args):
                return  # dynamic P(*parts)
            seen: Dict[str, int] = {}
            for arg in node.args:
                names, _ = _flatten_spec_entry(arg)
                for n in names:
                    if n not in known:
                        bad_axis(n, node, "a PartitionSpec")
                    seen[n] = seen.get(n, 0) + 1
            dupes = sorted(n for n, c in seen.items() if c > 1)
            if dupes:
                out.append(
                    Finding(
                        check="shard", path=sf.rel, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"mesh axis reuse within one PartitionSpec: "
                            f"{dupes} appear in more than one dimension "
                            "(one mesh axis may shard at most one dim; "
                            "tuple entries flatten)"
                        ),
                    )
                )
            return

        # LogicalRules((logical, mesh_axes), ...): validate the mesh side.
        if base == "LogicalRules" and node.args:
            table = node.args[0]
            if isinstance(table, (ast.Tuple, ast.List)):
                for pair in table.elts:
                    if (
                        isinstance(pair, (ast.Tuple, ast.List))
                        and len(pair.elts) == 2
                    ):
                        check_rule_value(
                            pair.elts[1], pair, "a LogicalRules mapping"
                        )
            return

        # RULES.replace(logical="mesh_axis", ...)
        if (
            base == "replace"
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in rules_names
        ):
            for kw in node.keywords:
                if kw.arg is not None and kw.value is not None:
                    check_rule_value(
                        kw.value, kw.value, f"LogicalRules.replace({kw.arg}=)"
                    )
            return

        # axis_name= / axis_names= keyword literals (psum, shard_map, ...).
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                src = kw.value
                elts = (
                    src.elts
                    if isinstance(src, (ast.Set, ast.Tuple, ast.List))
                    else [src]
                )
                for e in elts:
                    n = const_str(e)
                    if n is not None and n not in known:
                        bad_axis(n, e, f"{kw.arg}=")
