"""broad-except: `except:` / `except Exception:` handlers that swallow.

A handler this wide hides real failures (the reconcile loop retrying a
typo forever, a dead telemetry path nobody notices). Flagged unless the
handler re-raises the caught exception (a bare ``raise`` anywhere in its
body) — instrument-and-propagate wrappers stay legal. Deliberate
swallows (telemetry must never fail work, probe paths) carry
``# sublint: allow[broad-except]: reason`` on the ``except`` line, and
should log with the current trace id (observability/tracing.py
``current_trace_id``) so the swallow is at least visible in traces.
"""
from __future__ import annotations

import ast
from typing import Dict, List

from substratus_tpu.analysis.core import Check, Finding, SourceFile

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(
        isinstance(n, ast.Raise) and n.exc is None
        for n in ast.walk(handler)
    )


class BroadExceptCheck(Check):
    name = "broad-except"
    description = (
        "bare/Exception-wide handlers that swallow instead of "
        "narrowing, re-raising, or logging with a documented reason"
    )

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for sf in files.values():
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not _is_broad(node) or _reraises(node):
                    continue
                what = (
                    "bare 'except:'" if node.type is None
                    else "broad 'except Exception'"
                )
                out.append(
                    Finding(
                        check="broad-except", path=sf.rel,
                        line=node.lineno, col=node.col_offset + 1,
                        message=(
                            f"{what} swallows errors: narrow the type, "
                            "re-raise, or suppress with a reason and log "
                            "with the trace id "
                            "(observability.tracing.current_trace_id)"
                        ),
                    )
                )
        return out
