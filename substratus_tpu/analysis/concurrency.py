"""concurrency-lint: thread and event-loop hazards.

Three sub-checks:

  * **shared-attr**: in the configured serving modules, a `self.x`
    attribute written both by a thread-entry method (a method passed as
    `Thread(target=self.m)` / `run_in_executor(None, self.m)`, plus its
    intra-class callees) and by a method running on other threads, with
    at least one of the writes outside a `with self.<lock>:` block. The
    engine's threading contract — one scheduler thread owns all device
    state — stays enforceable as the code grows.
  * **thread-lifecycle**: `threading.Thread(...)` created neither
    `daemon=True` nor `.join()`ed anywhere in the module — a thread
    that can outlive shutdown silently.
  * **async-blocking**: known blocking calls (`time.sleep`,
    `subprocess.*`, `urllib.request.urlopen`, `os.system`, ...)
    lexically inside an `async def` (nested sync `def`s are exempt:
    that's the `run_in_executor` pattern).

Writes in ``__init__`` are pre-thread construction and ignored.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from substratus_tpu.analysis.core import Check, Finding, SourceFile, call_name

DEFAULT_SHARED_ATTR_MODULES: Tuple[str, ...] = (
    "serve/engine.py",
    "serve/server.py",
    # The AdapterStore is shared between the engine scheduler thread and
    # HTTP handlers (locked-attr discipline: every shared write under
    # self._lock) — any future thread-entry method there inherits the
    # engine's scrutiny.
    "serve/adapters.py",
    # The gateway is single-event-loop by contract (balancer.py docs);
    # covering it means any future thread handed a router method gets
    # the same unlocked-write scrutiny as the engine.
    "gateway/router.py",
    "gateway/balancer.py",
    # The KV-handoff layer is the most thread-dense module in the tree
    # (sender thread, per-channel readers, accept loop, the engine
    # scheduler calling ship()) — its _lock discipline stays enforced.
    "serve/disagg.py",
    # The batch-generation driver: engine scheduler threads call
    # _pull/_complete while the sink thread swaps the buffer and a
    # sampler thread reads progress — every shared write rides
    # self._lock (docs/batch-generation.md).
    "serve/batchgen.py",
    # Fleet telemetry (ISSUE 11): the aggregator is event-loop
    # confined like the balancer, and the step-timeline ring is
    # written by the engine scheduler thread while /debug/stepz
    # handlers read it — both keep the same scrutiny so an unlocked
    # shared write added later gets flagged, not shipped.
    "gateway/fleet.py",
    "observability/timeline.py",
    # The autoscale decision core + Server wiring (ISSUE 12): the
    # reconciler runs on the manager's loop thread today, but the
    # per-fleet Autoscaler instances hold mutable timing state
    # (cooldown stamps, sustain windows, seq latches) that a future
    # second entry point (e.g. a gateway-side caller) would share —
    # the same unlocked-write scrutiny as the engine catches that on
    # the PR, not in production.
    "controller/autoscale.py",
    # Request journeys (ISSUE 17): the event ring is written by the
    # engine scheduler thread, stitched by the disagg reader thread,
    # and read by /debug/requestz|slowz handlers — every shared write
    # rides the per-journey _lock, and new entry points inherit the
    # same unlocked-write scrutiny as the timeline ring.
    "observability/journey.py",
    # The RL actor-learner loop (ISSUE 20): the episode buffer is
    # written by the batchgen sink thread while the learner thread
    # drains it (lock-guarded swap), and swap_params stages weights
    # into the engine from the learner thread — the whole package
    # inherits the engine's unlocked-write scrutiny.
    "rl/buffer.py",
    "rl/learner.py",
    "rl/loop.py",
)

_BLOCKING = {
    "time.sleep": "time.sleep blocks the event loop",
    "os.system": "os.system blocks the event loop",
    "subprocess.run": "subprocess.run blocks the event loop",
    "subprocess.call": "subprocess.call blocks the event loop",
    "subprocess.check_call": "subprocess.check_call blocks the event loop",
    "subprocess.check_output": "subprocess.check_output blocks the event loop",
    "urllib.request.urlopen": "urlopen blocks the event loop",
    "socket.create_connection": "socket connect blocks the event loop",
}


def _is_thread_call(node: ast.Call) -> bool:
    name = call_name(node)
    return name == "threading.Thread" or name == "Thread"


def _lock_guarded(with_stack: Sequence[ast.AST]) -> bool:
    """True when any enclosing `with` context expression mentions a name
    containing 'lock' or 'mutex' (e.g. `with self._lock:`)."""
    for w in with_stack:
        for item in getattr(w, "items", []):
            expr = item.context_expr
            for node in ast.walk(expr):
                ident = None
                if isinstance(node, ast.Attribute):
                    ident = node.attr
                elif isinstance(node, ast.Name):
                    ident = node.id
                if ident and (
                    "lock" in ident.lower() or "mutex" in ident.lower()
                ):
                    return True
    return False


class _WriteCollector(ast.NodeVisitor):
    """self.<attr> writes inside one method, with lock context."""

    def __init__(self) -> None:
        self.writes: List[Tuple[str, ast.AST, bool]] = []
        self._with_stack: List[ast.AST] = []

    def _record(self, target: ast.AST) -> None:
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            self.writes.append(
                (target.attr, target, _lock_guarded(self._with_stack))
            )

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._record(node.target)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        self._with_stack.append(node)
        self.generic_visit(node)
        self._with_stack.pop()

    visit_AsyncWith = visit_With


def _self_target_methods(cls: ast.ClassDef) -> Set[str]:
    """Method names handed to another thread: Thread(target=self.m) or
    run_in_executor(<executor>, self.m)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        cands: List[ast.AST] = []
        if _is_thread_call(node):
            cands.extend(
                kw.value for kw in node.keywords if kw.arg == "target"
            )
        elif call_name(node).endswith("run_in_executor") and len(node.args) >= 2:
            cands.append(node.args[1])
        for c in cands:
            if (
                isinstance(c, ast.Attribute)
                and isinstance(c.value, ast.Name)
                and c.value.id == "self"
            ):
                out.add(c.attr)
    return out


class ConcurrencyCheck(Check):
    name = "concurrency"
    description = (
        "unlocked cross-thread attribute writes in the serving modules; "
        "threads without daemon/join; blocking calls in async handlers"
    )

    def __init__(
        self,
        shared_attr_modules: Sequence[str] = DEFAULT_SHARED_ATTR_MODULES,
    ):
        self.shared_attr_modules = tuple(shared_attr_modules)

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for sf in files.values():
            if sf.tree is None:
                continue
            out.extend(self._thread_lifecycle(sf))
            out.extend(self._async_blocking(sf))
            if any(sf.rel.endswith(m) for m in self.shared_attr_modules):
                out.extend(self._shared_attrs(sf))
        return out

    # -- thread lifecycle --------------------------------------------------

    def _thread_lifecycle(self, sf: SourceFile) -> List[Finding]:
        out: List[Finding] = []
        assigned: Dict[int, str] = {}  # Thread call lineno -> target source
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if _is_thread_call(node.value):
                    for t in node.targets:
                        assigned[node.value.lineno] = ast.unparse(t)
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_thread_call(node)):
                continue
            daemon = next(
                (kw.value for kw in node.keywords if kw.arg == "daemon"), None
            )
            if daemon is not None:
                if isinstance(daemon, ast.Constant) and daemon.value is False:
                    pass  # explicit non-daemon: fall through to join check
                else:
                    continue  # daemon=True or dynamic: accepted
            target = assigned.get(node.lineno)
            joined = target and f"{target}.join" in sf.text
            if not joined:
                out.append(
                    Finding(
                        check="concurrency", path=sf.rel,
                        line=node.lineno, col=node.col_offset + 1,
                        message=(
                            "thread created without daemon=True and never "
                            ".join()ed in this module — it can outlive "
                            "shutdown; mark it daemon or join it"
                        ),
                    )
                )
        return out

    # -- blocking calls inside async defs ---------------------------------

    def _async_blocking(self, sf: SourceFile) -> List[Finding]:
        out: List[Finding] = []

        def walk(node: ast.AST, in_async: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.AsyncFunctionDef):
                    walk(child, True)
                elif isinstance(child, (ast.FunctionDef, ast.Lambda)):
                    walk(child, False)  # executor-bound sync body
                else:
                    if in_async and isinstance(child, ast.Call):
                        why = _BLOCKING.get(call_name(child))
                        if why is not None:
                            out.append(
                                Finding(
                                    check="concurrency", path=sf.rel,
                                    line=child.lineno,
                                    col=child.col_offset + 1,
                                    message=(
                                        f"{why}: run it in an executor "
                                        "(await loop.run_in_executor) or "
                                        "use the async equivalent"
                                    ),
                                )
                            )
                    walk(child, in_async)

        walk(sf.tree, False)
        return out

    # -- cross-thread shared attribute writes ------------------------------

    def _shared_attrs(self, sf: SourceFile) -> List[Finding]:
        out: List[Finding] = []
        for cls in sf.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {
                n.name: n
                for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            entries = _self_target_methods(cls) & set(methods)
            if not entries:
                continue
            # Closure of the thread-entry methods over self-calls.
            owned: Set[str] = set()
            frontier = list(entries)
            while frontier:
                cur = frontier.pop()
                if cur in owned:
                    continue
                owned.add(cur)
                for node in ast.walk(methods[cur]):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods
                    ):
                        frontier.append(node.func.attr)

            def writes_of(names: Set[str]):
                acc: Dict[str, List[Tuple[ast.AST, bool, str]]] = {}
                for m in names:
                    col = _WriteCollector()
                    col.visit(methods[m])
                    for attr, node, locked in col.writes:
                        acc.setdefault(attr, []).append((node, locked, m))
                return acc

            others = set(methods) - owned - {"__init__"}
            w_thread = writes_of(owned)
            w_other = writes_of(others)
            for attr in sorted(set(w_thread) & set(w_other)):
                both = w_thread[attr] + w_other[attr]
                unlocked = [(n, m) for n, locked, m in both if not locked]
                if not unlocked:
                    continue
                node, method = unlocked[0]
                sites = sorted(
                    {f"{m}:{n.lineno}" for n, _l, m in both}
                )
                out.append(
                    Finding(
                        check="concurrency", path=sf.rel,
                        line=node.lineno, col=node.col_offset + 1,
                        message=(
                            f"self.{attr} is written from the "
                            f"{sorted(entries)} thread entry point(s) AND "
                            f"from other-thread methods ({sites}) without "
                            "a lock on every write — guard with a lock or "
                            "confine writes to one thread"
                        ),
                    )
                )
        return out
