"""lifecycle-lint: paired-call discipline for leak-prone resources.

Three resource contracts whose leak is a production incident, not a
style nit (each was pinned by convention/tests in PRs 5–7; this family
is their static gate):

  * **paged-KV pages** (`serve/paged_kv.py` allocator): every module
    that calls ``.alloc()`` on a pool/allocator must also free
    (``decref``/``release``); an alloc whose result is discarded is a
    guaranteed leak; an alloc held across a ``try`` whose handler
    swallows-and-exits without freeing leaks on the exception path.
  * **adapter-slot pins** (`serve/adapters.py` ``acquire``/``release``):
    same balance rules for the pin refcounts that keep LRU eviction
    from pulling weights out from under an active decode.
  * **shutdown-before-close sockets** (the PR 7 disagg contract): in
    the threaded socket modules, ``close()`` on a socket another thread
    may be blocked ``recv()``/``accept()``-ing neither wakes that
    thread nor reliably sends FIN — every such ``close()`` must be
    preceded by ``shutdown(SHUT_RDWR)`` on the same receiver
    (docs/serving.md "Failure semantics").

The checks are deliberately per-function/per-module AST reasoning, not
full dataflow: cross-function pin lifecycles (acquire at admission,
release at slot teardown) are validated as module-level balance, while
the two precise rules — discarded handle, exception-path leak — fire
only on patterns that are leaks by construction.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from substratus_tpu.analysis.core import Check, Finding, SourceFile, call_name


@dataclass(frozen=True)
class ResourcePair:
    """One paired-call contract: calls whose dotted name ends with an
    open suffix AND whose receiver identifier contains a hint must be
    balanced by close-suffix calls in the same module."""

    name: str
    open_suffixes: Tuple[str, ...]
    close_suffixes: Tuple[str, ...]
    receiver_hints: Tuple[str, ...]  # substring match on the receiver id
    modules: Tuple[str, ...]  # suffix match; where the contract applies


DEFAULT_RESOURCES: Tuple[ResourcePair, ...] = (
    ResourcePair(
        name="kv-page",
        open_suffixes=(".alloc",),
        close_suffixes=(".decref", ".release", ".free"),
        receiver_hints=("alloc", "pool"),
        modules=("serve/engine.py",),
    ),
    ResourcePair(
        name="adapter-pin",
        open_suffixes=(".acquire",),
        close_suffixes=(".release",),
        receiver_hints=("adapter",),
        modules=("serve/engine.py", "serve/server.py"),
    ),
    # Batch-generation output shards (serve/batchgen.py ShardWriter):
    # an opened shard the sink thread never closes is a lost flush — the
    # records in its user-space buffer would be regenerated on resume,
    # but the driver would report them written. open_shard()/close()
    # must balance in the driver.
    ResourcePair(
        name="shard-file",
        open_suffixes=(".open_shard",),
        close_suffixes=(".close",),
        receiver_hints=("writer", "out"),
        modules=("serve/batchgen.py",),
    ),
)

# Threaded socket modules where the shutdown-before-close contract is
# load-bearing (another thread may be blocked on the same fd).
DEFAULT_SOCKET_MODULES: Tuple[str, ...] = (
    "serve/disagg.py",
    "serve/multihost.py",
    "gateway/testing.py",
)

_SOCKETISH = ("sock", "conn", "srv", "listener", "client_s")


def _recv_ident(node: ast.AST) -> Optional[str]:
    """Receiver identifier of an attribute call chain: `self.alloc.alloc`
    -> "alloc", `pool.alloc` -> "pool", `c.close` -> "c"."""
    if isinstance(node, ast.Attribute):
        base = node.value
        if isinstance(base, ast.Attribute):
            return base.attr
        if isinstance(base, ast.Name):
            return base.id
    return None


def _matches(pair: ResourcePair, node: ast.Call) -> bool:
    name = call_name(node)
    if not any(name.endswith(s) for s in pair.open_suffixes):
        return False
    ident = _recv_ident(node.func) or ""
    return any(h in ident.lower() for h in pair.receiver_hints)


def _is_close(pair: ResourcePair, node: ast.Call) -> bool:
    name = call_name(node)
    return any(name.endswith(s) for s in pair.close_suffixes)


def _socket_vars(fn: ast.AST) -> Set[str]:
    """Local names that definitely hold sockets: assigned from
    socket.socket(...)/create_connection(...)/X.accept(...), or bound by
    iterating a connection-list-ish attribute (for c in self._conns)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = call_name(node.value)
            if (
                name.endswith("socket.socket")
                or name == "socket"
                or name.endswith("create_connection")
                or name.endswith(".accept")
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
                    elif isinstance(t, ast.Tuple) and t.elts:
                        first = t.elts[0]
                        if isinstance(first, ast.Name):
                            out.add(first.id)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            it_ident = None
            if isinstance(node.iter, ast.Attribute):
                it_ident = node.iter.attr
            elif isinstance(node.iter, ast.Name):
                it_ident = node.iter.id
            # "chan"-named iterables are deliberately excluded: channel
            # WRAPPERS own the shutdown-then-close sequence internally.
            if it_ident and any(
                k in it_ident.lower() for k in ("conn", "sock")
            ):
                if isinstance(node.target, ast.Name):
                    out.add(node.target.id)
    return out


class LifecycleCheck(Check):
    name = "lifecycle"
    description = (
        "paired-call resource discipline: paged-KV alloc/free balance, "
        "adapter-slot pin/unpin balance, exception-path leaks, and the "
        "shutdown(SHUT_RDWR)-before-close() socket contract in the "
        "threaded transfer modules"
    )

    def __init__(
        self,
        resources: Sequence[ResourcePair] = DEFAULT_RESOURCES,
        socket_modules: Sequence[str] = DEFAULT_SOCKET_MODULES,
    ):
        self.resources = tuple(resources)
        self.socket_modules = tuple(socket_modules)

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for rel, sf in sorted(files.items()):
            if sf.tree is None:
                continue
            for pair in self.resources:
                if any(rel.endswith(m) for m in pair.modules):
                    out.extend(self._pair_findings(pair, sf))
            if any(rel.endswith(m) for m in self.socket_modules):
                out.extend(self._socket_findings(sf))
        return out

    # -- paired-call balance ------------------------------------------------

    def _pair_findings(
        self, pair: ResourcePair, sf: SourceFile
    ) -> List[Finding]:
        out: List[Finding] = []
        opens: List[ast.Call] = []
        closes: List[ast.Call] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                if _matches(pair, node):
                    opens.append(node)
                elif _is_close(pair, node):
                    closes.append(node)
        if not opens:
            return out
        if not closes:
            for node in opens:
                out.append(
                    Finding(
                        check="lifecycle", path=sf.rel,
                        line=node.lineno, col=node.col_offset + 1,
                        message=(
                            f"{pair.name}: {call_name(node)}() is called "
                            f"here but this module never calls any of "
                            f"{list(pair.close_suffixes)} — the resource "
                            "can only leak"
                        ),
                    )
                )
            return out
        # Discarded handle: an open call as a bare expression statement.
        for stmt in ast.walk(sf.tree):
            if (
                isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and _matches(pair, stmt.value)
            ):
                out.append(
                    Finding(
                        check="lifecycle", path=sf.rel,
                        line=stmt.lineno, col=stmt.col_offset + 1,
                        message=(
                            f"{pair.name}: result of "
                            f"{call_name(stmt.value)}() is discarded — "
                            "nothing can ever free this handle"
                        ),
                    )
                )
        out.extend(self._exception_leaks(pair, sf))
        return out

    def _exception_leaks(
        self, pair: ResourcePair, sf: SourceFile
    ) -> List[Finding]:
        """An open BEFORE a try whose handler swallows-and-exits without
        a close (and no finally closes): the exception path leaks."""
        out: List[Finding] = []
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            opens = [
                n for n in ast.walk(fn)
                if isinstance(n, ast.Call) and _matches(pair, n)
            ]
            if not opens:
                continue
            for tr in ast.walk(fn):
                if not isinstance(tr, ast.Try):
                    continue
                prior = [o for o in opens if o.lineno < tr.lineno]
                if not prior:
                    continue  # open inside the try: a failing open holds nothing
                fin_closes = any(
                    isinstance(c, ast.Call) and _is_close(pair, c)
                    for f in tr.finalbody
                    for c in ast.walk(f)
                )
                if fin_closes:
                    continue
                for handler in tr.handlers:
                    closes = any(
                        isinstance(c, ast.Call) and _is_close(pair, c)
                        for s in handler.body
                        for c in ast.walk(s)
                    )
                    reraises = any(
                        isinstance(s, ast.Raise)
                        for s in ast.walk(handler)  # incl. nested raise
                    )
                    exits = any(
                        isinstance(s, (ast.Return, ast.Break, ast.Continue))
                        for b in handler.body
                        for s in ast.walk(b)
                    )
                    if exits and not closes and not reraises:
                        out.append(
                            Finding(
                                check="lifecycle", path=sf.rel,
                                line=handler.lineno,
                                col=handler.col_offset + 1,
                                message=(
                                    f"{pair.name}: resource opened at "
                                    f"line {prior[0].lineno} leaks on "
                                    "this exception path — the handler "
                                    "exits without any of "
                                    f"{list(pair.close_suffixes)}; free "
                                    "it in the handler or a finally"
                                ),
                            )
                        )
        return out

    # -- shutdown-before-close sockets --------------------------------------

    def _socket_findings(self, sf: SourceFile) -> List[Finding]:
        out: List[Finding] = []
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local_socks = _socket_vars(fn)
            shutdowns: List[Tuple[str, int]] = []
            closes: List[Tuple[str, int, ast.Call]] = []
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                ident = _recv_ident(node.func)
                if not ident:
                    continue
                sockish = ident in local_socks or any(
                    k in ident.lower() for k in _SOCKETISH
                )
                if not sockish:
                    continue
                if node.func.attr == "shutdown":
                    shutdowns.append((ident, node.lineno))
                elif node.func.attr == "close" and not node.args:
                    closes.append((ident, node.lineno, node))
            for ident, line, node in closes:
                if any(s_id == ident and s_line < line
                       for s_id, s_line in shutdowns):
                    continue
                out.append(
                    Finding(
                        check="lifecycle", path=sf.rel,
                        line=line, col=node.col_offset + 1,
                        message=(
                            f"socket {ident!r} is close()d without a "
                            "preceding shutdown(SHUT_RDWR) in this "
                            "function — a thread blocked in recv()/"
                            "accept() on this socket is neither woken "
                            "nor sent FIN (docs/serving.md \"Failure "
                            "semantics\")"
                        ),
                    )
                )
        return out
