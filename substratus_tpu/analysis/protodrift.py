"""protodrift-lint: producer/consumer agreement on hand-rolled wire
formats.

The serving stack has five hand-rolled protocols whose two ends live in
different modules (or different processes): the ``x-substratus-load``
header (gateway/loadreport.py), the disagg KV-handoff frames
(serve/disagg.py), the hello/PoolSpec negotiation, the lockstep
gang event broadcast (serve/multihost.py -> serve/engine.py), and the
request-journey segment on the disagg done frame
(observability/journey.py ``to_wire``/``from_wire``). A key
written on one side and dropped on the other is silent data loss — the
gateway quietly stops seeing transfer backlog, a decode worker ignores
a sampling parameter — so this family extracts the emitted and parsed
key sets from both ends and flags the symmetric difference:

  * **kvheader** protocols: producer keys from ``k=`` literals in
    f-strings/constants; consumer keys from ``.get("k")`` calls and
    ``== "k"`` comparisons.
  * **dict** protocols: producer keys from dict-literal string keys in
    the producer function; consumer keys from ``var["k"]`` /
    ``var.get("k")`` reads in the consumer function.
  * **frames** protocols: producer keys and ``"t"`` message kinds from
    dict literals passed to ``send``/``send_frame`` calls; consumer
    keys/kinds from reads of recv_frame-unpacked header variables.
  * **endian**: ``struct.pack``/``unpack`` and numpy dtype strings in
    the wire modules must carry an explicit byte order (the
    ``multihost.py`` big-endian-host lesson), and the pack side's
    (order, width) pairs must meet a matching read (``"<I"`` must meet
    ``"<I"``/``"<u4"`` — never a native-order view).
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from substratus_tpu.analysis.core import Check, Finding, SourceFile, call_name

_KV_KEY_RE = re.compile(r"([A-Za-z_][A-Za-z0-9_]*)=")


@dataclass(frozen=True)
class ProtoSpec:
    """One protocol: where its two ends live and how to read them.

    kind:
      * "kvheader": producer/consumer are (module_suffix, qualname)
        function refs; keys are `k=` literals vs get()/== reads.
      * "dict": dict-literal keys in producer fn vs subscript/get reads
        on local names in consumer fn.
      * "frames": producer/consumer are module suffixes; send-dict
        literals vs tracked header-var reads, plus "t" kind agreement.
    """

    name: str
    kind: str
    producers: Tuple[Tuple[str, str], ...]
    consumers: Tuple[Tuple[str, str], ...]
    # keys exempt from the drift check (documented one-sided fields)
    ignore: Tuple[str, ...] = ()


DEFAULT_PROTOCOLS: Tuple[ProtoSpec, ...] = (
    ProtoSpec(
        name="x-substratus-load",
        kind="kvheader",
        producers=(("gateway/loadreport.py", "LoadReport.to_header"),),
        consumers=(("gateway/loadreport.py", "LoadReport.from_header"),),
    ),
    ProtoSpec(
        name="disagg-frames",
        kind="frames",
        producers=(("serve/disagg.py", ""),),
        consumers=(("serve/disagg.py", ""),),
    ),
    ProtoSpec(
        name="poolspec-negotiation",
        kind="dict",
        producers=(("serve/disagg.py", "PoolSpec.to_dict"),),
        consumers=(("serve/disagg.py", "PoolSpec.from_dict"),),
    ),
    ProtoSpec(
        name="gang-events",
        kind="dict",
        producers=(("serve/multihost.py", "encode_events"),),
        consumers=(("serve/engine.py", "Engine._sync_iterate"),),
    ),
    # The request-journey segment shipped on the disagg ``done``
    # back-channel frame (``"j"`` key — the frame-level "tpar"/"j" keys
    # themselves ride the module-wide disagg-frames spec above).
    ProtoSpec(
        name="journey-segment",
        kind="dict",
        producers=(("observability/journey.py", "RequestJourney.to_wire"),),
        consumers=(("observability/journey.py", "RequestJourney.from_wire"),),
    ),
)

# Wire modules whose struct/numpy formats must be byte-order explicit.
DEFAULT_ENDIAN_MODULES: Tuple[str, ...] = (
    "serve/disagg.py",
    "serve/multihost.py",
)

# struct format characters that occupy >1 byte (order matters).
_MULTIBYTE_STRUCT = set("hHiIlLqQefd")
_STRUCT_FMT_RE = re.compile(r"^[@=<>!]?[0-9hHiIlLqQefdbBsxc]+$")
_NP_FMT_RE = re.compile(r"^([<>=|]?)([uif])(\d)$")

# struct char -> numpy (kindchar, bytes) equivalence for pairing.
_STRUCT_TO_NP = {
    "h": ("i", 2), "H": ("u", 2), "i": ("i", 4), "I": ("u", 4),
    "l": ("i", 8), "L": ("u", 8), "q": ("i", 8), "Q": ("u", 8),
    "e": ("f", 2), "f": ("f", 4), "d": ("f", 8),
}


def _find_fn(
    files: Dict[str, SourceFile], ref: Tuple[str, str]
) -> Optional[Tuple[SourceFile, ast.AST]]:
    suffix, qual = ref
    for rel, sf in sorted(files.items()):
        if not rel.endswith(suffix) or sf.tree is None:
            continue
        if not qual:
            return sf, sf.tree
        cls_name, _, fn_name = qual.rpartition(".")
        for node in sf.tree.body:
            if cls_name and isinstance(node, ast.ClassDef) \
                    and node.name == cls_name:
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and sub.name == fn_name:
                        return sf, sub
            elif not cls_name and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name == qual:
                return sf, node
    return None


def _str_fragments(fn: ast.AST) -> Iterable[Tuple[str, int]]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno
        elif isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.Constant) and isinstance(
                    part.value, str
                ):
                    yield part.value, node.lineno


def _kvheader_emitted(fn: ast.AST) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for text, line in _str_fragments(fn):
        for m in _KV_KEY_RE.finditer(text):
            out.setdefault(m.group(1), line)
    return out


def _read_keys(fn: ast.AST, tracked: Optional[Set[str]] = None) -> Dict[str, int]:
    """Keys read in `fn`: var["k"] subscripts, var.get("k") calls, and
    `x == "k"` comparisons. `tracked` restricts the subscript/get
    receivers to specific local names (frames kind); comparisons are
    always collected (the `k == "ad"` loop-dispatch idiom)."""
    out: Dict[str, int] = {}

    def rec(key: str, line: int) -> None:
        out.setdefault(key, line)

    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) and isinstance(
            node.value, ast.Name
        ):
            if tracked is not None and node.value.id not in tracked:
                continue
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                rec(sl.value, node.lineno)
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr == "get" and node.args:
            base = node.func.value
            if not isinstance(base, ast.Name):
                continue
            if tracked is not None and base.id not in tracked:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                rec(first.value, node.lineno)
        elif tracked is None and isinstance(node, ast.Compare):
            left_is_name = isinstance(node.left, ast.Name)
            for op, comp in zip(node.ops, node.comparators):
                if not isinstance(op, (ast.Eq, ast.NotEq)):
                    continue
                if isinstance(comp, ast.Constant) and isinstance(
                    comp.value, str
                ) and left_is_name:
                    rec(comp.value, node.lineno)
    return out


def _dict_literal_keys(fn: ast.AST) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.setdefault(k.value, k.lineno)
    return out


# -- frames kind -----------------------------------------------------------


def _frames_produced(tree: ast.Module) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(header keys, "t" kinds) from dict literals handed to send-like
    calls anywhere in the module — inline, or assigned to a local name
    one step earlier (`header = {...}; ch.send(header, payload)`)."""
    keys: Dict[str, int] = {}
    kinds: Dict[str, int] = {}

    def record(d: ast.Dict) -> None:
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.setdefault(k.value, k.lineno)
                if (
                    k.value == "t"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    kinds.setdefault(v.value, v.lineno)

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        local_dicts: Dict[str, ast.Dict] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Dict
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local_dicts[t.id] = node.value
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            last = name.rsplit(".", 1)[-1]
            if last not in ("send", "send_frame"):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Dict):
                    record(arg)
                elif isinstance(arg, ast.Name) and arg.id in local_dicts:
                    record(local_dicts[arg.id])
    return keys, kinds


_TRACKED_PARAMS = ("header", "hello", "reply", "frame")


def _frames_consumed(tree: ast.Module) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(header keys, "t" kinds) read from recv_frame-unpacked variables
    and header-named parameters, module-wide."""
    keys: Dict[str, int] = {}
    kinds: Dict[str, int] = {}
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tracked: Set[str] = {
            a.arg for a in fn.args.args if a.arg in _TRACKED_PARAMS
        }
        kind_vars: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                vname = call_name(node.value)
                if vname.endswith("recv_frame"):
                    for t in node.targets:
                        if isinstance(t, ast.Tuple) and t.elts:
                            first = t.elts[0]
                            if isinstance(first, ast.Name):
                                tracked.add(first.id)
                        elif isinstance(t, ast.Name):
                            tracked.add(t.id)
        if not tracked:
            continue
        for k, line in _read_keys(fn, tracked).items():
            keys.setdefault(k, line)
        # kind variables: x = header.get("t")
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "get"
                and isinstance(node.value.func.value, ast.Name)
                and node.value.func.value.id in tracked
                and node.value.args
                and isinstance(node.value.args[0], ast.Constant)
                and node.value.args[0].value == "t"
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        kind_vars.add(t.id)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            left = node.left
            left_kind = (
                isinstance(left, ast.Name) and left.id in kind_vars
            ) or (
                isinstance(left, ast.Call)
                and isinstance(left.func, ast.Attribute)
                and left.func.attr == "get"
                and isinstance(left.func.value, ast.Name)
                and left.func.value.id in tracked
                and left.args
                and isinstance(left.args[0], ast.Constant)
                and left.args[0].value == "t"
            )
            if not left_kind:
                continue
            for comp in node.comparators:
                if isinstance(comp, ast.Constant) and isinstance(
                    comp.value, str
                ):
                    kinds.setdefault(comp.value, node.lineno)
    return keys, kinds


# -- endianness ------------------------------------------------------------


def _endian_sites(
    tree: ast.Module,
) -> Tuple[List[Tuple[str, str, int]], List[Tuple[str, str, int]]]:
    """(writes, reads) as (fmt, normalized, line). Writes are
    struct.pack; reads are struct.unpack / np.dtype("<u4")-style
    strings / ndarray.view. Normalization maps struct chars to numpy
    (order, kind, size) triples so "<I" pairs with "<u4"."""
    writes: List[Tuple[str, str, int]] = []
    reads: List[Tuple[str, str, int]] = []

    def norm_struct(fmt: str) -> List[str]:
        order = fmt[0] if fmt[:1] in "@=<>!" else "@"
        order = {"!": ">"}.get(order, order)
        out = []
        for ch in fmt:
            if ch in _STRUCT_TO_NP and ch in _MULTIBYTE_STRUCT:
                kind, size = _STRUCT_TO_NP[ch]
                out.append(f"{order}{kind}{size}")
        return out

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        last = name.rsplit(".", 1)[-1]
        if last in ("pack", "pack_into", "unpack", "unpack_from", "Struct"):
            if not node.args:
                continue
            fmt = node.args[0]
            if not (
                isinstance(fmt, ast.Constant) and isinstance(fmt.value, str)
                and _STRUCT_FMT_RE.match(fmt.value)
            ):
                continue
            entries = norm_struct(fmt.value)
            target = writes if last.startswith("pack") else reads
            for e in entries:
                target.append((fmt.value, e, node.lineno))
        elif last in ("dtype", "view", "frombuffer"):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    m = _NP_FMT_RE.match(arg.value)
                    if m and int(m.group(3)) > 1:
                        order = m.group(1) or "@"
                        reads.append(
                            (
                                arg.value,
                                f"{order}{m.group(2)}{m.group(3)}",
                                node.lineno,
                            )
                        )
    return writes, reads


class ProtoDriftCheck(Check):
    name = "protodrift"
    description = (
        "producer/consumer key agreement on the hand-rolled wire "
        "formats (x-substratus-load header, disagg frames, PoolSpec "
        "negotiation, gang event broadcast, journey segments) and "
        "explicit-byte-order struct/numpy pairing in the wire modules"
    )

    def __init__(
        self,
        protocols: Sequence[ProtoSpec] = DEFAULT_PROTOCOLS,
        endian_modules: Sequence[str] = DEFAULT_ENDIAN_MODULES,
    ):
        self.protocols = tuple(protocols)
        self.endian_modules = tuple(endian_modules)

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for spec in self.protocols:
            out.extend(self._proto_findings(spec, files))
        out.extend(self._endian_findings(files))
        return out

    def _proto_findings(
        self, spec: ProtoSpec, files: Dict[str, SourceFile]
    ) -> List[Finding]:
        produced: Dict[str, Tuple[str, int]] = {}
        consumed: Dict[str, Tuple[str, int]] = {}
        p_kinds: Dict[str, Tuple[str, int]] = {}
        c_kinds: Dict[str, Tuple[str, int]] = {}
        found_any = False

        for ref in spec.producers:
            hit = _find_fn(files, ref)
            if hit is None:
                continue
            sf, fn = hit
            found_any = True
            if spec.kind == "kvheader":
                src = _kvheader_emitted(fn)
            elif spec.kind == "dict":
                src = _dict_literal_keys(fn)
            else:  # frames: module-wide
                src, kinds = _frames_produced(sf.tree)
                for k, line in kinds.items():
                    p_kinds.setdefault(k, (sf.rel, line))
            for k, line in src.items():
                produced.setdefault(k, (sf.rel, line))

        for ref in spec.consumers:
            hit = _find_fn(files, ref)
            if hit is None:
                continue
            sf, fn = hit
            found_any = True
            if spec.kind == "frames":
                src, kinds = _frames_consumed(sf.tree)
                for k, line in kinds.items():
                    c_kinds.setdefault(k, (sf.rel, line))
            else:
                src = _read_keys(
                    fn, tracked=None
                )
            for k, line in src.items():
                consumed.setdefault(k, (sf.rel, line))

        out: List[Finding] = []
        if not found_any:
            return out  # protocol's modules not in this lint scope
        ignore = set(spec.ignore)
        for k, (rel, line) in sorted(produced.items()):
            if k in consumed or k in ignore:
                continue
            out.append(
                Finding(
                    check="protodrift", path=rel, line=line, col=1,
                    message=(
                        f"protocol {spec.name!r}: key {k!r} is emitted "
                        "by the producer but never parsed by the "
                        "consumer — drift, or dead weight on the wire"
                    ),
                )
            )
        for k, (rel, line) in sorted(consumed.items()):
            if k in produced or k in ignore:
                continue
            out.append(
                Finding(
                    check="protodrift", path=rel, line=line, col=1,
                    message=(
                        f"protocol {spec.name!r}: key {k!r} is parsed "
                        "by the consumer but never emitted by the "
                        "producer — it silently reads its default "
                        "forever"
                    ),
                )
            )
        for k, (rel, line) in sorted(p_kinds.items()):
            if k not in c_kinds and k not in ignore:
                out.append(
                    Finding(
                        check="protodrift", path=rel, line=line, col=1,
                        message=(
                            f"protocol {spec.name!r}: message kind "
                            f"{k!r} is sent but no receiver dispatches "
                            "on it — the peer drops it on the floor"
                        ),
                    )
                )
        for k, (rel, line) in sorted(c_kinds.items()):
            if k not in p_kinds and k not in ignore:
                out.append(
                    Finding(
                        check="protodrift", path=rel, line=line, col=1,
                        message=(
                            f"protocol {spec.name!r}: message kind "
                            f"{k!r} is dispatched on but never sent — "
                            "dead protocol arm, or the sender renamed it"
                        ),
                    )
                )
        return out

    def _endian_findings(
        self, files: Dict[str, SourceFile]
    ) -> List[Finding]:
        out: List[Finding] = []
        all_writes: List[Tuple[str, str, str, int]] = []
        all_reads: List[Tuple[str, str, str, int]] = []
        for rel, sf in sorted(files.items()):
            if sf.tree is None or not any(
                rel.endswith(m) for m in self.endian_modules
            ):
                continue
            writes, reads = _endian_sites(sf.tree)
            for fmt, norm, line in writes + reads:
                if norm.startswith("@") or norm.startswith("="):
                    out.append(
                        Finding(
                            check="protodrift", path=rel, line=line, col=1,
                            message=(
                                f"wire format {fmt!r} has no explicit "
                                "byte order — native order differs "
                                "between hosts (the multihost.py "
                                "big-endian lesson); write '<' or '>'"
                            ),
                        )
                    )
            all_writes.extend((rel, f, n, l) for f, n, l in writes)
            all_reads.extend((rel, f, n, l) for f, n, l in reads)
        read_norms = {n for _, _, n, _ in all_reads}
        write_norms = {n for _, _, n, _ in all_writes}
        for rel, fmt, norm, line in all_writes:
            if norm.startswith(("@", "=")) or norm in read_norms:
                continue
            out.append(
                Finding(
                    check="protodrift", path=rel, line=line, col=1,
                    message=(
                        f"struct.pack format {fmt!r} ({norm}) has no "
                        "matching-endianness read anywhere in the wire "
                        "modules — the other side decodes garbage"
                    ),
                )
            )
        for rel, fmt, norm, line in all_reads:
            if norm.startswith(("@", "=")) or norm in write_norms:
                continue
            out.append(
                Finding(
                    check="protodrift", path=rel, line=line, col=1,
                    message=(
                        f"wire read format {fmt!r} ({norm}) has no "
                        "matching-endianness writer anywhere in the "
                        "wire modules — sender and reader disagree"
                    ),
                )
            )
        return out
