"""Static-analysis subsystem behind `make lint` (driver: hack/sublint.py).

Check families (the names are the suppression keys):

  shard         PartitionSpec / LogicalRules / axis-name literals must
                name axes from the canonical registry
                (parallel/mesh.py MESH_AXES); no axis reuse in one spec
  hostsync      host-device syncs reachable from the engine decode loop
                and trainer step
  concurrency   unlocked cross-thread attribute writes, threads without
                daemon/join, blocking calls in async handlers
  broad-except  except:/except Exception handlers that swallow
  lockorder     interprocedural lock-acquisition graph over the serving
                stack: lock-order cycles, blocking calls while holding
                a lock, bare acquire() without finally-guarded release
  lifecycle     paired-call resource discipline: paged-KV alloc/free,
                adapter-slot pin/unpin, exception-path leaks, and the
                shutdown(SHUT_RDWR)-before-close() socket contract
  protodrift    producer/consumer key agreement on the hand-rolled wire
                formats (load header, disagg frames, PoolSpec hello,
                gang events) + explicit-byte-order struct pairing

Plus two meta families that are never suppressible: "suppression"
(malformed/unused allow[] comments) and "parse" (unparseable files).
The driver also wraps the runtime lints (hack/metrics_lint.py,
hack/trace_lint.py) as registered checks named "metrics" and "trace".

Everything here is import-light on purpose (ast + stdlib only) so the
gate runs without jax or a TPU; hack/sublint.py loads this subpackage
without executing the substratus_tpu package __init__.
"""
from substratus_tpu.analysis.broadexcept import BroadExceptCheck
from substratus_tpu.analysis.concurrency import ConcurrencyCheck
from substratus_tpu.analysis.core import (
    Check,
    Finding,
    SourceFile,
    apply_suppressions,
    assign_fingerprints,
    baseline_fingerprints,
    discover,
    load_files,
    parse_suppressions,
    render_json,
    render_sarif,
    render_text,
    run_checks,
)
from substratus_tpu.analysis.hostsync import HostSyncCheck
from substratus_tpu.analysis.lifecycle import LifecycleCheck
from substratus_tpu.analysis.lockorder import LockOrderCheck
from substratus_tpu.analysis.protodrift import ProtoDriftCheck
from substratus_tpu.analysis.shardlint import ShardCheck

AST_CHECKS = {
    "shard": ShardCheck,
    "hostsync": HostSyncCheck,
    "concurrency": ConcurrencyCheck,
    "broad-except": BroadExceptCheck,
    "lockorder": LockOrderCheck,
    "lifecycle": LifecycleCheck,
    "protodrift": ProtoDriftCheck,
}

__all__ = [
    "AST_CHECKS",
    "BroadExceptCheck",
    "Check",
    "ConcurrencyCheck",
    "Finding",
    "HostSyncCheck",
    "LifecycleCheck",
    "LockOrderCheck",
    "ProtoDriftCheck",
    "ShardCheck",
    "SourceFile",
    "apply_suppressions",
    "assign_fingerprints",
    "baseline_fingerprints",
    "discover",
    "load_files",
    "parse_suppressions",
    "render_json",
    "render_sarif",
    "render_text",
    "run_checks",
]
