"""Shared static-analysis framework behind `make lint` (hack/sublint.py).

Findings, source loading, suppression parsing, and output rendering for
the repo's AST lint families (shard, hostsync, concurrency,
broad-except). Everything here is pure AST work: no jax, no devices, no
imports of the code under analysis — the lint is the repo's first
correctness gate that runs anywhere python does, TPU or not.

Suppression syntax (per line, reason REQUIRED):

    something_flagged()  # sublint: allow[hostsync]: one host read per step

Multiple families on one line: ``allow[hostsync,shard]: reason``. A
suppression without a reason, or one that suppresses nothing, is itself
a finding (family "suppression") and cannot be suppressed — the
suppression inventory stays honest.
"""
from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*sublint:\s*allow\[([A-Za-z0-9_\-, ]+)\]\s*(?::\s*(\S.*))?"
)


@dataclass
class Finding:
    """One lint result. `check` is the family name the suppression syntax
    keys on; `path` is repo-relative."""

    check: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


@dataclass
class SourceFile:
    path: str  # absolute
    rel: str  # repo-relative, forward slashes
    text: str
    tree: Optional[ast.Module]
    error: Optional[str] = None
    lines: List[str] = field(default_factory=list)

    @classmethod
    def load(cls, path: str, rel: str) -> "SourceFile":
        with open(path, encoding="utf-8") as f:
            text = f.read()
        tree, error = None, None
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            error = f"syntax error: {e.msg} (line {e.lineno})"
        return cls(
            path=path, rel=rel, text=text, tree=tree, error=error,
            lines=text.splitlines(),
        )


class Check:
    """Base class: a whole-repo check. Subclasses set `name` (the
    suppression key) and implement run() over the loaded file set."""

    name = ""
    description = ""

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        raise NotImplementedError


def discover(root: str, packages: Sequence[str] = ("substratus_tpu",)) -> List[str]:
    """Repo-relative paths of every .py file under the given packages."""
    rels: List[str] = []
    for pkg in packages:
        base = os.path.join(root, pkg)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    rels.append(os.path.relpath(full, root).replace(os.sep, "/"))
    return sorted(rels)


def load_files(root: str, rels: Iterable[str]) -> Dict[str, SourceFile]:
    return {
        rel: SourceFile.load(os.path.join(root, rel), rel) for rel in rels
    }


def _comment_tokens(sf: SourceFile) -> List[Tuple[int, int, str]]:
    """(line, col, text) of real COMMENT tokens — docstrings that merely
    *mention* the suppression syntax never count as suppressions."""
    try:
        return [
            (tok.start[0], tok.start[1], tok.string)
            for tok in tokenize.generate_tokens(io.StringIO(sf.text).readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Unparseable file: already a "parse" finding; best-effort lines.
        return [
            (i, 0, line)
            for i, line in enumerate(sf.lines, 1)
            if "#" in line
        ]


def parse_suppressions(
    sf: SourceFile,
) -> Tuple[Dict[int, Tuple[set, str]], List[Finding]]:
    """Per-line suppressions: {line: (families, reason)}. Malformed
    suppressions (missing reason) come back as findings."""
    out: Dict[int, Tuple[set, str]] = {}
    problems: List[Finding] = []
    for i, col, comment in _comment_tokens(sf):
        m = SUPPRESS_RE.search(comment)
        if not m:
            continue
        families = {p.strip() for p in m.group(1).split(",") if p.strip()}
        reason = (m.group(2) or "").strip()
        if not reason:
            problems.append(
                Finding(
                    check="suppression", path=sf.rel, line=i,
                    col=col + m.start() + 1,
                    message=(
                        "suppression without a reason: write "
                        "'# sublint: allow[family]: why this is deliberate'"
                    ),
                )
            )
            continue
        out[i] = (families, reason)
    return out, problems


def apply_suppressions(
    files: Dict[str, SourceFile],
    findings: List[Finding],
    ran_families: Optional[set] = None,
) -> List[Finding]:
    """Mark findings suppressed by a same-line allow[]; append findings
    for malformed and unused suppressions. `ran_families` scopes the
    unused-suppression detection: an allow[] for a family that did not
    run this invocation (e.g. `--checks metrics`) is not "unused".
    Returns the full list sorted by location."""
    by_file: Dict[str, Dict[int, Tuple[set, str]]] = {}
    out = list(findings)
    for rel, sf in files.items():
        supp, problems = parse_suppressions(sf)
        by_file[rel] = supp
        out.extend(problems)
    used: Dict[Tuple[str, int], set] = {}
    for f in out:
        supp = by_file.get(f.path, {}).get(f.line)
        if supp and f.check in supp[0] and f.check != "suppression":
            f.suppressed = True
            f.reason = supp[1]
            used.setdefault((f.path, f.line), set()).add(f.check)
    for rel, supp in by_file.items():
        for line, (families, _reason) in supp.items():
            unused = families - used.get((rel, line), set())
            if ran_families is not None:
                unused &= ran_families
            if unused:
                out.append(
                    Finding(
                        check="suppression", path=rel, line=line, col=1,
                        message=(
                            f"unused suppression for {sorted(unused)}: "
                            "nothing was flagged on this line — remove it "
                            "or fix the family name"
                        ),
                    )
                )
    out.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    return out


def run_checks(
    files: Dict[str, SourceFile], checks: Sequence[Check]
) -> List[Finding]:
    """Run the given checks and fold in suppressions. Files that failed
    to parse surface as findings instead of crashing the gate."""
    findings: List[Finding] = []
    for sf in files.values():
        if sf.error is not None:
            findings.append(
                Finding(
                    check="parse", path=sf.rel, line=1, col=1,
                    message=sf.error,
                )
            )
    for check in checks:
        findings.extend(check.run(files))
    return apply_suppressions(
        files, findings, ran_families={c.name for c in checks}
    )


# --- stable finding fingerprints (baseline diff, CI) ----------------------

_DIGITS_RE = re.compile(r"\d+")


def _normalized_message(f: Finding) -> str:
    """Message with every number masked: line numbers embedded in
    concurrency/lockorder messages (call-site lists) must not churn the
    fingerprint when unrelated lines shift."""
    return _DIGITS_RE.sub("#", f.message)


def assign_fingerprints(findings: Sequence[Finding]) -> Dict[int, str]:
    """id(finding) -> stable fingerprint. The fingerprint commits to
    (check, path, digit-masked message, occurrence index among findings
    sharing that key, ordered by location) — NOT to the line number, so
    a finding survives unrelated edits above it, while two identical
    findings in one file stay distinct."""
    by_key: Dict[Tuple[str, str, str], List[Finding]] = {}
    for f in findings:
        by_key.setdefault(
            (f.check, f.path, _normalized_message(f)), []
        ).append(f)
    out: Dict[int, str] = {}
    for (check, path, norm), group in by_key.items():
        group.sort(key=lambda f: (f.line, f.col))
        for idx, f in enumerate(group):
            h = hashlib.sha1(
                f"{check}|{path}|{norm}|{idx}".encode()
            ).hexdigest()[:20]
            out[id(f)] = h
    return out


def baseline_fingerprints(sarif_path: str) -> Tuple[Set[str], int]:
    """(active-finding fingerprints, suppressed count) from a previously
    published SARIF file — the `--baseline` input. Only UNSUPPRESSED
    results enter the fingerprint set: a finding whose in-source
    suppression is deleted must read as NEW, not as baseline-known.
    Results written before the fingerprint era (no partialFingerprints)
    are reconstructed from ruleId + uri + digit-masked message with the
    same occurrence indexing, so an old baseline still diffs correctly."""
    with open(sarif_path, encoding="utf-8") as fh:
        doc = json.load(fh)
    fps: Set[str] = set()
    n_suppressed = 0
    legacy: Dict[Tuple[str, str, str], int] = {}
    for run in doc.get("runs", ()):
        for res in run.get("results", ()):
            if res.get("suppressions"):
                n_suppressed += 1
                continue
            fp = (res.get("partialFingerprints") or {}).get("sublint/v1")
            if fp:
                fps.add(fp)
                continue
            loc = (res.get("locations") or [{}])[0].get(
                "physicalLocation", {}
            )
            uri = loc.get("artifactLocation", {}).get("uri", "")
            norm = _DIGITS_RE.sub(
                "#", res.get("message", {}).get("text", "")
            )
            key = (str(res.get("ruleId", "")), uri, norm)
            idx = legacy.get(key, 0)
            legacy[key] = idx + 1
            fps.add(
                hashlib.sha1(
                    f"{key[0]}|{key[1]}|{key[2]}|{idx}".encode()
                ).hexdigest()[:20]
            )
    return fps, n_suppressed


# --- small AST helpers shared by the check families ----------------------


def call_name(node: ast.Call) -> str:
    """Best-effort dotted name of a call target: `jax.device_get` ->
    "jax.device_get", `x[0].item` -> ".item" (unresolvable base becomes
    a leading dot so suffix checks still work)."""
    parts: List[str] = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    elif parts:
        parts.append("")
    return ".".join(reversed(parts))


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# --- renderers ------------------------------------------------------------


def render_text(findings: Sequence[Finding]) -> str:
    lines = []
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    for f in active:
        lines.append(f"{f.location()}: [{f.check}] {f.message}")
    if suppressed:
        lines.append(
            f"({len(suppressed)} finding(s) suppressed in-source with reasons)"
        )
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        [
            {
                "check": f.check,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "suppressed": f.suppressed,
                "reason": f.reason,
            }
            for f in findings
        ],
        indent=2,
    )


def render_sarif(
    findings: Sequence[Finding], checks: Sequence[Check] = ()
) -> str:
    """SARIF 2.1.0 — one run, one rule per check family; suppressed
    findings carry their in-source justification."""
    rule_ids = sorted(
        {f.check for f in findings} | {c.name for c in checks if c.name}
    )
    fps = assign_fingerprints(findings)
    results = []
    for f in findings:
        result = {
            "ruleId": f.check,
            "level": "error",
            "message": {"text": f.message},
            "partialFingerprints": {"sublint/v1": fps[id(f)]},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {
                            "startLine": f.line, "startColumn": max(f.col, 1)
                        },
                    }
                }
            ],
        }
        if f.suppressed:
            result["suppressions"] = [
                {"kind": "inSource", "justification": f.reason}
            ]
        results.append(result)
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "sublint",
                        "informationUri": (
                            "docs/development.md#static-analysis-sublint"
                        ),
                        "rules": [{"id": rid} for rid in rule_ids],
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)
