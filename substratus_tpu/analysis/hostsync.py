"""hostsync-lint: host-device synchronization points in hot loops.

A single stray `.item()` / `np.asarray` / `jax.device_get` in the
engine's decode loop or the trainer's step serializes the host against
the device and caps achieved MFU (the Podracer / Gemma-on-TPU lesson:
host syncs dominate once the per-step compute is tuned). This check
builds the intra-module call graph from each configured hot-loop root
and flags every statically-recognizable sync reachable from it:

  * ``x.item()``
  * ``jax.device_get(...)``
  * ``jax.block_until_ready(...)`` / ``x.block_until_ready()``
  * ``np.asarray(...)`` / ``numpy.asarray(...)``
  * ``int(f(...))`` / ``float(f(...))`` — a call or attribute result
    coerced to a python scalar (``int(name)`` / ``int(arr[i])`` are
    skipped: in this codebase those read host-side numpy mirrors, and
    flagging them would bury the real syncs in noise)

The deliberate ones — the one host read per decode step that emits
tokens, telemetry flush points — carry
``# sublint: allow[hostsync]: reason`` so every accepted sync is
documented at its site. Reachability is intra-module (self.method and
module-function edges); jitted bodies built outside the loop are
correctly out of scope.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from substratus_tpu.analysis.core import Check, Finding, SourceFile, call_name

DEFAULT_ROOTS: Tuple[Tuple[str, str], ...] = (
    ("serve/engine.py", "Engine._loop"),
    ("train/trainer.py", "Trainer.train_step"),
)

_SYNC_DOTTED = {
    "jax.device_get": "jax.device_get() copies device buffers to host",
    "np.asarray": "np.asarray() on a device array blocks on a transfer",
    "numpy.asarray": "numpy.asarray() on a device array blocks on a transfer",
}


def _index_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """Qualified name -> def node, for module functions and class
    methods (one level: `f` and `Class.method`)."""
    out: Dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[f"{node.name}.{sub.name}"] = sub
    return out


def _callees(
    qual: str, fn: ast.AST, index: Dict[str, ast.AST]
) -> List[str]:
    """Intra-module call edges out of `fn` (including nested defs):
    `self.m(...)` -> same-class method, `g(...)` -> module function."""
    cls = qual.split(".")[0] if "." in qual else None
    out: List[str] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (
            cls is not None
            and isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in ("self", "cls")
        ):
            cand = f"{cls}.{f.attr}"
            if cand in index:
                out.append(cand)
        elif isinstance(f, ast.Name) and f.id in index:
            out.append(f.id)
    return out


def reachable_from(
    tree: ast.Module, root: str
) -> Optional[Dict[str, ast.AST]]:
    """BFS closure of the intra-module call graph from `root`
    ("Class.method" or "function"). None when the root doesn't exist."""
    index = _index_functions(tree)
    if root not in index:
        return None
    seen = {root}
    frontier = [root]
    while frontier:
        cur = frontier.pop()
        for nxt in _callees(cur, index[cur], index):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return {q: index[q] for q in seen}


def _classify_sync(node: ast.Call) -> Optional[str]:
    """A human message when this call is a recognizable host sync."""
    name = call_name(node)
    last = name.rsplit(".", 1)[-1]
    if name in _SYNC_DOTTED:
        return _SYNC_DOTTED[name]
    if last == "item" and "." in name and not node.args:
        return ".item() forces a device->host scalar read"
    if last == "block_until_ready":
        return "block_until_ready() stalls the host on device completion"
    if (
        isinstance(node.func, ast.Name)
        and node.func.id in ("int", "float")
        and len(node.args) == 1
        and isinstance(node.args[0], (ast.Call, ast.Attribute))
    ):
        return (
            f"{node.func.id}() on a computed value forces a device->host "
            "scalar read when the operand is a device array"
        )
    return None


class HostSyncCheck(Check):
    name = "hostsync"
    description = (
        "host-device sync constructs (.item, device_get, np.asarray, "
        "block_until_ready, int/float coercion) reachable from the "
        "engine decode loop and the trainer step"
    )

    def __init__(self, roots: Sequence[Tuple[str, str]] = DEFAULT_ROOTS):
        self.roots = tuple(roots)

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for suffix, root in self.roots:
            sf = next(
                (s for r, s in sorted(files.items()) if r.endswith(suffix)),
                None,
            )
            if sf is None or sf.tree is None:
                continue  # module not in the lint scope (fixture runs)
            reach = reachable_from(sf.tree, root)
            if reach is None:
                out.append(
                    Finding(
                        check="hostsync", path=sf.rel, line=1, col=1,
                        message=(
                            f"hot-loop root {root!r} not found — update "
                            "analysis/hostsync.py DEFAULT_ROOTS after "
                            "renaming the loop"
                        ),
                    )
                )
                continue
            for qual, fn in sorted(reach.items()):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    why = _classify_sync(node)
                    if why is None:
                        continue
                    out.append(
                        Finding(
                            check="hostsync", path=sf.rel,
                            line=node.lineno, col=node.col_offset + 1,
                            message=(
                                f"{why} (in {qual}, reachable from the "
                                f"{root} hot loop)"
                            ),
                        )
                    )
        return out
