"""hostsync-lint: host-device synchronization points in hot loops.

A single stray `.item()` / `np.asarray` / `jax.device_get` in the
engine's decode loop or the trainer's step serializes the host against
the device and caps achieved MFU (the Podracer / Gemma-on-TPU lesson:
host syncs dominate once the per-step compute is tuned). This check
builds the intra-module call graph from each configured hot-loop root
and flags every statically-recognizable sync reachable from it:

  * ``x.item()``
  * ``jax.device_get(...)``
  * ``jax.block_until_ready(...)`` / ``x.block_until_ready()``
  * ``np.asarray(...)`` / ``numpy.asarray(...)``
  * ``int(f(...))`` / ``float(f(...))`` — a call or attribute result
    coerced to a python scalar (``int(name)`` / ``int(arr[i])`` are
    skipped: in this codebase those read host-side numpy mirrors, and
    flagging them would bury the real syncs in noise)

The deliberate ones — the one host read per decode step that emits
tokens, telemetry flush points — carry
``# sublint: allow[hostsync]: reason`` so every accepted sync is
documented at its site. Reachability is intra-module (self.method and
module-function edges); jitted bodies built outside the loop are
correctly out of scope.

Deferred-read idiom (the overlapped scheduler, serve/engine.py): the
engine's decode step is split into ``Engine._dispatch`` (device-only —
capacity growth, on-device token feedback, the jitted launch) and
``Engine._drain`` (the ONE deferred host read plus emits, run while the
next step occupies the device). Speculative rounds use the same split
(``Engine._spec_dispatch`` chains round N+1's inputs off round N's
device-resident verify output through a jitted accept-mask advance;
``Engine._spec_drain`` owns the round's one deferred read and the host
acceptance walk). The allowed host reads therefore live in the drain
halves; any sync reachable from a STALL_ROOTS entry (``_dispatch``,
``_spec_dispatch``) is reported as a *pipeline stall* — it would block
the launch path on device completion and re-serialize the
one-step-ahead pipeline, which is strictly worse than a sync elsewhere
in the loop.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from substratus_tpu.analysis.core import Check, Finding, SourceFile, call_name

DEFAULT_ROOTS: Tuple[Tuple[str, str], ...] = (
    ("serve/engine.py", "Engine._loop"),
    ("train/trainer.py", "Trainer.train_step"),
    # Hot weight-swap (ISSUE 20): the staging half runs on the CALLER's
    # thread but its validation walk touches the live param tree — a
    # stray device read there would stall the caller on the scheduler's
    # in-flight step. (_apply_swap runs inside the loop root above.)
    ("serve/engine.py", "Engine.swap_params"),
)

# Dispatch-side roots of the deferred-read split: a host sync reachable
# from one of these is a pipeline stall (the launch path must stay
# async; the matching drain owns the one deferred read). Checked as
# roots in their own right — the stall report survives even if the
# loop-root edge to the dispatch half is ever refactored away.
STALL_ROOTS: Tuple[Tuple[str, str], ...] = (
    ("serve/engine.py", "Engine._dispatch"),
    ("serve/engine.py", "Engine._spec_dispatch"),
)

# The stall walk stops at explicit pipeline-flush methods: a flush IS a
# deliberate, metered stall (substratus_serve_pipeline_flushes_total),
# and the deferred read it drains through is the hot loop's accepted
# sync — only syncs on the launch path itself re-serialize every step.
STALL_BOUNDARIES: Tuple[str, ...] = ("_flush",)

_SYNC_DOTTED = {
    "jax.device_get": "jax.device_get() copies device buffers to host",
    "np.asarray": "np.asarray() on a device array blocks on a transfer",
    "numpy.asarray": "numpy.asarray() on a device array blocks on a transfer",
}


def _index_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """Qualified name -> def node, for module functions and class
    methods (one level: `f` and `Class.method`)."""
    out: Dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[f"{node.name}.{sub.name}"] = sub
    return out


def _callees(
    qual: str, fn: ast.AST, index: Dict[str, ast.AST]
) -> List[str]:
    """Intra-module call edges out of `fn` (including nested defs):
    `self.m(...)` -> same-class method, `g(...)` -> module function."""
    cls = qual.split(".")[0] if "." in qual else None
    out: List[str] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (
            cls is not None
            and isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in ("self", "cls")
        ):
            cand = f"{cls}.{f.attr}"
            if cand in index:
                out.append(cand)
        elif isinstance(f, ast.Name) and f.id in index:
            out.append(f.id)
    return out


def reachable_from(
    tree: ast.Module, root: str, prune: Sequence[str] = ()
) -> Optional[Dict[str, ast.AST]]:
    """BFS closure of the intra-module call graph from `root`
    ("Class.method" or "function"). None when the root doesn't exist.
    `prune` names methods/functions the walk never enters (boundary
    functions whose bodies are accounted separately)."""
    index = _index_functions(tree)
    if root not in index:
        return None
    seen = {root}
    frontier = [root]
    while frontier:
        cur = frontier.pop()
        for nxt in _callees(cur, index[cur], index):
            if nxt in seen or nxt.rsplit(".", 1)[-1] in prune:
                continue
            seen.add(nxt)
            frontier.append(nxt)
    return {q: index[q] for q in seen}


def _classify_sync(node: ast.Call) -> Optional[str]:
    """A human message when this call is a recognizable host sync."""
    name = call_name(node)
    last = name.rsplit(".", 1)[-1]
    if name in _SYNC_DOTTED:
        return _SYNC_DOTTED[name]
    if last == "item" and "." in name and not node.args:
        return ".item() forces a device->host scalar read"
    if last == "block_until_ready":
        return "block_until_ready() stalls the host on device completion"
    if (
        isinstance(node.func, ast.Name)
        and node.func.id in ("int", "float")
        and len(node.args) == 1
        and isinstance(node.args[0], (ast.Call, ast.Attribute))
    ):
        return (
            f"{node.func.id}() on a computed value forces a device->host "
            "scalar read when the operand is a device array"
        )
    return None


class HostSyncCheck(Check):
    name = "hostsync"
    description = (
        "host-device sync constructs (.item, device_get, np.asarray, "
        "block_until_ready, int/float coercion) reachable from the "
        "engine decode loop and the trainer step"
    )

    def __init__(
        self,
        roots: Sequence[Tuple[str, str]] = DEFAULT_ROOTS,
        stall_roots: Sequence[Tuple[str, str]] = STALL_ROOTS,
    ):
        self.roots = tuple(roots)
        self.stall_roots = tuple(stall_roots)

    @staticmethod
    def _find_sf(files: Dict[str, SourceFile], suffix: str):
        return next(
            (s for r, s in sorted(files.items()) if r.endswith(suffix)),
            None,
        )

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        seen = set()  # (path, line, col) — stall findings take priority

        def emit(sf, node, text):
            key = (sf.rel, node.lineno, node.col_offset + 1)
            if key in seen:
                return
            seen.add(key)
            out.append(
                Finding(
                    check="hostsync", path=sf.rel,
                    line=node.lineno, col=node.col_offset + 1,
                    message=text,
                )
            )

        def walk(sf, root, reach, stall: bool):
            for qual, fn in sorted(reach.items()):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    why = _classify_sync(node)
                    if why is None:
                        continue
                    if stall:
                        emit(
                            sf, node,
                            f"{why} — PIPELINE STALL: reachable from "
                            f"{root}, the device-only dispatch half of "
                            "the overlapped scheduler; the one deferred "
                            "host read belongs in the matching drain() "
                            "(docs/performance.md \"Overlapped "
                            "scheduling\")",
                        )
                    else:
                        emit(
                            sf, node,
                            f"{why} (in {qual}, reachable from the "
                            f"{root} hot loop)",
                        )

        # Stall roots first: a sync inside the dispatch half is the
        # worse defect, so its report wins the per-site dedupe.
        for suffix, root in self.stall_roots:
            sf = self._find_sf(files, suffix)
            if sf is None or sf.tree is None:
                continue  # module not in the lint scope (fixture runs)
            reach = reachable_from(sf.tree, root, prune=STALL_BOUNDARIES)
            if reach is None:
                out.append(
                    Finding(
                        check="hostsync", path=sf.rel, line=1, col=1,
                        message=(
                            f"dispatch root {root!r} not found — update "
                            "analysis/hostsync.py STALL_ROOTS after "
                            "renaming the overlapped scheduler's "
                            "dispatch half"
                        ),
                    )
                )
                continue
            walk(sf, root, reach, stall=True)
        for suffix, root in self.roots:
            sf = self._find_sf(files, suffix)
            if sf is None or sf.tree is None:
                continue  # module not in the lint scope (fixture runs)
            reach = reachable_from(sf.tree, root)
            if reach is None:
                out.append(
                    Finding(
                        check="hostsync", path=sf.rel, line=1, col=1,
                        message=(
                            f"hot-loop root {root!r} not found — update "
                            "analysis/hostsync.py DEFAULT_ROOTS after "
                            "renaming the loop"
                        ),
                    )
                )
                continue
            walk(sf, root, reach, stall=False)
        return out
