"""lockorder-lint: interprocedural lock-acquisition analysis.

PRs 5–7 made the serving stack genuinely concurrent — gateway routing,
the lock-guarded AdapterStore, the disagg KV-handoff threads — and a
single deadlock in a lockstep gang stalls the whole slice. This family
builds a lock-acquisition graph across the configured modules by
resolving lock sites through each module's intra-class/intra-module
call graph, then reports three hazard classes:

  * **lock-order cycle**: lock B is acquired while A is held on one
    path and A while B is held on another (including re-acquiring a
    plain ``threading.Lock`` already held through a callee — a
    guaranteed self-deadlock; ``RLock``-assigned attributes are
    exempt). Edges propagate through calls: ``with self._a: self.m()``
    contributes every acquisition ``m`` makes to ``_a``'s successors.
  * **blocking-while-locked**: a known blocking call — ``recv``/
    ``accept``/``recv_frame``, thread ``.join()``, queue ``.get()``
    / ``Event.wait()`` without a timeout, ``time.sleep``,
    ``socket.create_connection`` — reachable (lexically or through the
    call graph) while a lock is held. A blocked holder starves every
    other thread that needs the lock.
  * **acquire-without-release-path**: a bare ``lock.acquire()`` whose
    matching ``release()`` is not in a ``finally`` (or the acquire is
    not itself the first statement guarded by ``try``) — an exception
    between the two leaks the lock forever. Prefer ``with``.

Lock identity is ``module.py:Class.attr`` (or ``module.py:name`` for
module-level locks); an attribute counts as a lock when its identifier
contains ``lock``/``mutex``/``cond``. Calls into the shared metrics
registry (``METRICS.*``) are modeled as acquiring the registry lock —
the one deliberate cross-module edge every instrumented module shares.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from substratus_tpu.analysis.core import Check, Finding, SourceFile, call_name

# Modules whose lock discipline is load-bearing (suffix match).
DEFAULT_LOCK_MODULES: Tuple[str, ...] = (
    "serve/engine.py",
    "serve/server.py",
    "serve/adapters.py",
    "serve/disagg.py",
    "serve/multihost.py",
    "gateway/",
    "observability/",
)

_LOCKISH = ("lock", "mutex", "cond")

# Calls that are known to acquire a lock living in another module; the
# metrics registry is the edge every instrumented module shares.
EXTERNAL_LOCKS: Dict[str, str] = {
    "METRICS.": "observability/metrics.py:Metrics._lock",
}

# Blocking calls by dotted-name suffix. `.join`/`.get`/`.wait` need the
# receiver filters below to stay precise (str.join, dict.get, ...).
_BLOCKING_SUFFIX = {
    ".recv": "socket recv blocks until the peer writes",
    ".recv_into": "socket recv blocks until the peer writes",
    ".accept": "accept blocks until a client connects",
    ".sendall": None,  # noisy; covered by frame-send discipline docs
}
_BLOCKING_EXACT = {
    "time.sleep": "time.sleep parks the holder",
    "socket.create_connection": "connect blocks for the full timeout",
    "recv_exact": "recv_exact blocks until the peer writes",
    "recv_frame": "recv_frame blocks until the peer writes",
    "select.select": "select blocks until a descriptor is ready",
}


def _lock_ident(expr: ast.AST) -> Optional[str]:
    """The lock-ish identifier a with-item / call receiver names, or
    None. `self._lock` -> "_lock", `REGISTRY_LOCK` -> "REGISTRY_LOCK"."""
    node = expr
    # Unwrap .acquire()/.release() attribute to the receiver.
    if isinstance(node, ast.Attribute) and node.attr in ("acquire", "release"):
        node = node.value
    ident = None
    if isinstance(node, ast.Attribute):
        ident = node.attr
    elif isinstance(node, ast.Name):
        ident = node.id
    if ident and any(k in ident.lower() for k in _LOCKISH):
        return ident
    return None


def _is_blocking(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name in _BLOCKING_EXACT:
        return _BLOCKING_EXACT[name]
    last = name.rsplit(".", 1)[-1]
    has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
    recv_ident = ""
    if isinstance(node.func, ast.Attribute):
        base = node.func.value
        if isinstance(base, ast.Constant):
            return None  # "sep".join(...) and friends
        if isinstance(base, ast.Attribute):
            recv_ident = base.attr
        elif isinstance(base, ast.Name):
            recv_ident = base.id
    for suffix, why in _BLOCKING_SUFFIX.items():
        if why and ("." + last) == suffix:
            return why
    if last == "join" and not node.args and not name.startswith("os.path"):
        # Thread/process join: receiver looks like a thread handle.
        if any(
            k in recv_ident.lower()
            for k in ("thread", "worker", "sender", "proc", "_t")
        ) or recv_ident in ("t", "th"):
            return "join blocks until the thread exits"
    if last == "get" and not has_timeout and not node.args:
        # queue.Queue.get() without timeout (dict.get always has args).
        if "queue" in recv_ident.lower() or recv_ident in ("q",):
            return "Queue.get() without timeout blocks indefinitely"
    if last == "wait" and not has_timeout and not node.args:
        if any(
            k in recv_ident.lower()
            for k in ("event", "cond", "stop", "ready", "done")
        ):
            return "wait() without timeout blocks indefinitely"
    return None


def _index_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[f"{node.name}.{sub.name}"] = sub
    return out


def _rlock_attrs(tree: ast.Module) -> Set[str]:
    """Attribute/name identifiers assigned from threading.RLock() —
    re-acquiring those while held is legal."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if call_name(node.value).endswith("RLock"):
                for t in node.targets:
                    ident = _lock_ident(t)
                    if ident:
                        out.add(ident)
    return out


class _FnSummary:
    """Per-function facts: lock events and call edges, each with the
    set of locks lexically held at that point."""

    def __init__(self) -> None:
        # (lockid, node, held-at-site)
        self.acquires: List[Tuple[str, ast.AST, frozenset]] = []
        # (callee qualname, held-at-site)
        self.calls: List[Tuple[str, frozenset]] = []
        # (message, node, held-at-site)
        self.blocking: List[Tuple[str, ast.AST, frozenset]] = []
        # external lock ids touched, with held-at-site
        self.external: List[Tuple[str, ast.AST, frozenset]] = []
        # bare .acquire() without try/finally release (node, lockid)
        self.bare_acquires: List[Tuple[ast.AST, str]] = []


def _summarize(
    rel: str, qual: str, fn: ast.AST, index: Dict[str, ast.AST],
    rlocks: Set[str],
) -> _FnSummary:
    cls = qual.split(".")[0] if "." in qual else None
    out = _FnSummary()

    def lock_id(ident: str) -> str:
        scope = cls if cls else ""
        return f"{rel}:{scope + '.' if scope else ''}{ident}"

    def visit(node: ast.AST, held: frozenset) -> None:
        if (
            node is not fn
            and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
        ):
            return  # nested defs run on their own schedule/thread
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                visit(item.context_expr, held)
                ident = _lock_ident(item.context_expr)
                if ident:
                    lid = lock_id(ident)
                    out.acquires.append((lid, node, held))
                    inner = inner | {lid}
            for sub in node.body:
                visit(sub, inner)
            return
        if isinstance(node, ast.Call):
            name = call_name(node)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
            ):
                ident = _lock_ident(node.func)
                if ident:
                    out.acquires.append((lock_id(ident), node, held))
                    out.bare_acquires.append((node, ident))
            for prefix, lid in EXTERNAL_LOCKS.items():
                if name.startswith(prefix):
                    out.external.append((lid, node, held))
            why = _is_blocking(node)
            if why:
                out.blocking.append((why, node, held))
            f = node.func
            if (
                cls is not None
                and isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id in ("self", "cls")
                and f"{cls}.{f.attr}" in index
            ):
                out.calls.append((f"{cls}.{f.attr}", held))
            elif isinstance(f, ast.Name) and f.id in index:
                out.calls.append((f.id, held))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    out.rlock_ids = {lock_id(i) for i in rlocks}  # type: ignore[attr-defined]
    visit(fn, frozenset())
    return out


class LockOrderCheck(Check):
    name = "lockorder"
    description = (
        "interprocedural lock analysis over the serving/gateway/"
        "observability modules: lock-order cycles, blocking calls while "
        "holding a lock, bare acquire() without a finally-guarded release"
    )

    def __init__(self, modules: Sequence[str] = DEFAULT_LOCK_MODULES):
        self.modules = tuple(modules)

    def _in_scope(self, rel: str) -> bool:
        return any(m in rel for m in self.modules)

    @staticmethod
    def _canon(lid: str) -> str:
        """Unify in-module lock ids (full repo-relative path) with the
        EXTERNAL_LOCKS suffix form, so the metrics registry acquired
        from inside metrics.py and via METRICS.* is ONE graph node."""
        for ext in EXTERNAL_LOCKS.values():
            if lid.endswith(ext):
                return ext
        return lid

    def run(self, files: Dict[str, SourceFile]) -> List[Finding]:
        findings: List[Finding] = []
        # lock graph: edge (held -> acquired) with one witness site
        edges: Dict[Tuple[str, str], Tuple[str, int, int]] = {}
        rlock_ids: Set[str] = set()

        for rel, sf in sorted(files.items()):
            if sf.tree is None or not self._in_scope(rel):
                continue
            index = _index_functions(sf.tree)
            rlocks = _rlock_attrs(sf.tree)
            summaries = {
                qual: _summarize(rel, qual, fn, index, rlocks)
                for qual, fn in index.items()
            }
            for s in summaries.values():
                rlock_ids |= {
                    self._canon(x) for x in getattr(s, "rlock_ids", set())
                }

            # Interprocedural propagation: visit (fn, inherited-held).
            seen: Set[Tuple[str, frozenset]] = set()
            work: List[Tuple[str, frozenset]] = [
                (q, frozenset()) for q in summaries
            ]
            while work:
                qual, inherited = work.pop()
                if (qual, inherited) in seen:
                    continue
                seen.add((qual, inherited))
                s = summaries[qual]
                for lid, node, held in s.acquires:
                    lid = self._canon(lid)
                    for h in held | inherited:
                        h = self._canon(h)
                        if h == lid and lid in rlock_ids:
                            continue
                        edges.setdefault(
                            (h, lid),
                            (rel, node.lineno, node.col_offset + 1),
                        )
                for lid, node, held in s.external:
                    for h in held | inherited:
                        edges.setdefault(
                            (self._canon(h), lid),
                            (rel, node.lineno, node.col_offset + 1),
                        )
                for why, node, held in s.blocking:
                    all_held = held | inherited
                    if all_held:
                        findings.append(
                            Finding(
                                check="lockorder", path=rel,
                                line=node.lineno, col=node.col_offset + 1,
                                message=(
                                    f"{why} while holding "
                                    f"{sorted(all_held)} (in {qual}) — "
                                    "every thread needing the lock stalls "
                                    "behind this call; move it outside "
                                    "the critical section"
                                ),
                            )
                        )
                for node, ident in s.bare_acquires:
                    if not _released_in_finally(
                        index[qual], node, ident
                    ):
                        findings.append(
                            Finding(
                                check="lockorder", path=rel,
                                line=node.lineno, col=node.col_offset + 1,
                                message=(
                                    f"{ident}.acquire() without a "
                                    "finally-guarded release — an "
                                    "exception on this path leaks the "
                                    "lock forever; use `with` or "
                                    "try/finally"
                                ),
                            )
                        )
                for callee, held in s.calls:
                    work.append((callee, held | inherited))

        findings.extend(_cycle_findings(edges, rlock_ids))
        return findings


def _released_in_finally(fn: ast.AST, acquire: ast.Call, ident: str) -> bool:
    """True when the acquire's release is exception-safe: the acquire
    sits immediately before (or as the first statement of) a try whose
    finally releases the same lock identifier."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        releases = any(
            isinstance(c, ast.Call)
            and isinstance(c.func, ast.Attribute)
            and c.func.attr == "release"
            and _lock_ident(c.func) == ident
            for f in node.finalbody
            for c in ast.walk(f)
        )
        if not releases:
            continue
        # acquire just before the try, or the try's first statement
        if acquire.lineno <= node.lineno:
            return True
        first = node.body[0] if node.body else None
        if first is not None and acquire.lineno <= first.lineno:
            return True
    return False


def _cycle_findings(
    edges: Dict[Tuple[str, str], Tuple[str, int, int]],
    rlock_ids: Set[str],
) -> List[Finding]:
    out: List[Finding] = []
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    # Self-deadlock: plain lock re-acquired while held.
    for (a, b), (rel, line, col) in sorted(edges.items()):
        if a == b and a not in rlock_ids:
            out.append(
                Finding(
                    check="lockorder", path=rel, line=line, col=col,
                    message=(
                        f"lock {a} re-acquired while already held "
                        "(through the call graph) — threading.Lock is "
                        "not re-entrant; this deadlocks the holder"
                    ),
                )
            )

    # Simple cycle detection via DFS over distinct nodes; report each
    # 2+-node cycle once, anchored at its lexically-first edge site.
    reported: Set[frozenset] = set()

    def reachable(src: str, dst: str) -> bool:
        seen, stack = {src}, [src]
        while stack:
            cur = stack.pop()
            for nxt in graph.get(cur, ()):
                if nxt == dst:
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return False

    for (a, b), (rel, line, col) in sorted(edges.items()):
        if a == b:
            continue
        key = frozenset((a, b))
        if key in reported:
            continue
        if reachable(b, a):
            reported.add(key)
            out.append(
                Finding(
                    check="lockorder", path=rel, line=line, col=col,
                    message=(
                        f"lock-order cycle: {a} is held while acquiring "
                        f"{b} here, and {b} is (transitively) held while "
                        f"acquiring {a} elsewhere — two threads taking "
                        "the two orders deadlock; pick one global order"
                    ),
                )
            )
    return out
