"""The closed loop: actors generate, learner updates, weights hot-swap
back — and the engines never restart.

Topology (docs/rl.md): the batchgen driver (serve/batchgen.py) drives
the actor engines through a per-round prompt manifest exactly as an
offline run would — continuous refill, sharded exactly-once output —
and its ``record_hook`` tees every completed record into the episode
buffer, scored by the caller's ``reward_fn``. When the round's
manifest drains, the learner does a pass over the episodes
(reward-weighted loss, rl/learner.py) and the refreshed params flow to
every actor through ``Engine.swap_params`` — a pipeline settle + an
in-place tree replace, compiled programs kept. Round N+1 generates
with round N's policy on the SAME live engines.

Failure semantics: an engine death aborts the round loudly
(BatchGenDriver.run raises); a swap rejection (shape drift — cannot
happen when the learner was seeded from the actors' checkpoint) raises
out of the loop before any actor takes a partial update; a dry round
(zero ok records) skips the learn + swap and counts as no progress.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.rl.buffer import Episode, ReplayBuffer
from substratus_tpu.rl.learner import RLLearner
from substratus_tpu.serve.batchgen import BatchGenDriver

log = logging.getLogger(__name__)

METRICS.describe(
    "substratus_rl_rounds_total",
    "Completed actor->learner->actor RL rounds.",
    type="counter",
)
METRICS.describe(
    "substratus_rl_mean_reward",
    "Mean episode reward of the most recent RL round.",
    type="gauge",
)

# reward_fn(output_record, prompt_tokens) -> float. The record is the
# batchgen output line (tokens/finish_reason/text when a tokenizer is
# attached); prompt ids ride alongside because the record only stores
# their count.
RewardFn = Callable[[Dict[str, Any], List[int]], float]


class RLLoop:
    """Drives N actor->learner->actor rounds over live engines.

    ``prompts`` are token-id lists (the manifest's ``tokens`` form — no
    tokenizer needed on the hot path; pass ``tokenizer`` only if the
    reward function wants decoded text on the records).
    """

    def __init__(
        self,
        engines: Sequence[Any],
        learner: RLLearner,
        prompts: Sequence[List[int]],
        reward_fn: RewardFn,
        out_dir: str,
        *,
        max_tokens: int = 32,
        temperature: float = 1.0,
        top_p: float = 1.0,
        tokenizer=None,
    ):
        if not engines:
            raise ValueError("the RL loop needs at least one actor engine")
        if not prompts:
            raise ValueError("the RL loop needs at least one prompt")
        self.engines = list(engines)
        self.learner = learner
        self.prompts = [list(p) for p in prompts]
        self.reward_fn = reward_fn
        self.out_dir = out_dir
        self.max_tokens = int(max_tokens)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.tokenizer = tokenizer
        self.rounds_done = 0
        self.history: List[Dict[str, Any]] = []
        # Weight generations the loop has pushed; engines report it as
        # weights_version after each swap (round r -> version base+r).
        self._version = max(
            int(getattr(e, "weights_version", 0)) for e in self.engines
        )

    def _write_manifest(self, rnd: int, round_dir: str) -> str:
        path = os.path.join(round_dir, "manifest.jsonl")
        with open(path, "w") as f:
            for i, toks in enumerate(self.prompts):
                f.write(json.dumps({"id": f"r{rnd}-{i}", "tokens": toks}))
                f.write("\n")
        return path

    def run_round(self, rnd: Optional[int] = None) -> Dict[str, Any]:
        """One actor->learner->actor round. Returns the round report:
        {round, episodes, mean_reward, losses, weights_version, gen}."""
        rnd = self.rounds_done if rnd is None else int(rnd)
        round_dir = os.path.join(self.out_dir, f"round{rnd:03d}")
        os.makedirs(round_dir, exist_ok=True)
        manifest = self._write_manifest(rnd, round_dir)
        buffer = ReplayBuffer(capacity=max(len(self.prompts), 1))

        def hook(record: Dict[str, Any], prompt_tokens: List[int]) -> None:
            buffer.add(
                Episode(
                    prompt_tokens=prompt_tokens,
                    completion_tokens=list(record.get("tokens") or []),
                    reward=float(self.reward_fn(record, prompt_tokens)),
                    meta={"id": record.get("id"), "round": rnd},
                )
            )

        driver = BatchGenDriver(
            self.engines,
            manifest,
            os.path.join(round_dir, "out"),
            tokenizer=self.tokenizer,
            max_tokens=self.max_tokens,
            temperature=self.temperature,
            top_p=self.top_p,
            record_hook=hook,
        )
        gen = driver.run()
        episodes = buffer.drain()
        mean_reward = (
            sum(ep.reward for ep in episodes) / len(episodes)
            if episodes else 0.0
        )
        METRICS.set("substratus_rl_mean_reward", mean_reward)
        losses = self.learner.learn(episodes)
        version = self._version
        if losses:
            # Ship the refreshed policy to every live actor. The
            # explicit version keeps a multi-actor fleet on ONE
            # generation per round (None would let each engine
            # self-increment from wherever it started).
            version = self._version + 1
            params = self.learner.snapshot_params()
            for e in self.engines:
                e.swap_params(params, version=version)
            self._version = version
        report = {
            "round": rnd,
            "episodes": len(episodes),
            "mean_reward": round(mean_reward, 6),
            "losses": losses,
            "weights_version": version,
            "gen": gen,
        }
        self.rounds_done += 1
        self.history.append(report)
        METRICS.inc("substratus_rl_rounds_total")
        log.info(
            "rl round %d: %d episodes, mean reward %.4f, "
            "%d updates, weights_version=%d",
            rnd, len(episodes), mean_reward, len(losses), version,
        )
        return report

    def run(self, rounds: int) -> List[Dict[str, Any]]:
        return [self.run_round() for _ in range(int(rounds))]
