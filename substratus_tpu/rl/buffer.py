"""Episode buffer + batch assembly for the RL actor-learner loop.

The actors' sink thread deposits scored episodes here (through the
batchgen ``record_hook``), and the learner drains them into fixed-shape
``{"tokens", "weights"}`` batches — the exact contract
``Trainer.train_step`` already speaks, with the per-token weights array
carrying the reward weighting:

  * prompt positions and padding get weight 0 (the learner never trains
    on the prompt it was given);
  * completion positions get the episode's normalized reward weight —
    rewards are shifted positive (min-shift + eps) and scaled to mean
    1.0 across the batch, so the loss magnitude stays comparable to
    plain supervised training and a uniform-reward batch degenerates to
    ordinary cross-entropy (v1 reward-weighted regression; docs/rl.md
    "Loss").

Fixed [B, S] shapes per loop mean the learner's jitted step compiles
once, the same economics the serving engine gets from bucketing.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List

import numpy as np


@dataclass
class Episode:
    """One generated completion with its scalar reward."""

    prompt_tokens: List[int]
    completion_tokens: List[int]
    reward: float
    meta: Dict = field(default_factory=dict)


class ReplayBuffer:
    """Thread-safe episode accumulator.

    ``add`` is called from the batchgen sink thread while the learner
    thread may be draining — a lock (never held across I/O) covers the
    list swap. v1 is on-policy: ``drain`` hands over everything and
    empties the buffer; there is no cross-round replay.
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._episodes: List[Episode] = []
        self.dropped = 0

    def add(self, episode: Episode) -> None:
        with self._lock:
            if len(self._episodes) >= self.capacity:
                # Newest-wins under overflow: stale on-policy episodes
                # are the least valuable thing in the building.
                self._episodes.pop(0)
                self.dropped += 1
            self._episodes.append(episode)

    def __len__(self) -> int:
        with self._lock:
            return len(self._episodes)

    def drain(self) -> List[Episode]:
        with self._lock:
            out, self._episodes = self._episodes, []
            return out


def reward_weights(episodes: List[Episode]) -> List[float]:
    """Per-episode loss weights from raw rewards: shift positive
    (min-shift + eps so the worst episode still contributes a little
    signal), normalize to mean 1.0. All-equal rewards -> uniform 1.0
    (plain cross-entropy)."""
    rewards = [float(ep.reward) for ep in episodes]
    if not rewards:
        return []
    lo, hi = min(rewards), max(rewards)
    if hi - lo < 1e-9:
        return [1.0] * len(rewards)
    eps = 0.05 * (hi - lo)
    shifted = [r - lo + eps for r in rewards]
    mean = sum(shifted) / len(shifted)
    return [s / mean for s in shifted]


def episodes_to_batches(
    episodes: List[Episode],
    batch_size: int,
    seq_len: int,
    pad_id: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Episodes -> fixed-shape Trainer batches.

    Every batch is exactly [batch_size, seq_len]: long episodes truncate,
    the final ragged batch pads with zero-weight filler rows (repeating
    the last episode's tokens with weight 0 keeps shapes fixed without
    teaching the model anything). Yields nothing for an empty drain.
    """
    if batch_size < 1 or seq_len < 2:
        raise ValueError("batch_size >= 1 and seq_len >= 2 required")
    if not episodes:
        return
    weights = reward_weights(episodes)
    rows = []
    for ep, w in zip(episodes, weights):
        toks = (list(ep.prompt_tokens) + list(ep.completion_tokens))[:seq_len]
        row_t = np.full((seq_len,), pad_id, np.int32)
        row_t[: len(toks)] = np.asarray(toks, np.int32)
        row_w = np.zeros((seq_len,), np.float32)
        # Weight the COMPLETION positions only (the loss reads
        # weights[:, 1:] against targets tokens[:, 1:], so position j
        # weights the prediction OF token j).
        start = min(len(ep.prompt_tokens), seq_len)
        end = min(len(toks), seq_len)
        row_w[start:end] = w
        rows.append((row_t, row_w))
    while len(rows) % batch_size:
        filler_t, _ = rows[-1]
        rows.append((filler_t.copy(), np.zeros((seq_len,), np.float32)))
    for i in range(0, len(rows), batch_size):
        chunk = rows[i : i + batch_size]
        yield {
            "tokens": np.stack([t for t, _ in chunk]),
            "weights": np.stack([w for _, w in chunk]),
        }
