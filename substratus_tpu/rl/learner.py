"""The learner half of the RL closed loop: Trainer + episode batches.

A thin composition, on purpose — everything hard (sharded params,
donation, grad accumulation, the reward-carrying ``weights`` array in
the loss) already lives in ``train/trainer.py``; the learner only
assembles episode batches (rl/buffer.py) and keeps the loss history a
smoke test can assert on. Full-finetune only in v1: ``swap_params``
ships whole param trees to the actors, and shipping a LoRA delta
instead is the actors' adapter plane's job (serve/adapters.py), not a
second weight path.
"""
from __future__ import annotations

import logging
from typing import List, Optional

from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.rl.buffer import Episode, episodes_to_batches
from substratus_tpu.train.trainer import TrainConfig, Trainer

log = logging.getLogger(__name__)

METRICS.describe(
    "substratus_rl_learner_updates_total",
    "Optimizer updates applied by the RL learner.",
    type="counter",
)
METRICS.describe(
    "substratus_rl_episodes_total",
    "Episodes consumed by the RL learner.",
    type="counter",
)
METRICS.describe(
    "substratus_rl_learner_loss",
    "Reward-weighted loss of the learner's most recent update.",
    type="gauge",
)


class RLLearner:
    """Consumes episode drains, returns per-update losses.

    ``seq_len`` fixes the batch shape (one compile); pick it to cover
    prompt + max_tokens of the actor run. ``params`` seeds the learner
    from the ACTORS' boot checkpoint so round 0's policy gradient is
    computed against the weights that generated the episodes.
    """

    def __init__(
        self,
        cfg,
        tc: TrainConfig,
        mesh,
        params=None,
        model=None,
        batch_size: int = 8,
        seq_len: int = 128,
        pad_id: int = 0,
    ):
        if tc.lora_rank > 0:
            raise ValueError(
                "the RL learner is full-finetune only (lora_rank=0): "
                "swap_params ships full param trees to the actors"
            )
        self.trainer = Trainer(cfg, tc, mesh, params=params, model=model)
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.pad_id = int(pad_id)
        self.losses: List[float] = []

    def learn(self, episodes: List[Episode]) -> List[float]:
        """One pass over a drain of episodes; returns that pass's
        losses (empty for an empty drain — the loop treats a dry round
        as 'nothing to learn', not an error)."""
        out: List[float] = []
        for batch in episodes_to_batches(
            episodes, self.batch_size, self.seq_len, pad_id=self.pad_id
        ):
            loss = self.trainer.train_step(batch)
            out.append(loss)
            METRICS.inc("substratus_rl_learner_updates_total")
            METRICS.set("substratus_rl_learner_loss", loss)
        if episodes:
            METRICS.inc("substratus_rl_episodes_total", by=len(episodes))
        self.losses.extend(out)
        if out:
            log.info(
                "rl learner: %d episodes -> %d updates, loss %.4f -> %.4f",
                len(episodes), len(out), out[0], out[-1],
            )
        return out

    def snapshot_params(self):
        """Donation-safe copy of the current policy weights — the ONLY
        object the loop may hand to Engine.swap_params (the live tree's
        buffers are donated to the next train_step)."""
        return self.trainer.snapshot_params()

    @property
    def step(self) -> int:
        return self.trainer.step
