"""RL actor-learner closed loop (docs/rl.md).

Batchgen actor engines generate episodes into the sink; a learner built
on train/'s Trainer consumes them as a streaming dataset with a
reward-weighted loss; refreshed params flow back to the live actors
through Engine.swap_params — no engine teardown, no recompile
(the Podracer / Sebulba topology on this codebase's existing pieces).
"""
from substratus_tpu.rl.buffer import Episode, ReplayBuffer, episodes_to_batches
from substratus_tpu.rl.learner import RLLearner
from substratus_tpu.rl.loop import RLLoop

__all__ = [
    "Episode",
    "ReplayBuffer",
    "episodes_to_batches",
    "RLLearner",
    "RLLoop",
]
