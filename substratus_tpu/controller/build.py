"""Shared image-build reconciler for all four CR kinds (reference:
internal/controller/build_reconciler.go:31-574).

Flow parity:
  * skip unless the CR has spec.build and spec.image != the deterministic
    built-image URL (build_reconciler.go:67-72);
  * upload builds: signed-URL handshake — controller publishes a signed PUT
    URL for the client's {md5, requestID} in status.buildUpload, waits until
    storage MD5 matches, then builds (183-268);
  * git builds: clone-and-build Job (270-403);
  * the build Job is annotated with its target image and recreated when the
    target changes (117-136);
  * on success: spec.image <- built URL, condition Built=True (157-171).

The builder pod runs kaniko exactly like the reference — image building is
cloud machinery, not accelerator work, so the same tool is the right call.
"""
from __future__ import annotations

from typing import Optional

from substratus_tpu.api import conditions as C
from substratus_tpu.cloud.base import Cloud
from substratus_tpu.observability.events import EVENTS
from substratus_tpu.controller.common import (
    SA_CONTAINER_BUILDER,
    job_state,
    reconcile_child,
    reconcile_service_account,
    set_condition,
    write_status,
)
from substratus_tpu.controller.runtime import Result
from substratus_tpu.controller.workloads import owner_reference
from substratus_tpu.kube.client import KubeClient, NotFound, Obj
from substratus_tpu.resources.apply import builder_resources
from substratus_tpu.sci.client import SCIClient

KANIKO_IMAGE = "gcr.io/kaniko-project/executor:latest"
GIT_IMAGE = "alpine/git:latest"
UPLOAD_OBJECT_PREFIX = "uploads"


class BuildReconciler:
    def __init__(self, client: KubeClient, cloud: Cloud, sci: SCIClient):
        self.client = client
        self.cloud = cloud
        self.sci = sci

    def __call__(self, obj: Obj) -> Result:
        spec = obj.get("spec") or {}
        build = spec.get("build")
        if not build:
            return Result()
        md0 = obj["metadata"]
        git = build.get("git") or {}
        if git.get("tag") and git.get("branch"):
            # Tag OR branch, never both (reference common_types.go:32-47)
            # — cloning one silently while the user believes the other
            # was built is the worst outcome, so reject loudly.
            set_condition(
                obj, C.CONDITION_BUILT, False, C.REASON_INVALID_SPEC,
                "build.git: set tag OR branch, not both",
            )
            EVENTS.emit(
                "InvalidSpec", kind=obj["kind"],
                namespace=md0["namespace"], name=md0["name"],
                message="build.git: set tag OR branch, not both",
                type="Warning",
            )
            write_status(self.client, obj)
            return Result()

        class _Ref:
            KIND = obj["kind"]
            name = obj["metadata"]["name"]
            namespace = obj["metadata"]["namespace"]

        target_image = self.cloud.object_built_image_url(_Ref)
        if spec.get("image") == target_image:
            return Result()  # already built

        md = obj["metadata"]
        ns = md["namespace"]

        if build.get("upload"):
            result = self._reconcile_upload(obj, build["upload"], target_image)
            if result is not None:
                return result

        reconcile_service_account(
            self.client, self.cloud, self.sci, ns, SA_CONTAINER_BUILDER
        )

        job_name = f"{md['name']}-{obj['kind'].lower()}-bld"
        desired = self._build_job(obj, build, job_name, target_image)
        try:
            job = self.client.get("Job", ns, job_name)
            if (
                job["metadata"].get("annotations", {}).get("image")
                != target_image
            ):
                # Target moved (e.g. new upload): recreate (ref :117-136).
                self.client.delete("Job", ns, job_name)
                job = self.client.create(desired)
                EVENTS.emit(
                    "BuildJobRecreated", kind=obj["kind"], namespace=ns,
                    name=md["name"],
                    message=f"target image moved; recreated job {job_name}",
                )
        except NotFound:
            job = self.client.create(desired)
            EVENTS.emit(
                "BuildJobCreated", kind=obj["kind"], namespace=ns,
                name=md["name"], message=f"created build job {job_name}",
            )

        state = job_state(job)
        if state == "complete":
            set_condition(
                obj, C.CONDITION_BUILT, True, C.REASON_BUILD_JOB_COMPLETE
            )
            EVENTS.emit(
                "BuildComplete", kind=obj["kind"], namespace=ns,
                name=md["name"], message=f"image built: {target_image}",
            )
            write_status(self.client, obj)
            fresh = self.client.get(obj["kind"], ns, md["name"])
            fresh["spec"]["image"] = target_image
            self.client.update(fresh)
            obj["spec"]["image"] = target_image
        elif state == "failed":
            set_condition(
                obj, C.CONDITION_BUILT, False, C.REASON_JOB_FAILED,
                f"build job {job_name} failed",
            )
            EVENTS.emit(
                "BuildFailed", kind=obj["kind"], namespace=ns,
                name=md["name"], message=f"build job {job_name} failed",
                type="Warning",
            )
            write_status(self.client, obj)
        else:
            set_condition(
                obj, C.CONDITION_BUILT, False, C.REASON_BUILD_JOB_RUNNING
            )
            write_status(self.client, obj)
        return Result()

    # -- upload handshake --------------------------------------------------

    def _upload_object_path(self, obj: Obj, md5: str) -> str:
        md = obj["metadata"]
        return (
            f"{UPLOAD_OBJECT_PREFIX}/{md['namespace']}/"
            f"{obj['kind'].lower()}s/{md['name']}/{md5}.tar.gz"
        )

    def _reconcile_upload(
        self, obj: Obj, upload: dict, target_image: str
    ) -> Optional[Result]:
        """Returns None when the upload is verified (build may proceed)."""
        md5 = upload.get("md5Checksum", "")
        request_id = upload.get("requestId", "")
        status_upload = obj.setdefault("status", {}).setdefault(
            "buildUpload", {}
        )
        object_path = self._upload_object_path(obj, md5)

        md = obj["metadata"]
        stored = self.sci.get_object_md5(
            self.cloud.cfg.artifact_bucket_url, object_path
        )
        if stored == md5:
            set_condition(
                obj, C.CONDITION_UPLOADED, True, C.REASON_UPLOAD_FOUND
            )
            EVENTS.emit(
                "UploadReceived", kind=obj["kind"],
                namespace=md["namespace"], name=md["name"],
                message=f"build context present (md5 {md5})",
            )
            status_upload["storedMd5Checksum"] = stored
            write_status(self.client, obj)
            return None

        if status_upload.get("requestId") != request_id or not status_upload.get(
            "signedUrl"
        ):
            signed = self.sci.create_signed_url(
                self.cloud.cfg.artifact_bucket_url, object_path, md5
            )
            status_upload.update(
                {"signedUrl": signed.url, "requestId": request_id}
            )
        set_condition(
            obj, C.CONDITION_UPLOADED, False, C.REASON_AWAITING_UPLOAD
        )
        # Count-deduped: the 10 s poll below re-emits this every pass and
        # the recorder folds them into one entry with a rising count.
        EVENTS.emit(
            "AwaitingUpload", kind=obj["kind"],
            namespace=md["namespace"], name=md["name"],
            message="signed URL published; waiting for client upload",
        )
        write_status(self.client, obj)
        # Poll storage until the client PUT lands (the client also patches an
        # annotation to requeue us immediately, reference upload.go:184-189).
        return Result(requeue_after=10.0)

    # -- build job ---------------------------------------------------------

    def _build_job(
        self, obj: Obj, build: dict, job_name: str, target_image: str
    ) -> Obj:
        md = obj["metadata"]
        init_containers = []
        volumes = [{"name": "workspace", "emptyDir": {}}]
        kaniko_args = [
            "--dockerfile=Dockerfile",
            "--context=dir:///workspace",
            f"--destination={target_image}",
        ]
        if build.get("git"):
            git = build["git"]
            clone = ["git", "clone", "--depth=1"]
            # `--branch` accepts tags too (detached HEAD) — one flag
            # covers both BuildGit refs (reference common_types.go:32-47:
            # tag OR branch, pulled at build time only).
            ref = git.get("tag") or git.get("branch")
            if ref:
                clone += ["--branch", ref]
            clone += [git["url"], "/workspace/repo"]
            init_containers.append(
                {
                    "name": "clone",
                    "image": GIT_IMAGE,
                    "command": clone,
                    "volumeMounts": [
                        {"name": "workspace", "mountPath": "/workspace"}
                    ],
                }
            )
            ctx = "/workspace/repo"
            if git.get("path"):
                ctx = f"{ctx}/{git['path']}"
            kaniko_args[1] = f"--context=dir://{ctx}"
        else:
            upload = build.get("upload") or {}
            object_path = self._upload_object_path(
                obj, upload.get("md5Checksum", "")
            )
            kaniko_args[1] = (
                "--context="
                f"{self.cloud.cfg.artifact_bucket_url.rstrip('/')}/{object_path}"
            )
        return {
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": {
                "name": job_name,
                "namespace": md["namespace"],
                "annotations": {"image": target_image},
                "ownerReferences": [owner_reference(obj)],
            },
            "spec": {
                "backoffLimit": 2,
                "template": {
                    "metadata": {
                        "annotations": {
                            "kubectl.kubernetes.io/default-container": "kaniko"
                        }
                    },
                    "spec": {
                        "serviceAccountName": SA_CONTAINER_BUILDER,
                        "restartPolicy": "Never",
                        "initContainers": init_containers,
                        "containers": [
                            {
                                "name": "kaniko",
                                "image": KANIKO_IMAGE,
                                "args": kaniko_args,
                                "resources": builder_resources(),
                                "volumeMounts": [
                                    {
                                        "name": "workspace",
                                        "mountPath": "/workspace",
                                    }
                                ],
                            }
                        ],
                        "volumes": volumes,
                    },
                },
            },
        }
