"""Reconcilers for the four CR kinds (reference: internal/controller/
{dataset,model,notebook,server}_controller.go).

Behavior parity with the reference plus the TPU-first changes:
  * workloads with multi-host TPU asks become JobSet+headless-Service gangs
    (workloads.py) instead of single-pod Jobs;
  * default images/commands point at the in-repo runtime entrypoints
    (load.main / train.main / serve.main) instead of external
    `substratusai/*` images (SURVEY.md §2.2).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from substratus_tpu.api import conditions as C
from substratus_tpu.cloud.base import Cloud
from substratus_tpu.controller.common import (
    SA_DATA_LOADER,
    SA_MODELLER,
    SA_MODEL_SERVER,
    SA_NOTEBOOK,
    condition_true,
    job_state,
    pod_ready,
    reconcile_child,
    reconcile_service_account,
    set_condition,
    write_status,
)
from substratus_tpu.controller.runtime import Result
from substratus_tpu.controller.workloads import (
    build_container,
    build_pod,
    owner_reference,
    params_configmap,
    workload_for_pod,
)
from substratus_tpu.kube.client import KubeClient, NotFound, Obj
from substratus_tpu.sci.client import SCIClient

# The one runtime image holding this package; commands select the entrypoint.
DEFAULT_RUNTIME_IMAGE = "ghcr.io/substratus-tpu/runtime:latest"
LOADER_COMMAND = ["python", "-m", "substratus_tpu.load.main"]
TRAINER_COMMAND = ["python", "-m", "substratus_tpu.train.main"]
SERVER_COMMAND = ["python", "-m", "substratus_tpu.serve.main"]
BATCHGEN_COMMAND = ["python", "-m", "substratus_tpu.serve.batchgen"]
NOTEBOOK_COMMAND = [
    "jupyter", "lab", "--ip=0.0.0.0", "--port=8888", "--allow-root",
    "--no-browser", "--notebook-dir=/content",
]


class _ObjRef:
    def __init__(self, obj: Obj):
        self.KIND = obj["kind"]
        self.name = obj["metadata"]["name"]
        self.namespace = obj["metadata"]["namespace"]


class BaseReconciler:
    def __init__(self, client: KubeClient, cloud: Cloud, sci: SCIClient):
        self.client = client
        self.cloud = cloud
        self.sci = sci

    # -- shared gates ------------------------------------------------------

    def image_gate(self, obj: Obj) -> bool:
        """True = proceed. A CR with a build in flight has no image yet
        (reference model_controller.go:54-57)."""
        spec = obj.get("spec") or {}
        if spec.get("image"):
            return True
        if spec.get("build"):
            return False  # BuildReconciler owns progress
        # No image, no build: run the in-repo runtime image.
        fresh = self.client.get(
            obj["kind"], obj["metadata"]["namespace"], obj["metadata"]["name"]
        )
        fresh["spec"]["image"] = DEFAULT_RUNTIME_IMAGE
        self.client.update(fresh)
        obj["spec"]["image"] = DEFAULT_RUNTIME_IMAGE
        return True

    def stamp_artifacts_url(self, obj: Obj) -> str:
        url = self.cloud.object_artifact_url(_ObjRef(obj))
        status = obj.setdefault("status", {})
        if (status.get("artifacts") or {}).get("url") != url:
            status["artifacts"] = {"url": url}
            write_status(self.client, obj)
        return url

    def artifact_url_of(self, dep: Obj) -> str:
        return (dep.get("status", {}).get("artifacts") or {}).get(
            "url"
        ) or self.cloud.object_artifact_url(_ObjRef(dep))

    def resolve_ref(
        self,
        obj: Obj,
        field: str,
        kind: str,
        cond_type: str,
        not_found_reason: str,
        not_ready_reason: str,
    ) -> Tuple[Optional[Obj], Optional[Result]]:
        """Fetch a referenced CR; set a typed condition and park (watch
        indexes requeue us) when missing/not ready (reference
        model_controller.go:92-172)."""
        ref = (obj.get("spec") or {}).get(field)
        if not ref:
            return None, None
        ns = ref.get("namespace") or obj["metadata"]["namespace"]
        try:
            dep = self.client.get(kind, ns, ref["name"])
        except NotFound:
            set_condition(
                obj, cond_type, False, not_found_reason,
                f"{kind} {ns}/{ref['name']} not found",
            )
            write_status(self.client, obj)
            return None, Result()
        if not dep.get("status", {}).get("ready"):
            set_condition(
                obj, cond_type, False, not_ready_reason,
                f"{kind} {ns}/{ref['name']} not ready",
            )
            write_status(self.client, obj)
            return None, Result()
        return dep, None

    def finish_from_workload(
        self, obj: Obj, workload: Obj, cond_type: str
    ) -> None:
        state = job_state(workload)
        if state == "complete":
            set_condition(obj, cond_type, True, C.REASON_JOB_COMPLETE)
            obj["status"]["ready"] = True
        elif state == "failed":
            set_condition(obj, cond_type, False, C.REASON_JOB_FAILED)
            obj["status"]["ready"] = False
        else:
            set_condition(obj, cond_type, False, C.REASON_JOB_NOT_COMPLETE)
            obj["status"]["ready"] = False
        write_status(self.client, obj)

    def backoff_limit(self, obj: Obj) -> int:
        """Accelerator jobs are expensive: don't blind-retry (reference
        model_controller.go:294-303 — 0 for GPU jobs, 2 for cheap ones)."""
        res = (obj.get("spec") or {}).get("resources") or {}
        if res.get("tpu") or (res.get("gpu") or {}).get("count"):
            return 0
        return 2


class DatasetReconciler(BaseReconciler):
    """-data-loader Job with RW artifacts mount (reference
    dataset_controller.go:35-217)."""

    def __call__(self, obj: Obj) -> Result:
        if obj.get("status", {}).get("ready") and condition_true(
            obj, C.CONDITION_COMPLETE
        ):
            return Result()
        if not self.image_gate(obj):
            return Result()
        reconcile_child(self.client, params_configmap(obj))
        url = self.stamp_artifacts_url(obj)
        reconcile_service_account(
            self.client, self.cloud, self.sci,
            obj["metadata"]["namespace"], SA_DATA_LOADER,
        )
        container = build_container(
            obj, self.cloud, artifact_mounts={}, default_command=LOADER_COMMAND
        )
        pod = build_pod(
            obj, self.cloud,
            name=f"{obj['metadata']['name']}-data-loader",
            sa_name=SA_DATA_LOADER,
            container=container,
            mounts={
                "artifacts": (url, {"artifacts": "/content/artifacts"}, False)
            },
        )
        workloads = workload_for_pod(obj, pod, self.backoff_limit(obj))
        live = [reconcile_child(self.client, w) for w in workloads]
        self.finish_from_workload(obj, live[-1], C.CONDITION_COMPLETE)
        return Result()


class ModelReconciler(BaseReconciler):
    """-modeller Job/JobSet: import (no refs) or finetune (base model +
    dataset RO mounts) (reference model_controller.go:43-218, 286-395)."""

    def __call__(self, obj: Obj) -> Result:
        if obj.get("status", {}).get("ready") and condition_true(
            obj, C.CONDITION_COMPLETE
        ):
            return Result()
        if not self.image_gate(obj):
            return Result()
        reconcile_child(self.client, params_configmap(obj))
        url = self.stamp_artifacts_url(obj)
        ns = obj["metadata"]["namespace"]
        reconcile_service_account(
            self.client, self.cloud, self.sci, ns, SA_MODELLER
        )

        base_model, park = self.resolve_ref(
            obj, "model", "Model", C.CONDITION_COMPLETE,
            C.REASON_MODEL_NOT_FOUND, C.REASON_MODEL_NOT_READY,
        )
        if park:
            return park
        dataset, park = self.resolve_ref(
            obj, "dataset", "Dataset", C.CONDITION_COMPLETE,
            C.REASON_DATASET_NOT_FOUND, C.REASON_DATASET_NOT_READY,
        )
        if park:
            return park

        mounts: Dict[str, tuple] = {
            "artifacts": (url, {"artifacts": "/content/artifacts"}, False)
        }
        if base_model is not None:
            mounts["model"] = (
                self.artifact_url_of(base_model),
                {"artifacts": "/content/model"},
                True,
            )
        if dataset is not None:
            mounts["data"] = (
                self.artifact_url_of(dataset),
                {"artifacts": "/content/data"},
                True,
            )

        default_cmd = TRAINER_COMMAND if dataset is not None else LOADER_COMMAND
        container = build_container(
            obj, self.cloud, artifact_mounts={}, default_command=default_cmd
        )
        pod = build_pod(
            obj, self.cloud,
            name=f"{obj['metadata']['name']}-modeller",
            sa_name=SA_MODELLER,
            container=container,
            mounts=mounts,
        )
        workloads = workload_for_pod(obj, pod, self.backoff_limit(obj))
        live = [reconcile_child(self.client, w) for w in workloads]
        self.finish_from_workload(obj, live[-1], C.CONDITION_COMPLETE)
        return Result()


class NotebookReconciler(BaseReconciler):
    """Long-running -notebook Pod with jupyter; suspend deletes the Pod
    (reference notebook_controller.go:131-155, 316-454)."""

    def __call__(self, obj: Obj) -> Result:
        md = obj["metadata"]
        ns = md["namespace"]
        pod_name = f"{md['name']}-notebook"
        if (obj.get("spec") or {}).get("suspend"):
            try:
                self.client.delete("Pod", ns, pod_name)
            except NotFound:
                pass
            obj.setdefault("status", {})["ready"] = False
            set_condition(
                obj, C.CONDITION_DEPLOYED, False, C.REASON_SUSPENDED
            )
            write_status(self.client, obj)
            return Result()

        if not self.image_gate(obj):
            return Result()
        reconcile_child(self.client, params_configmap(obj))
        url = self.stamp_artifacts_url(obj)
        reconcile_service_account(
            self.client, self.cloud, self.sci, ns, SA_NOTEBOOK
        )

        base_model, park = self.resolve_ref(
            obj, "model", "Model", C.CONDITION_DEPLOYED,
            C.REASON_MODEL_NOT_FOUND, C.REASON_MODEL_NOT_READY,
        )
        if park:
            return park
        dataset, park = self.resolve_ref(
            obj, "dataset", "Dataset", C.CONDITION_DEPLOYED,
            C.REASON_DATASET_NOT_FOUND, C.REASON_DATASET_NOT_READY,
        )
        if park:
            return park

        mounts: Dict[str, tuple] = {
            "artifacts": (url, {"artifacts": "/content/artifacts"}, False)
        }
        if base_model is not None:
            mounts["model"] = (
                self.artifact_url_of(base_model),
                {"artifacts": "/content/model"}, True,
            )
        if dataset is not None:
            mounts["data"] = (
                self.artifact_url_of(dataset),
                {"artifacts": "/content/data"}, True,
            )

        container = build_container(
            obj, self.cloud, artifact_mounts={},
            default_command=NOTEBOOK_COMMAND,
            ports=[{"containerPort": 8888, "name": "notebook"}],
        )
        container["env"].append(
            {"name": "NOTEBOOK_TOKEN", "value": "default"}
        )
        container["readinessProbe"] = {
            "httpGet": {"path": "/api", "port": 8888},
            "initialDelaySeconds": 2,
            "periodSeconds": 5,
        }
        pod = build_pod(
            obj, self.cloud,
            name=pod_name,
            sa_name=SA_NOTEBOOK,
            container=container,
            mounts=mounts,
            restart_policy="Always",
        )
        desired_pod: Obj = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "namespace": ns,
                "ownerReferences": [owner_reference(obj)],
                **pod["metadata"],
            },
            "spec": pod["spec"],
        }
        live = reconcile_child(self.client, desired_pod)
        ready = pod_ready(live)
        obj.setdefault("status", {})["ready"] = ready
        set_condition(
            obj, C.CONDITION_DEPLOYED, ready,
            C.REASON_POD_READY if ready else C.REASON_POD_NOT_READY,
        )
        write_status(self.client, obj)
        return Result()


class ServerReconciler(BaseReconciler):
    """-server Deployment + Service; Serving condition from readyReplicas
    (reference server_controller.go:50-335).

    Servers whose `params.baseModel` names a shared base Model collapse
    onto ONE backing deployment with every tenant's LoRA adapter mounted
    (multi-tenant adapter serving) — see _reconcile_shared."""

    def __call__(self, obj: Obj) -> Result:
        if not self.image_gate(obj):
            return Result()
        if ((obj.get("spec") or {}).get("params") or {}).get(
            "batchGenerate"
        ):
            return self._reconcile_batchgen(obj)
        if ((obj.get("spec") or {}).get("params") or {}).get("baseModel"):
            return self._reconcile_shared(obj)
        reconcile_child(self.client, params_configmap(obj))
        md = obj["metadata"]
        ns = md["namespace"]

        model, park = self.resolve_ref(
            obj, "model", "Model", C.CONDITION_SERVING,
            C.REASON_MODEL_NOT_FOUND, C.REASON_MODEL_NOT_READY,
        )
        if park:
            return park
        reconcile_service_account(
            self.client, self.cloud, self.sci, ns, SA_MODEL_SERVER
        )

        mounts: Dict[str, tuple] = {}
        if model is not None:
            mounts["model"] = (
                self.artifact_url_of(model),
                {"artifacts": "/content/model"}, True,
            )
        container = build_container(
            obj, self.cloud, artifact_mounts={},
            default_command=SERVER_COMMAND,
            ports=[{"containerPort": 8080, "name": "http-serve"}],
        )
        container["readinessProbe"] = {
            "httpGet": {"path": "/", "port": 8080},
            "initialDelaySeconds": 5,
            "periodSeconds": 10,
        }
        pod = build_pod(
            obj, self.cloud,
            name=f"{md['name']}-server",
            sa_name=SA_MODEL_SERVER,
            container=container,
            mounts=mounts,
            restart_policy="Always",
        )
        if pod["_slice"]["num_hosts"] > 1:
            return self._reconcile_multihost(obj, pod)
        disagg = ((obj.get("spec") or {}).get("params") or {}).get(
            "disaggregated"
        )
        if disagg:
            return self._reconcile_disaggregated(obj, pod, disagg)
        replicas = int((obj.get("spec") or {}).get("params", {}).get("replicas", 1))
        engine_selector = {"substratus.ai/object": f"server-{md['name']}"}
        deployment: Obj = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": f"{md['name']}-server",
                "namespace": ns,
                "ownerReferences": [owner_reference(obj)],
            },
            "spec": {
                "replicas": replicas,
                "selector": {
                    "matchLabels": {
                        "substratus.ai/object": f"server-{md['name']}"
                    }
                },
                "template": {"metadata": pod["metadata"], "spec": pod["spec"]},
            },
        }
        # replicas > 1: a plain k8s Service would round-robin blind —
        # no backpressure, no load shedding, broken streams on replica
        # loss. Put the routing tier in front (docs/serving.md) and
        # keep the client-facing Service NAME stable by repointing its
        # selector at the gateway pods.
        front_selector = dict(engine_selector)
        gateway_ready = True
        if replicas > 1:
            from substratus_tpu.controller.workloads import (
                serving_gateway_workloads,
            )

            front_selector = {
                "substratus.ai/object": f"server-gateway-{md['name']}"
            }
            gw_live = [
                reconcile_child(self.client, w)
                for w in serving_gateway_workloads(
                    obj, f"{md['name']}-server",
                    (obj.get("spec") or {}).get("image"), engine_selector,
                )
            ]
            gateway_ready = (
                gw_live[-1].get("status", {}).get("readyReplicas") or 0
            ) > 0
        service: Obj = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": f"{md['name']}-server",
                "namespace": ns,
                "ownerReferences": [owner_reference(obj)],
            },
            "spec": {
                "selector": front_selector,
                "ports": [
                    {"port": 8080, "targetPort": "http-serve", "name": "http"}
                ],
            },
        }
        if replicas > 1:
            # The gateway container port is named http-gw.
            service["spec"]["ports"][0]["targetPort"] = "http-gw"
        reconcile_child(self.client, service)
        live = reconcile_child(self.client, deployment)
        ready = (
            (live.get("status", {}).get("readyReplicas") or 0) > 0
            and gateway_ready
        )
        obj.setdefault("status", {})["ready"] = ready
        set_condition(
            obj, C.CONDITION_SERVING, ready,
            C.REASON_DEPLOYMENT_READY if ready else C.REASON_DEPLOYMENT_NOT_READY,
        )
        write_status(self.client, obj)
        return Result()

    def _reconcile_batchgen(self, obj: Obj) -> Result:
        """Batch-generation flavor (ROADMAP item 5, serve/batchgen.py,
        docs/batch-generation.md): a Server whose `params.batchGenerate`
        is set runs to COMPLETION instead of serving — a Job on a single
        host, or the same JobSet gang shape a multi-host lockstep Server
        gets (headless rendezvous Service + TPU_WORKER_*/JAX coordinator
        env) when the resources ask for a multi-host slice. Mounts:
        model RO at /content/model, the manifest Dataset RO at
        /content/data, this CR's artifact bucket RW at /content/artifacts
        (the output-shard home). Status follows the Job like a Model
        import: Complete condition + ready on completion."""
        if obj.get("status", {}).get("ready") and condition_true(
            obj, C.CONDITION_COMPLETE
        ):
            return Result()
        reconcile_child(self.client, params_configmap(obj))
        url = self.stamp_artifacts_url(obj)
        ns = obj["metadata"]["namespace"]
        reconcile_service_account(
            self.client, self.cloud, self.sci, ns, SA_MODEL_SERVER
        )

        model, park = self.resolve_ref(
            obj, "model", "Model", C.CONDITION_COMPLETE,
            C.REASON_MODEL_NOT_FOUND, C.REASON_MODEL_NOT_READY,
        )
        if park:
            return park
        dataset, park = self.resolve_ref(
            obj, "dataset", "Dataset", C.CONDITION_COMPLETE,
            C.REASON_DATASET_NOT_FOUND, C.REASON_DATASET_NOT_READY,
        )
        if park:
            return park

        mounts: Dict[str, tuple] = {
            "artifacts": (url, {"artifacts": "/content/artifacts"}, False)
        }
        if model is not None:
            mounts["model"] = (
                self.artifact_url_of(model),
                {"artifacts": "/content/model"}, True,
            )
        if dataset is not None:
            mounts["data"] = (
                self.artifact_url_of(dataset),
                {"artifacts": "/content/data"}, True,
            )
        container = build_container(
            obj, self.cloud, artifact_mounts={},
            default_command=BATCHGEN_COMMAND,
        )
        pod = build_pod(
            obj, self.cloud,
            name=f"{obj['metadata']['name']}-batchgen",
            sa_name=SA_MODEL_SERVER,
            container=container,
            mounts=mounts,
        )
        workloads = workload_for_pod(obj, pod, self.backoff_limit(obj))
        live = [reconcile_child(self.client, w) for w in workloads]
        self.finish_from_workload(obj, live[-1], C.CONDITION_COMPLETE)
        return Result()

    def _reconcile_disaggregated(self, obj: Obj, pod, disagg) -> Result:
        """Disaggregated prefill/decode serving (docs/serving.md,
        serve/disagg.py): `params.disaggregated` — `true` for a 1+1
        split, or `{"prefill": N, "decode": M}` — deploys two
        phase-specialized tiers plus the routing gateway fronting the
        PREFILL tier; the client-facing Service name stays
        `{name}-server`, exactly like the replicated path."""
        from substratus_tpu.controller.workloads import (
            disagg_tier_selector,
            disaggregated_server_workloads,
            serving_gateway_workloads,
        )

        md = obj["metadata"]
        ns = md["namespace"]
        counts = disagg if isinstance(disagg, dict) else {}
        n_prefill = max(1, int(counts.get("prefill", 1)))
        n_decode = max(1, int(counts.get("decode", 1)))
        front_name = f"{md['name']}-server"
        tier_live = {}
        for w in disaggregated_server_workloads(
            obj, front_name, pod, n_prefill, n_decode
        ):
            live = reconcile_child(self.client, w)
            if w["kind"] == "Deployment":
                tier_live[w["metadata"]["name"]] = live
        # The gateway routes admissions; its replica set is the PREFILL
        # tier (role-aware pick would skip decode replicas anyway, but
        # not discovering them avoids wasted /loadz polls).
        gw_live = [
            reconcile_child(self.client, w)
            for w in serving_gateway_workloads(
                obj, front_name,
                (obj.get("spec") or {}).get("image"),
                disagg_tier_selector(md["name"], "prefill"),
            )
        ]
        gateway_ready = (
            gw_live[-1].get("status", {}).get("readyReplicas") or 0
        ) > 0
        service: Obj = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": front_name,
                "namespace": ns,
                "ownerReferences": [owner_reference(obj)],
            },
            "spec": {
                "selector": {
                    "substratus.ai/object": f"server-gateway-{md['name']}"
                },
                "ports": [
                    {"port": 8080, "targetPort": "http-gw", "name": "http"}
                ],
            },
        }
        reconcile_child(self.client, service)
        tiers_ready = all(
            (live.get("status", {}).get("readyReplicas") or 0) > 0
            for live in tier_live.values()
        ) and len(tier_live) == 2
        ready = tiers_ready and gateway_ready
        obj.setdefault("status", {})["ready"] = ready
        set_condition(
            obj, C.CONDITION_SERVING, ready,
            C.REASON_DEPLOYMENT_READY if ready
            else C.REASON_DEPLOYMENT_NOT_READY,
        )
        write_status(self.client, obj)
        return Result()

    def _reconcile_shared(self, obj: Obj) -> Result:
        """Multi-tenant adapter serving: every Server in this namespace
        whose `params.baseModel` names the same base Model CR becomes a
        TENANT of one shared deployment — the base model loaded once,
        each tenant's adapter artifact mounted under /content/adapters,
        one engine serving the whole roster (ParvaGPU's packing insight:
        spatial sharing, not per-kernel speed, dominates inference
        economics — ROADMAP item 2, docs/serving.md "Multi-tenant
        adapters"). The tenant's own `spec.model` must point at its
        adapter Model (a LoRA finetune: train/main.py writes the
        `{artifacts}/adapter` artifact); its front Service keeps the
        `{name}-server` address and selects the shared pods, so clients
        only ever differ in the OpenAI `model` field."""
        md = obj["metadata"]
        ns = md["namespace"]
        params = (obj.get("spec") or {}).get("params") or {}
        base_name = str(params["baseModel"])

        # Base Model gate (params-ref flavor of resolve_ref).
        try:
            base = self.client.get("Model", ns, base_name)
        except NotFound:
            set_condition(
                obj, C.CONDITION_SERVING, False, C.REASON_MODEL_NOT_FOUND,
                f"base Model {ns}/{base_name} not found",
            )
            obj.setdefault("status", {})["ready"] = False
            write_status(self.client, obj)
            return Result()
        if not base.get("status", {}).get("ready"):
            set_condition(
                obj, C.CONDITION_SERVING, False, C.REASON_MODEL_NOT_READY,
                f"base Model {ns}/{base_name} not ready",
            )
            obj.setdefault("status", {})["ready"] = False
            write_status(self.client, obj)
            return Result()

        # This tenant's adapter Model gate.
        adapter_model, park = self.resolve_ref(
            obj, "model", "Model", C.CONDITION_SERVING,
            C.REASON_MODEL_NOT_FOUND, C.REASON_MODEL_NOT_READY,
        )
        if park:
            return park
        if adapter_model is None:
            set_condition(
                obj, C.CONDITION_SERVING, False, C.REASON_INVALID_SPEC,
                "params.baseModel requires spec.model to name the "
                "tenant's adapter Model",
            )
            obj.setdefault("status", {})["ready"] = False
            write_status(self.client, obj)
            return Result()

        reconcile_service_account(
            self.client, self.cloud, self.sci, ns, SA_MODEL_SERVER
        )

        # The full tenant roster, deterministic: every reconcile (from
        # any tenant) derives the SAME shared deployment, so
        # reconcile_child converges instead of churning.
        tenants = sorted(
            (
                s for s in self.client.list("Server", ns)
                if str(
                    ((s.get("spec") or {}).get("params") or {}).get(
                        "baseModel", ""
                    )
                ) == base_name
            ),
            key=lambda s: s["metadata"]["name"],
        )
        adapter_urls: Dict[str, str] = {}
        replicas = 1
        for t in tenants:
            replicas = max(
                replicas,
                int((t.get("spec") or {}).get("params", {}).get("replicas", 1)),
            )
            ref = (t.get("spec") or {}).get("model")
            if not ref:
                continue
            try:
                m = self.client.get(
                    "Model", ref.get("namespace") or ns, ref["name"]
                )
            except NotFound:
                continue
            if m.get("status", {}).get("ready"):
                # Tenants whose adapter isn't ready yet simply aren't
                # mounted; their own reconcile parks them NotReady.
                adapter_urls[t["metadata"]["name"]] = self.artifact_url_of(m)
        primary = tenants[0]

        from substratus_tpu.controller.workloads import (
            shared_server_deployment,
            shared_server_name,
            shared_server_selector,
        )

        # The primary tenant's params ConfigMap configures the engine
        # (created here too: convergence must not depend on reconcile
        # order between tenants).
        reconcile_child(self.client, params_configmap(primary))
        container = build_container(
            primary, self.cloud, artifact_mounts={},
            default_command=SERVER_COMMAND,
            ports=[{"containerPort": 8080, "name": "http-serve"}],
        )
        container["readinessProbe"] = {
            "httpGet": {"path": "/", "port": 8080},
            "initialDelaySeconds": 5,
            "periodSeconds": 10,
        }
        pod = build_pod(
            primary, self.cloud,
            name=shared_server_name(base_name),
            sa_name=SA_MODEL_SERVER,
            container=container,
            mounts={},
            restart_policy="Always",
        )
        if pod["_slice"]["num_hosts"] > 1:
            obj.setdefault("status", {})["ready"] = False
            set_condition(
                obj, C.CONDITION_SERVING, False, C.REASON_INVALID_SPEC,
                "params.baseModel is unsupported for multi-host slices",
            )
            write_status(self.client, obj)
            return Result()
        deployment = shared_server_deployment(
            tenants, self.artifact_url_of(base), adapter_urls, pod,
            self.cloud, replicas, base_name,
        )
        live = reconcile_child(self.client, deployment)

        # Each tenant keeps its own front Service NAME (clients never
        # re-address when a Server joins or leaves the shared base).
        service: Obj = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": f"{md['name']}-server",
                "namespace": ns,
                "ownerReferences": [owner_reference(obj)],
            },
            "spec": {
                "selector": shared_server_selector(base_name),
                "ports": [
                    {"port": 8080, "targetPort": "http-serve", "name": "http"}
                ],
            },
        }
        reconcile_child(self.client, service)

        ready = (live.get("status", {}).get("readyReplicas") or 0) > 0
        obj.setdefault("status", {})["ready"] = ready
        set_condition(
            obj, C.CONDITION_SERVING, ready,
            C.REASON_DEPLOYMENT_READY if ready
            else C.REASON_DEPLOYMENT_NOT_READY,
        )
        write_status(self.client, obj)
        return Result()

    def _reconcile_multihost(self, obj: Obj, pod: Dict) -> Result:
        """Server over a multi-host TPU slice: a lockstep serving gang
        (JobSet + headless rendezvous Service + a front Service routing
        to worker 0) instead of a Deployment — the shape the
        examples/llama2-70b v5e-16 Server needs and the single-pod
        reference could not express (server_controller.go:114-205).
        Ready when the gang's leader pod (completion index 0) reports
        the Ready condition, which its HTTP readiness probe gates."""
        from substratus_tpu.controller.workloads import (
            serving_gang_name, serving_group_from_pod,
            serving_leader_selector,
        )

        ns = obj["metadata"]["namespace"]
        replicas = int(
            (obj.get("spec") or {}).get("params", {}).get("replicas", 1)
        )
        if replicas > 1:
            # Loud rejection beats silently serving 1/N of the asked
            # capacity: gang replication (N JobSets behind one Service)
            # is not implemented.
            obj.setdefault("status", {})["ready"] = False
            set_condition(
                obj, C.CONDITION_SERVING, False, C.REASON_INVALID_SPEC,
                f"params.replicas={replicas} is unsupported for "
                "multi-host slices (one serving gang per Server)",
            )
            write_status(self.client, obj)
            return Result()
        for w in serving_group_from_pod(obj, pod):
            reconcile_child(self.client, w)

        want = serving_leader_selector(serving_gang_name(pod["_name"]))

        def pod_ready(p: Dict) -> bool:
            # Terminating pods don't count: during gang recreation a
            # stale leader with a lingering Ready=True must not mask the
            # replacement that is still starting.
            if p.get("metadata", {}).get("deletionTimestamp"):
                return False
            conds = (p.get("status") or {}).get("conditions") or []
            return any(
                c.get("type") == "Ready" and c.get("status") == "True"
                for c in conds
            )

        ready = any(
            pod_ready(p)
            for p in self.client.list("Pod", ns)
            if all(
                (p.get("metadata", {}).get("labels") or {}).get(k) == v
                for k, v in want.items()
            )
        )
        obj.setdefault("status", {})["ready"] = ready
        set_condition(
            obj, C.CONDITION_SERVING, ready,
            C.REASON_DEPLOYMENT_READY if ready else C.REASON_DEPLOYMENT_NOT_READY,
        )
        write_status(self.client, obj)
        return Result()
