"""Lease-based leader election (reference: controller-runtime leader
election, cmd/controllermanager/main.go:51-68 — only one manager replica
reconciles at a time).

Standard coordination.k8s.io/Lease protocol: acquire when unheld or
expired, renew at a fraction of the lease duration, step down by letting
the lease lapse. `run_with_leadership` blocks until elected, then keeps
renewing on a daemon thread; if renewal fails (apiserver partition, lease
stolen) the process exits so the replacement replica takes over — crash-
and-restart beats split-brain reconciling.
"""
from __future__ import annotations

import datetime
import logging
import os
import socket
import threading
import time
from typing import Optional

from substratus_tpu.kube.client import Conflict, KubeClient, NotFound
from substratus_tpu.observability.tracing import current_trace_id

log = logging.getLogger("substratus.leader")

LEASE_NAME = "substratus-controller-manager"


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _parse(ts: Optional[str]) -> Optional[datetime.datetime]:
    if not ts:
        return None
    return datetime.datetime.fromisoformat(ts.replace("Z", "+00:00"))


class LeaderElector:
    def __init__(
        self,
        client: KubeClient,
        namespace: str = "substratus",
        identity: Optional[str] = None,
        lease_seconds: int = 15,
    ):
        self.client = client
        self.namespace = namespace
        self.identity = identity or f"{socket.gethostname()}-{os.getpid()}"
        self.lease_seconds = lease_seconds

    def _try_acquire(self) -> bool:
        now = _now()
        stamp = now.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"
        try:
            lease = self.client.get("Lease", self.namespace, LEASE_NAME)
        except NotFound:
            try:
                self.client.create(
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {
                            "name": LEASE_NAME,
                            "namespace": self.namespace,
                        },
                        "spec": {
                            "holderIdentity": self.identity,
                            "leaseDurationSeconds": self.lease_seconds,
                            "renewTime": stamp,
                        },
                    }
                )
                return True
            except Conflict:
                return False
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        renew = _parse(spec.get("renewTime"))
        expired = renew is None or (
            now - renew
        ).total_seconds() > spec.get("leaseDurationSeconds", self.lease_seconds)
        if holder not in (None, "", self.identity) and not expired:
            return False
        lease["spec"] = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": self.lease_seconds,
            "renewTime": stamp,
        }
        try:
            self.client.update(lease)
            return True
        except Conflict:
            return False  # raced another candidate; retry

    def acquire_blocking(self) -> None:
        while not self._try_acquire():
            log.info("waiting for leadership (%s)", self.identity)
            time.sleep(self.lease_seconds / 3)
        log.info("acquired leadership as %s", self.identity)

    def keep_renewing(self, on_lost=None) -> threading.Thread:
        def lost():
            log.error("lost leadership; exiting for failover")
            if on_lost is not None:
                on_lost()
            else:
                # os._exit, not sys.exit: SystemExit raised in a daemon
                # thread kills only that thread — the ex-leader would keep
                # reconciling (the split-brain this module exists to stop).
                os._exit(1)

        def loop():
            last_renewed = time.monotonic()
            while True:
                time.sleep(self.lease_seconds / 3)
                try:
                    ok = self._try_acquire()
                except Exception:  # sublint: allow[broad-except]: any renewal error is a failed renewal, never a thread-killer; retried until the lease deadline
                    log.exception(
                        "lease renewal error (trace_id=%s)",
                        current_trace_id(),
                    )
                    ok = False
                if ok:
                    last_renewed = time.monotonic()
                elif time.monotonic() - last_renewed > self.lease_seconds:
                    lost()

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t
