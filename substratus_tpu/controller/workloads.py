"""Workload construction shared by the CR reconcilers.

Builds the pod/Job/JobSet/Deployment dicts that run contract containers:
/content/* mounts, params ConfigMap, PARAM_* env, secret-ref env resolution,
owner references for GC + watch wakeup, and — the TPU-first part the
reference never had (SURVEY.md §2.3) — multi-host TPU slice wiring: a JobSet
of one Job per slice host with a headless Service for worker discovery and
the TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / MEGASCALE coordinator env that
`jax.distributed.initialize` consumes (parallel/distributed.py).
"""
from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional

from substratus_tpu.api.types import API_VERSION
from substratus_tpu.cloud.base import Cloud
from substratus_tpu.kube.client import Obj
from substratus_tpu.resources.apply import apply_resources
from substratus_tpu.utils.serde import from_dict

CONTENT_DIR = "/content"
SECRET_REF_RE = re.compile(
    r"^\s*\$\{\{\s*secrets\.([A-Za-z0-9-_.]+)\.([A-Za-z0-9-_.]+)\s*\}\}\s*$"
)


def owner_reference(obj: Obj) -> Dict[str, Any]:
    md = obj["metadata"]
    return {
        "apiVersion": obj.get("apiVersion", API_VERSION),
        "kind": obj["kind"],
        "name": md["name"],
        "uid": md.get("uid", ""),
        "controller": True,
        "blockOwnerDeletion": True,
    }


def workload_traceparent(obj: Obj) -> str:
    """The TRACEPARENT env value stamped into a CR's workload containers,
    read back by train/main.py and load/main.py so the job's spans join a
    trace named after the CR.

    Deliberately DETERMINISTIC (derived from the CR's identity, not the
    live reconcile span): reconcile passes mint fresh span ids every
    time, and a per-pass value in the pod template would read as spec
    drift — reconcile_child would delete-and-recreate a running Job on
    every reconcile. Stable identity -> stable env -> no churn; the
    reconcile spans record the same id as `workload_trace_id` so the two
    traces join in queries."""
    from substratus_tpu.observability.propagation import (
        deterministic_traceparent,
    )

    md = obj["metadata"]
    return deterministic_traceparent(
        obj["kind"], md.get("namespace", "default"), md["name"],
        md.get("uid", ""),
    )


def resolve_env(env: Dict[str, str]) -> List[Dict[str, Any]]:
    """CR env -> container env; `${{ secrets.name.key }}` values become
    SecretKeyRef entries (reference utils.go:67-93)."""
    out: List[Dict[str, Any]] = []
    for key, value in sorted((env or {}).items()):
        m = SECRET_REF_RE.match(str(value))
        if m:
            out.append(
                {
                    "name": key,
                    "valueFrom": {
                        "secretKeyRef": {"name": m.group(1), "key": m.group(2)}
                    },
                }
            )
        else:
            out.append({"name": key, "value": str(value)})
    return out


def params_env(params: Dict[str, Any]) -> List[Dict[str, Any]]:
    """params {k: v} -> PARAM_K env vars (docs/design.md:271-281)."""
    out = []
    for key, value in sorted((params or {}).items()):
        name = "PARAM_" + re.sub(r"[^A-Za-z0-9]", "_", str(key)).upper()
        if isinstance(value, (dict, list)):
            value = json.dumps(value)
        out.append({"name": name, "value": str(value)})
    return out


def params_configmap(obj: Obj) -> Obj:
    """ConfigMap `{name}-{kind}-params` holding params.json (reference
    params_reconciler.go:28-104)."""
    md = obj["metadata"]
    params = (obj.get("spec") or {}).get("params") or {}
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": f"{md['name']}-{obj['kind'].lower()}-params",
            "namespace": md["namespace"],
            "ownerReferences": [owner_reference(obj)],
        },
        "data": {"params.json": json.dumps(params, sort_keys=True)},
    }


def build_container(
    obj: Obj,
    cloud: Cloud,
    *,
    artifact_mounts: Dict[str, tuple],  # volume name -> (bucket_url, subpath->target, ro)
    default_command: Optional[List[str]] = None,
    ports: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The single workload container + its pod-level mount side effects are
    assembled by build_pod_spec; this returns the container skeleton."""
    spec = obj.get("spec") or {}
    container: Dict[str, Any] = {
        "name": obj["kind"].lower(),
        "image": spec.get("image"),
        "workingDir": CONTENT_DIR,
        "env": resolve_env(spec.get("env"))
        + params_env(spec.get("params"))
        # Distributed tracing across the spawn boundary: the job process
        # (train/load main) parents its root span from this env var.
        + [{"name": "TRACEPARENT", "value": workload_traceparent(obj)}],
    }
    if spec.get("command"):
        container["command"] = list(spec["command"])
    elif default_command:
        container["command"] = list(default_command)
    if ports:
        container["ports"] = ports
    return container


def build_pod(
    obj: Obj,
    cloud: Cloud,
    *,
    name: str,
    sa_name: str,
    container: Dict[str, Any],
    mounts: Dict[str, tuple],  # volname -> (bucket_url, {sub: target}, read_only)
    restart_policy: str = "Never",
) -> Dict[str, Any]:
    """Pod template dict with params CM mount + bucket mounts + resources."""
    from substratus_tpu.observability.tracing import tracer

    md = obj["metadata"]
    spec = obj.get("spec") or {}
    # Joins the controller trace to the job trace: the reconcile span gets
    # a child naming the deterministic trace id the workload will run
    # under (see workload_traceparent).
    with tracer.span(
        "controller.plan_workload", kind=obj["kind"], workload=name,
        workload_trace_id=workload_traceparent(obj).split("-")[1],
    ):
        pass
    pod_metadata: Dict[str, Any] = {
        "labels": {
            "app.kubernetes.io/managed-by": "substratus-tpu",
            "substratus.ai/object": f"{obj['kind'].lower()}-{md['name']}",
        },
        "annotations": {"kubectl.kubernetes.io/default-container": container["name"]},
    }
    pod_spec: Dict[str, Any] = {
        "serviceAccountName": sa_name,
        "restartPolicy": restart_policy,
        "containers": [container],
    }

    # params.json mount via subPath (reference params_reconciler.go:78-104).
    cm_name = f"{md['name']}-{obj['kind'].lower()}-params"
    pod_spec.setdefault("volumes", []).append(
        {"name": "params", "configMap": {"name": cm_name}}
    )
    container.setdefault("volumeMounts", []).append(
        {
            "name": "params",
            "mountPath": f"{CONTENT_DIR}/params.json",
            "subPath": "params.json",
        }
    )

    for vol_name, (bucket_url, sub_mounts, read_only) in mounts.items():
        cloud.mount_bucket(
            pod_metadata, pod_spec, container, vol_name, bucket_url,
            sub_mounts, read_only=read_only,
        )

    from substratus_tpu.api.common import Resources

    res = from_dict(Resources, spec.get("resources"))
    slice_info = apply_resources(
        pod_metadata, pod_spec, container, cloud.name, res
    )
    return {
        "metadata": pod_metadata,
        "spec": pod_spec,
        "_slice": slice_info,
        "_name": name,
    }


def job_from_pod(obj: Obj, pod: Dict[str, Any], backoff_limit: int) -> Obj:
    md = obj["metadata"]
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": pod["_name"],
            "namespace": md["namespace"],
            "ownerReferences": [owner_reference(obj)],
        },
        "spec": {
            "backoffLimit": backoff_limit,
            "template": {"metadata": pod["metadata"], "spec": pod["spec"]},
        },
    }


def _coordinator_fqdn(jobset_name: str, namespace: str) -> str:
    # JobSet pod DNS: {jobset}-{replicatedJob}-{jobIndex}-{podIndex}.{jobset}
    return f"{jobset_name}-workers-0-0.{jobset_name}.{namespace}"


def jobset_from_pod(
    obj: Obj, pod: Dict[str, Any], backoff_limit: int = 0
) -> List[Obj]:
    """Multi-host TPU slice: JobSet (one replicated Job, num_hosts indexed
    pods) + headless Service for stable worker DNS. Greenfield vs the
    reference (its Jobs were single-pod, SURVEY.md §2.3)."""
    md = obj["metadata"]
    slice_info = pod["_slice"]
    n = slice_info["num_hosts"]
    name = pod["_name"]
    coord = _coordinator_fqdn(name, md["namespace"])
    hostnames = ",".join(
        f"{name}-workers-0-{i}.{name}.{md['namespace']}" for i in range(n)
    )
    container = pod["spec"]["containers"][0]
    container.setdefault("env", []).extend(
        [
            {"name": "TPU_WORKER_HOSTNAMES", "value": hostnames},
            {
                "name": "TPU_WORKER_ID",
                "valueFrom": {
                    "fieldRef": {
                        "fieldPath": (
                            "metadata.annotations"
                            "['batch.kubernetes.io/job-completion-index']"
                        )
                    }
                },
            },
            {"name": "MEGASCALE_COORDINATOR_ADDRESS", "value": coord},
            {"name": "JAX_COORDINATOR_ADDRESS", "value": f"{coord}:8476"},
            {"name": "JAX_NUM_PROCESSES", "value": str(n)},
        ]
    )
    pod["spec"]["subdomain"] = name
    pod["spec"]["hostNetwork"] = False

    headless_svc: Obj = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "namespace": md["namespace"],
            "ownerReferences": [owner_reference(obj)],
        },
        "spec": {
            "clusterIP": "None",
            "selector": {"jobset.sigs.k8s.io/jobset-name": name},
            # Rendezvous DNS must exist BEFORE pods are Ready: serving
            # gang followers never pass the HTTP readiness probe (only
            # worker 0 binds :8080), and worker 0 itself cannot become
            # ready until jax.distributed rendezvous — which needs this
            # Service's records — completes. Without this flag the gang
            # deadlocks at bootstrap on a real cluster.
            "publishNotReadyAddresses": True,
        },
    }
    jobset: Obj = {
        "apiVersion": "jobset.x-k8s.io/v1alpha2",
        "kind": "JobSet",
        "metadata": {
            "name": name,
            "namespace": md["namespace"],
            "ownerReferences": [owner_reference(obj)],
        },
        "spec": {
            # all-or-nothing: any host failure recreates the whole slice
            # group; checkpoint-resume picks up from the last save.
            "failurePolicy": {"maxRestarts": 3},
            "replicatedJobs": [
                {
                    "name": "workers",
                    "replicas": 1,
                    "template": {
                        "spec": {
                            "backoffLimit": backoff_limit,
                            "completions": n,
                            "parallelism": n,
                            "completionMode": "Indexed",
                            "template": {
                                "metadata": pod["metadata"],
                                "spec": pod["spec"],
                            },
                        }
                    },
                }
            ],
        },
    }
    return [headless_svc, jobset]


def workload_for_pod(obj: Obj, pod: Dict[str, Any], backoff_limit: int) -> List[Obj]:
    """Single-host -> [Job]; multi-host TPU -> [Service, JobSet]."""
    if pod["_slice"]["num_hosts"] > 1:
        return jobset_from_pod(obj, pod, backoff_limit)
    return [job_from_pod(obj, pod, backoff_limit)]


GATEWAY_COMMAND = ["python", "-m", "substratus_tpu.gateway.main"]


def replicas_service_name(front_name: str) -> str:
    """Headless Service enumerating the engine replica pods — the DNS
    name the gateway's --discover loop re-resolves."""
    return f"{front_name}-replicas"


def gateway_name(front_name: str) -> str:
    return f"{front_name}-gateway"


def serving_gateway_workloads(
    obj: Obj, front_name: str, image: str, engine_selector: Dict[str, str],
) -> List[Obj]:
    """The routing tier for a replicated single-host Server
    (docs/serving.md "Serving gateway"): [headless replicas Service,
    gateway Deployment]. The caller repoints the front Service at the
    gateway pods, so the client address never changes when `replicas`
    crosses 1.

    The gateway is jax-free and stateless: one replica suffices for
    correctness (it restarts in milliseconds), and its Deployment
    scales independently of the engines if the HTTP tier ever
    saturates. `publishNotReadyAddresses` stays FALSE on the replicas
    Service: DNS only hands the gateway pods that passed the engine
    readiness probe; the gateway's own circuit breaker handles the
    ready-but-dying window."""
    md = obj["metadata"]
    ns = md["namespace"]
    gw_labels = {
        "app.kubernetes.io/managed-by": "substratus-tpu",
        "substratus.ai/object": f"server-gateway-{md['name']}",
    }
    replicas_svc: Obj = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": replicas_service_name(front_name),
            "namespace": ns,
            "ownerReferences": [owner_reference(obj)],
        },
        "spec": {
            "clusterIP": "None",
            "selector": dict(engine_selector),
            "ports": [
                {"port": 8080, "targetPort": "http-serve", "name": "http"}
            ],
        },
    }
    container: Dict[str, Any] = {
        "name": "gateway",
        "image": image,
        "command": list(GATEWAY_COMMAND),
        "args": [
            "--port", "8080",
            "--discover",
            f"{replicas_service_name(front_name)}.{ns}.svc:8080",
        ],
        "env": [{"name": "TRACEPARENT", "value": workload_traceparent(obj)}],
        "ports": [{"containerPort": 8080, "name": "http-gw"}],
        "readinessProbe": {
            # Gateway readiness = "at least one routable replica":
            # clients only reach a gateway that can actually serve.
            "httpGet": {"path": "/", "port": 8080},
            "initialDelaySeconds": 1,
            "periodSeconds": 5,
        },
    }
    deployment: Obj = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": gateway_name(front_name),
            "namespace": ns,
            "ownerReferences": [owner_reference(obj)],
        },
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {
                "substratus.ai/object": gw_labels["substratus.ai/object"]
            }},
            "template": {
                "metadata": {"labels": dict(gw_labels)},
                "spec": {"containers": [container]},
            },
        },
    }
    return [replicas_svc, deployment]


KV_TRANSFER_PORT = 8500


def disagg_tier_selector(obj_name: str, role: str) -> Dict[str, str]:
    """Pod selector of one disaggregated serving tier."""
    return {
        "substratus.ai/object": f"server-{obj_name}",
        "substratus.ai/serve-role": role,
    }


def decode_transfer_service_name(front_name: str) -> str:
    """Headless Service exposing the decode tier's KV-transfer port —
    the DNS name prefill workers resolve into their peer set."""
    return f"{front_name}-decode-transfer"


def disaggregated_server_workloads(
    obj: Obj, front_name: str, pod: Dict[str, Any],
    prefill_replicas: int, decode_replicas: int,
) -> List[Obj]:
    """Two phase-specialized tiers for one Server (docs/serving.md
    "Disaggregated prefill/decode", serve/disagg.py): a prefill
    Deployment that admits requests and ships KV pages, a decode
    Deployment that continues them, and a headless Service exposing the
    decode tier's transfer port. Both tiers run the SAME image/params —
    the controller differentiates them purely through env
    (SUBSTRATUS_SERVE_ROLE / SUBSTRATUS_DECODE_PEERS /
    SUBSTRATUS_TRANSFER_PORT, read by serve.main), so one ConfigMap
    serves both. The routing gateway fronts the prefill tier only
    (decode replicas never take client admissions)."""
    import copy

    md = obj["metadata"]
    ns = md["namespace"]
    transfer_dns = (
        f"{decode_transfer_service_name(front_name)}.{ns}.svc"
        f":{KV_TRANSFER_PORT}"
    )
    out: List[Obj] = [{
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": decode_transfer_service_name(front_name),
            "namespace": ns,
            "ownerReferences": [owner_reference(obj)],
        },
        "spec": {
            "clusterIP": "None",
            "selector": disagg_tier_selector(md["name"], "decode"),
            "ports": [{
                "port": KV_TRANSFER_PORT,
                "targetPort": "kv-transfer",
                "name": "kv-transfer",
            }],
        },
    }]
    for role, n in (
        ("decode", decode_replicas), ("prefill", prefill_replicas)
    ):
        tier = copy.deepcopy(
            {"metadata": pod["metadata"], "spec": pod["spec"]}
        )
        labels = disagg_tier_selector(md["name"], role)
        tier["metadata"].setdefault("labels", {}).update(labels)
        container = tier["spec"]["containers"][0]
        env = container.setdefault("env", [])
        env.append({"name": "SUBSTRATUS_SERVE_ROLE", "value": role})
        if role == "decode":
            env.append({
                "name": "SUBSTRATUS_TRANSFER_PORT",
                "value": str(KV_TRANSFER_PORT),
            })
            container.setdefault("ports", []).append(
                {"containerPort": KV_TRANSFER_PORT, "name": "kv-transfer"}
            )
        else:
            env.append({
                "name": "SUBSTRATUS_DECODE_PEERS", "value": transfer_dns,
            })
        out.append({
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": f"{front_name}-{role}",
                "namespace": ns,
                "ownerReferences": [owner_reference(obj)],
            },
            "spec": {
                "replicas": int(n),
                "selector": {"matchLabels": dict(labels)},
                "template": {
                    "metadata": tier["metadata"],
                    "spec": tier["spec"],
                },
            },
        })
    return out


def shared_server_name(base_model_name: str) -> str:
    """Backing Deployment name for Servers that share one base Model
    (multi-tenant adapter serving, docs/serving.md)."""
    return f"{base_model_name}-shared-server"


def shared_server_selector(base_model_name: str) -> Dict[str, str]:
    return {"substratus.ai/object": f"shared-server-{base_model_name}"}


ADAPTERS_MOUNT_DIR = "/content/adapters"


def shared_server_deployment(
    tenants: List[Obj],  # every tenant Server, sorted by name
    base_url: str,
    adapter_urls: Dict[str, str],  # tenant Server name -> adapter Model url
    pod: Dict[str, Any],
    cloud: Cloud,
    replicas: int,
    base_model_name: str,
) -> Obj:
    """ONE Deployment backing every tenant Server of a base Model: the
    base mounted at /content/model, each tenant's adapter artifact
    (`{artifacts}/adapter`, written by train/main.py for LoRA runs)
    mounted at /content/adapters/<tenant> — serve.main discovers the
    directory and serves all tenants from one engine (serve/adapters.py).

    Derived entirely from the SORTED tenant list, so whichever tenant's
    reconcile runs produces the identical object and reconcile_child
    converges instead of churning. EVERY tenant is an ownerReference
    (the primary — first by name — as controller): deployment status
    changes requeue all tenants, and GC only collects the deployment
    when the last tenant is deleted."""
    primary = tenants[0]
    md = primary["metadata"]
    container = pod["spec"]["containers"][0]
    cloud.mount_bucket(
        pod["metadata"], pod["spec"], container, "model", base_url,
        {"artifacts": "/content/model"}, read_only=True,
    )
    for tenant, url in sorted(adapter_urls.items()):
        cloud.mount_bucket(
            pod["metadata"], pod["spec"], container, f"adapter-{tenant}",
            url, {"artifacts/adapter": f"{ADAPTERS_MOUNT_DIR}/{tenant}"},
            read_only=True,
        )
    labels = shared_server_selector(base_model_name)
    pod["metadata"]["labels"].update(labels)
    owners = []
    for t in tenants:
        ref = owner_reference(t)
        if t is not primary:
            ref["controller"] = False
        owners.append(ref)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": shared_server_name(base_model_name),
            "namespace": md["namespace"],
            "ownerReferences": owners,
        },
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": pod["metadata"],
                "spec": pod["spec"],
            },
        },
    }


def serving_gang_name(front_name: str) -> str:
    """JobSet/headless-Service name for a multi-host serving gang whose
    client-facing front Service is `front_name`."""
    return f"{front_name}-gang"


# Leader pods of a serving gang (worker 0 owns HTTP; serve/multihost.py).
# The JobSet controller stamps the jobset-name label on every pod and the
# Job controller stamps the completion index, so this selector is exactly
# "worker 0 of this gang".
def serving_leader_selector(gang_name: str) -> Dict[str, str]:
    return {
        "jobset.sigs.k8s.io/jobset-name": gang_name,
        "batch.kubernetes.io/job-completion-index": "0",
    }


def serving_group_from_pod(obj: Obj, pod: Dict[str, Any]) -> List[Obj]:
    """Multi-host serving gang: [headless Service, JobSet, front Service].

    A Server whose resources ask for a multi-host TPU slice (e.g. v5e
    4x4 = 4 hosts x 4 chips) cannot be one Deployment pod — each host
    runs one engine process and they jointly execute every step over the
    global mesh (serve/multihost.py lockstep). The gang is a JobSet like
    the trainer's (same TPU_WORKER_*/JAX_COORDINATOR env and headless
    Service for rendezvous, jobset_from_pod above) with serving
    restart semantics: containers restart in place (OnFailure) and the
    whole gang is recreated on unrecoverable host failure. The FRONT
    Service routes only to worker 0 — the lockstep leader owns HTTP;
    followers serve no traffic. Replaces the reference's single-pod
    Server shape (internal/controller/server_controller.go:114-205) for
    slices the reference could never span.

    Naming: the gang (JobSet + its headless rendezvous Service, which
    must share the pods' subdomain) is `{name}-server-gang`; the FRONT
    Service keeps the `{name}-server` name clients use on the
    single-host path, so switching a Server between slice sizes never
    changes its address."""
    md = obj["metadata"]
    front_name = pod["_name"]
    pod = dict(pod)
    pod["_name"] = serving_gang_name(front_name)
    headless_svc, jobset = jobset_from_pod(obj, pod, backoff_limit=0)
    tmpl = jobset["spec"]["replicatedJobs"][0]["template"]["spec"]
    # In-place container restarts (a Job pod may not use Always); the
    # JobSet failurePolicy still gang-recreates on pod/host loss.
    tmpl["template"]["spec"]["restartPolicy"] = "OnFailure"
    jobset["spec"]["failurePolicy"] = {"maxRestarts": 1000}

    front_svc: Obj = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": front_name,
            "namespace": md["namespace"],
            "ownerReferences": [owner_reference(obj)],
        },
        "spec": {
            "selector": serving_leader_selector(serving_gang_name(front_name)),
            "ports": [
                {"port": 8080, "targetPort": "http-serve", "name": "http"}
            ],
        },
    }
    return [headless_svc, jobset, front_svc]
