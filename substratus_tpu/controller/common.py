"""Shared reconciler helpers (reference: internal/controller/utils.go,
service_accounts_controller.go)."""
from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional

from substratus_tpu.cloud.base import Cloud
from substratus_tpu.kube.client import KubeClient, NotFound, Obj
from substratus_tpu.sci.client import SCIClient

BOUND_ANNOTATION = "substratus.ai/identity-bound"
PRINCIPAL_ANNOTATION = "iam.gke.io/gcp-service-account"

# Per-workload service accounts (reference service_accounts_controller.go:16-22).
SA_CONTAINER_BUILDER = "container-builder"
SA_MODELLER = "modeller"
SA_MODEL_SERVER = "model-server"
SA_NOTEBOOK = "notebook"
SA_DATA_LOADER = "data-loader"


def utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


def get_conditions(obj: Obj) -> List[Dict[str, Any]]:
    return obj.setdefault("status", {}).setdefault("conditions", [])


def set_condition(
    obj: Obj, ctype: str, status: bool, reason: str, message: str = ""
) -> None:
    conds = get_conditions(obj)
    new = {
        "type": ctype,
        "status": "True" if status else "False",
        "reason": reason,
        "message": message,
        "observedGeneration": obj.get("metadata", {}).get("generation"),
    }
    for i, c in enumerate(conds):
        if c.get("type") == ctype:
            new["lastTransitionTime"] = (
                c.get("lastTransitionTime")
                if c.get("status") == new["status"]
                else utcnow()
            )
            conds[i] = new
            return
    new["lastTransitionTime"] = utcnow()
    conds.append(new)


def condition_true(obj: Obj, ctype: str) -> bool:
    return any(
        c.get("type") == ctype and c.get("status") == "True"
        for c in obj.get("status", {}).get("conditions", [])
    )


def job_state(job: Obj) -> Optional[str]:
    """'complete' | 'failed' | None (reference utils.go:23-49)."""
    for c in job.get("status", {}).get("conditions", []):
        if c.get("status") != "True":
            continue
        if c.get("type") in ("Complete", "Completed"):
            return "complete"
        if c.get("type") == "Failed":
            return "failed"
    return None


def pod_ready(pod: Obj) -> bool:
    """(reference utils.go:51-65)"""
    if pod.get("status", {}).get("phase") != "Running":
        return False
    return any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in pod.get("status", {}).get("conditions", [])
    )


def reconcile_service_account(
    client: KubeClient,
    cloud: Cloud,
    sci: SCIClient,
    namespace: str,
    name: str,
) -> str:
    """Ensure the workload SA exists, carries the cloud principal annotation,
    and the principal<->SA identity binding has been made via SCI
    (reference service_accounts_controller.go:38-66). Returns SA name."""
    principal = cloud.associate_principal(namespace, name)
    try:
        sa = client.get("ServiceAccount", namespace, name)
    except NotFound:
        sa = client.create(
            {
                "apiVersion": "v1",
                "kind": "ServiceAccount",
                "metadata": {"name": name, "namespace": namespace},
            }
        )
    annotations = sa.setdefault("metadata", {}).setdefault("annotations", {})
    if annotations.get(BOUND_ANNOTATION) != "true":
        sci.bind_identity(principal, namespace, name)
        annotations[PRINCIPAL_ANNOTATION] = principal
        annotations[BOUND_ANNOTATION] = "true"
        client.update(sa)
    return name


# Kinds whose spec the apiserver lets us update in place; everything else
# pod-templated (Job, Pod, JobSet) has immutable fields and must be
# delete-and-recreated on drift (reference: server_controller.go:264-274
# SSA-Patches the Deployment; notebook_controller.go:266-281 falls back to
# delete-and-recreate on immutable-field errors).
_MUTABLE_KINDS = {"Deployment", "Service", "ConfigMap", "Secret"}

# Sections of a desired child we own and converge. metadata is deliberately
# excluded (labels/annotations may be written by other controllers).
_OWNED_SECTIONS = ("spec", "data", "stringData")


def _covers(desired: Any, live: Any) -> bool:
    """True when every field the desired object specifies is present with
    the same value in live. Dicts compare per-key (apiserver-defaulted
    extra keys in live are fine), lists positionally and exhaustively
    (container lists are ordered), scalars by equality."""
    if isinstance(desired, dict):
        if not isinstance(live, dict):
            return False
        return all(_covers(v, live.get(k)) for k, v in desired.items())
    if isinstance(desired, list):
        if not isinstance(live, list) or len(desired) != len(live):
            return False
        return all(_covers(d, l) for d, l in zip(desired, live))
    return desired == live


def child_drifted(desired: Obj, live: Obj) -> bool:
    return any(
        not _covers(desired[s], live.get(s))
        for s in _OWNED_SECTIONS
        if s in desired
    )


def reconcile_child(client: KubeClient, desired: Obj) -> Obj:
    """Create the child if absent; converge it when the CR-derived desired
    state drifts from live (the reference does this with server-side-apply
    Patches + FieldOwner, falling back to delete-and-recreate for
    immutable fields — see _MUTABLE_KINDS). Returns live state."""
    kind = desired["kind"]
    md = desired["metadata"]
    try:
        live = client.get(kind, md["namespace"], md["name"])
    except NotFound:
        return client.create(desired)
    if not child_drifted(desired, live):
        return live
    if kind in _MUTABLE_KINDS:
        for s in _OWNED_SECTIONS:
            if s not in desired:
                continue
            if s == "spec" and isinstance(live.get(s), dict):
                # Merge per-key: a wholesale replace would clear
                # apiserver-assigned spec fields (Service clusterIP is
                # immutable — the PUT would be rejected with "field is
                # immutable"). data/stringData we own outright.
                live[s].update(desired[s])
            else:
                live[s] = desired[s]
        return client.update(live)
    # Immutable (pod-carrying) kinds: recreate. The fake and real clients
    # both cascade owned objects (Job pods) on delete.
    client.delete(kind, md["namespace"], md["name"])
    return client.create(desired)


def write_status(client: KubeClient, obj: Obj) -> Obj:
    """Write obj's status only if it differs from the live object's status.

    Idempotence is what lets the watch-driven queue quiesce: a reconcile
    pass that changes nothing must write nothing (every write fans out a
    MODIFIED event that re-enqueues the object)."""
    md = obj["metadata"]
    live = client.get(obj["kind"], md.get("namespace", "default"), md["name"])
    if live.get("status") == obj.get("status"):
        return live
    live["status"] = obj.get("status")
    return client.update_status(live)
