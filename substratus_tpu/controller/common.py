"""Shared reconciler helpers (reference: internal/controller/utils.go,
service_accounts_controller.go)."""
from __future__ import annotations

import copy
import datetime
import json
from typing import Any, Dict, List, Optional, Sequence

from substratus_tpu.cloud.base import Cloud
from substratus_tpu.kube.client import (
    KubeClient, NotFound, Obj, fold_secret_string_data,
)
from substratus_tpu.sci.client import SCIClient

BOUND_ANNOTATION = "substratus.ai/identity-bound"
PRINCIPAL_ANNOTATION = "iam.gke.io/gcp-service-account"

# Per-workload service accounts (reference service_accounts_controller.go:16-22).
SA_CONTAINER_BUILDER = "container-builder"
SA_MODELLER = "modeller"
SA_MODEL_SERVER = "model-server"
SA_NOTEBOOK = "notebook"
SA_DATA_LOADER = "data-loader"


def utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


def get_conditions(obj: Obj) -> List[Dict[str, Any]]:
    return obj.setdefault("status", {}).setdefault("conditions", [])


def set_condition(
    obj: Obj, ctype: str, status: bool, reason: str, message: str = ""
) -> None:
    conds = get_conditions(obj)
    new = {
        "type": ctype,
        "status": "True" if status else "False",
        "reason": reason,
        "message": message,
        "observedGeneration": obj.get("metadata", {}).get("generation"),
    }
    for i, c in enumerate(conds):
        if c.get("type") == ctype:
            new["lastTransitionTime"] = (
                c.get("lastTransitionTime")
                if c.get("status") == new["status"]
                else utcnow()
            )
            conds[i] = new
            return
    new["lastTransitionTime"] = utcnow()
    conds.append(new)


def condition_true(obj: Obj, ctype: str) -> bool:
    return any(
        c.get("type") == ctype and c.get("status") == "True"
        for c in obj.get("status", {}).get("conditions", [])
    )


def job_state(job: Obj) -> Optional[str]:
    """'complete' | 'failed' | None (reference utils.go:23-49)."""
    for c in job.get("status", {}).get("conditions", []):
        if c.get("status") != "True":
            continue
        if c.get("type") in ("Complete", "Completed"):
            return "complete"
        if c.get("type") == "Failed":
            return "failed"
    return None


def pod_ready(pod: Obj) -> bool:
    """(reference utils.go:51-65)"""
    if pod.get("status", {}).get("phase") != "Running":
        return False
    return any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in pod.get("status", {}).get("conditions", [])
    )


def reconcile_service_account(
    client: KubeClient,
    cloud: Cloud,
    sci: SCIClient,
    namespace: str,
    name: str,
) -> str:
    """Ensure the workload SA exists, carries the cloud principal annotation,
    and the principal<->SA identity binding has been made via SCI
    (reference service_accounts_controller.go:38-66). Returns SA name."""
    principal = cloud.associate_principal(namespace, name)
    try:
        sa = client.get("ServiceAccount", namespace, name)
    except NotFound:
        sa = client.create(
            {
                "apiVersion": "v1",
                "kind": "ServiceAccount",
                "metadata": {"name": name, "namespace": namespace},
            }
        )
    annotations = sa.setdefault("metadata", {}).setdefault("annotations", {})
    if annotations.get(BOUND_ANNOTATION) != "true":
        sci.bind_identity(principal, namespace, name)
        annotations[PRINCIPAL_ANNOTATION] = principal
        annotations[BOUND_ANNOTATION] = "true"
        client.update(sa)
    return name


# Kinds whose spec the apiserver lets us update in place; everything else
# pod-templated (Job, Pod, JobSet) has immutable fields and must be
# delete-and-recreated on drift (reference: server_controller.go:264-274
# SSA-Patches the Deployment; notebook_controller.go:266-281 falls back to
# delete-and-recreate on immutable-field errors).
_MUTABLE_KINDS = {"Deployment", "Service", "ConfigMap", "Secret"}

# Sections of a desired child we own and converge. metadata is deliberately
# excluded (labels/annotations may be written by other controllers).
_OWNED_SECTIONS = ("spec", "data", "stringData")

# kubectl-style applied-config record. The reference gets field ownership
# for free from server-side apply with a FieldOwner (server_controller.go:
# 264-274): fields the owner stops asserting are pruned by the apiserver.
# Against a plain PUT-based client we reproduce that with the same
# mechanism `kubectl apply` uses — remember what we last asserted in an
# annotation and three-way merge (last-applied, desired, live).
#
# Only the KEY STRUCTURE is recorded (dicts keep keys, list shapes kept,
# scalars stripped to null): merge3 never reads last-applied values, and
# storing values would copy Secret stringData into metadata — the
# kubectl-apply secret-leak pattern SSA was designed to end — and risk the
# apiserver's 256KiB annotation budget on big pod templates.
LAST_APPLIED_ANNOTATION = "substratus.ai/last-applied"


def _skeleton(v: Any, in_list: bool = False) -> Any:
    """Strip values, keep key structure — EXCEPT the strategic-merge
    identity fields of list elements (containers[].name, ports[].port, …),
    which three-way list pruning needs to know WHICH elements we asserted.
    Identity fields are names/ports by construction, never payload; map
    values (Secret data included) are always stripped because only list
    elements get the exemption."""
    if isinstance(v, dict):
        return {
            k: (
                x
                if in_list and k in _LIST_MERGE_KEYS
                and not isinstance(x, (dict, list))
                else _skeleton(x)
            )
            for k, x in v.items()
        }
    if isinstance(v, list):
        return [_skeleton(x, in_list=True) for x in v]
    return None


def _applied_config(desired: Obj) -> str:
    return json.dumps(
        {s: _skeleton(desired[s]) for s in _OWNED_SECTIONS if s in desired},
        sort_keys=True, separators=(",", ":"),
    )


def _last_applied(live: Obj) -> Dict[str, Any]:
    raw = (
        live.get("metadata", {}).get("annotations", {})
        .get(LAST_APPLIED_ANNOTATION)
    )
    if not raw:
        return {}
    try:
        out = json.loads(raw)
    except ValueError:
        return {}
    return out if isinstance(out, dict) else {}


# k8s strategic-merge identity keys, in patchMergeKey precedence: list
# elements pair up for an in-place merge only when they agree on the first
# of these present in either element (containers/env/volumes key on name,
# Service ports on port, volumeMounts on mountPath, tolerations on key).
# Dict lists with NO recognized merge key are atomic — exactly what
# strategic merge does for unkeyed lists.
_LIST_MERGE_KEYS = ("name", "port", "containerPort", "mountPath", "key")


def _list_key_field(els: Sequence[Any]) -> Optional[str]:
    """The strategic-merge key field shared by EVERY dict element of a
    list (with unique values), or None when the list is not keyable."""
    if not els or not all(isinstance(e, dict) for e in els):
        return None
    for key in _LIST_MERGE_KEYS:
        if all(key in e for e in els):
            vals = [e[key] for e in els]
            if len(set(map(repr, vals))) == len(vals):
                return key
    return None


def _merge_keyed_list(live: list, desired: list, last: Any,
                      key: str) -> list:
    """Strategic-merge a keyed list: desired elements (in desired order)
    merge with their key-matched live/last counterparts; live elements the
    controller never asserted (admission-injected kube-api-access-*
    volumes, webhook sidecars) are KEPT, appended in live order; live
    elements previously asserted but dropped from desired are pruned."""
    live_by = {e[key]: e for e in live if isinstance(e, dict) and key in e}
    last = last if isinstance(last, list) else []
    last_by = {e[key]: e for e in last if isinstance(e, dict) and key in e}
    desired_keys = {e[key] for e in desired}
    out = [
        merge3(live_by.get(e[key]), e, last_by.get(e[key])) for e in desired
    ]
    for e in live:
        k = e.get(key) if isinstance(e, dict) else None
        if k is not None and k not in desired_keys and k not in last_by:
            out.append(copy.deepcopy(e))  # foreign element: keep
    return out


def _prune_keyed_list(live: list, last: Any) -> list:
    """live minus the elements our last-applied record asserted (by
    strategic-merge key). No key field -> the list was ours atomically ->
    nothing survives."""
    key = _list_key_field(live)
    if key is None:
        return []
    last = last if isinstance(last, list) else []
    owned = {e.get(key) for e in last if isinstance(e, dict)}
    return [copy.deepcopy(e) for e in live
            if isinstance(e, dict) and e.get(key) not in owned]


def merge3(live: Any, desired: Any, last: Any) -> Any:
    """Three-way merge of one owned value.

    Dicts: keys desired asserts are set (recursively); keys last-applied
    asserted that desired no longer does are PRUNED — but only the parts
    we asserted: a nested dict another writer also populated keeps its
    foreign keys. Any live key we never asserted (Service clusterIP,
    apiserver defaults) is kept.

    Lists whose elements all carry a strategic-merge key (_LIST_MERGE_KEYS)
    merge per-element by that key — apiserver defaults inside container
    entries survive, admission-injected elements are kept, and reorders
    can't graft one element's assigned fields onto another. Unkeyed lists
    are atomic (strategic-merge semantics): desired replaces live.
    Scalars: desired wins."""
    if isinstance(desired, dict) and isinstance(live, dict):
        last = last if isinstance(last, dict) else {}
        out: Dict[str, Any] = {}
        for k, v in live.items():
            if k in desired or k not in last:
                out[k] = v
            elif isinstance(v, dict):
                # previously asserted, now dropped: prune only what we
                # asserted inside it; foreign nested keys survive
                pruned = merge3(v, {}, last[k])
                if pruned:
                    out[k] = pruned
            elif isinstance(v, list):
                # dropped keyed list: remove OUR elements, keep foreign
                # (admission-injected) ones; unkeyed lists were owned
                # atomically and go entirely
                kept = _prune_keyed_list(v, last[k])
                if kept:
                    out[k] = kept
        for k, v in desired.items():
            out[k] = merge3(out.get(k), v, last.get(k))
        return out
    if isinstance(desired, list) and isinstance(live, list):
        key = _list_key_field(desired)
        if key is not None and _list_key_field(live) == key:
            return _merge_keyed_list(live, desired, last, key)
    return copy.deepcopy(desired)


def _converged_sections(desired: Obj, live: Obj) -> Dict[str, Any]:
    """The owned sections live *should* have: three-way merge per section.
    A section present in last-applied but dropped from desired entirely is
    merged against an empty assertion — our keys prune, foreign keys stay."""
    last = _last_applied(live)
    out: Dict[str, Any] = {}
    for s in _OWNED_SECTIONS:
        if s in desired:
            out[s] = merge3(live.get(s), desired[s], last.get(s))
        elif s in last and isinstance(live.get(s), dict):
            out[s] = merge3(live[s], {}, last[s])
    return out


def _stamp(obj: Obj, applied: str) -> Obj:
    obj.setdefault("metadata", {}).setdefault("annotations", {})[
        LAST_APPLIED_ANNOTATION
    ] = applied
    return obj


def _normalize_desired(desired: Obj) -> Obj:
    """Rewrite desired state into the form the apiserver STORES, so the
    drift comparison is stable. Today: Secret stringData is write-only —
    the server folds it into data (base64) and never returns it; asserting
    stringData verbatim would read as drift on every reconcile, a
    permanent hot loop. The fold implementation is SHARED with the fake
    apiserver (kube/client.py::fold_secret_string_data)."""
    if desired.get("kind") == "Secret" and "stringData" in desired:
        desired = copy.deepcopy(desired)
        fold_secret_string_data(desired)
    return desired


def reconcile_child(client: KubeClient, desired: Obj) -> Obj:
    """Create the child if absent; converge it when the CR-derived desired
    state drifts from live. The reference does this with server-side-apply
    Patches + FieldOwner (fields the owner stops asserting are pruned by
    the apiserver — server_controller.go:264-274); here the same semantics
    come from a last-applied annotation + three-way merge, falling back to
    delete-and-recreate for immutable kinds (see _MUTABLE_KINDS).
    Returns live state."""
    desired = _normalize_desired(desired)
    kind = desired["kind"]
    md = desired["metadata"]
    applied = _applied_config(desired)
    try:
        live = client.get(kind, md["namespace"], md["name"])
    except NotFound:
        return client.create(_stamp(copy.deepcopy(desired), applied))
    merged = _converged_sections(desired, live)
    drifted = any(m != live.get(s) for s, m in merged.items())
    stale_record = (
        live.get("metadata", {}).get("annotations", {})
        .get(LAST_APPLIED_ANNOTATION) != applied
    )
    if not drifted:
        if stale_record:
            # Live already matches, but what we assert changed (a field we
            # now own already had the right value): record ownership so a
            # later removal still prunes it. Annotation-only update — legal
            # even on immutable kinds.
            live = client.update(_stamp(live, applied))
        return live
    if kind in _MUTABLE_KINDS:
        live.update(merged)
        return client.update(_stamp(live, applied))
    # Immutable (pod-carrying) kinds: recreate. The fake and real clients
    # both cascade owned objects (Job pods) on delete.
    client.delete(kind, md["namespace"], md["name"])
    return client.create(_stamp(copy.deepcopy(desired), applied))


def write_status(client: KubeClient, obj: Obj) -> Obj:
    """Write obj's status only if it differs from the live object's status.

    Idempotence is what lets the watch-driven queue quiesce: a reconcile
    pass that changes nothing must write nothing (every write fans out a
    MODIFIED event that re-enqueues the object)."""
    md = obj["metadata"]
    live = client.get(obj["kind"], md.get("namespace", "default"), md["name"])
    if live.get("status") == obj.get("status"):
        return live
    live["status"] = obj.get("status")
    return client.update_status(live)
