"""Controller-manager wiring + entrypoint (reference:
cmd/controllermanager/main.go:40-240).

    python -m substratus_tpu.controller.manager_main [--fake] [--sci-address ...]

Wires cloud autodetect, SCI client, and 4x(Build + main) reconcilers onto the
Manager; serves healthz/readyz + Prometheus-format metrics on :8081.
"""
from __future__ import annotations

import argparse
import logging
import os
from typing import Optional

from substratus_tpu.cloud.base import Cloud, new_cloud
from substratus_tpu.controller.autoscale import ServerAutoscaler
from substratus_tpu.controller.build import BuildReconciler
from substratus_tpu.controller.crs import (
    DatasetReconciler,
    ModelReconciler,
    NotebookReconciler,
    ServerReconciler,
)
from substratus_tpu.controller.rollout import ServerRollout
from substratus_tpu.controller.runtime import Manager
from substratus_tpu.kube.client import KubeClient
from substratus_tpu.sci.client import FakeSCIClient, SCIClient


def build_manager(
    client: KubeClient, cloud: Cloud, sci: SCIClient
) -> Manager:
    mgr = Manager(client)
    for kind, main_cls in (
        ("Dataset", DatasetReconciler),
        ("Model", ModelReconciler),
        ("Notebook", NotebookReconciler),
        ("Server", ServerReconciler),
    ):
        mgr.register(kind, BuildReconciler(client, cloud, sci))
        mgr.register(kind, main_cls(client, cloud, sci))
    # Closed-loop autoscaling (controller/autoscale.py): runs AFTER the
    # deploy reconciler so a params patch it writes re-enqueues the
    # Server and the next pass deploys the new size.
    mgr.register("Server", ServerAutoscaler(client))
    # Zero-downtime rollout (controller/rollout.py): a changed checkpoint
    # ref hot-swaps weights across the live fleet via /swapz — no drain,
    # no recompile — instead of waiting for pod churn.
    mgr.register("Server", ServerRollout(client))
    return mgr


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--sci-address",
        default=os.environ.get(
            "SCI_ADDRESS", "sci.substratus.svc.cluster.local:10080"
        ),
    )
    ap.add_argument("--cloud", default=None)
    ap.add_argument("--probe-port", type=int, default=8081)
    ap.add_argument(
        "--metrics-port", type=int, default=8443,
        help="RBAC-protected HTTPS /metrics (kube-rbac-proxy equivalent, "
        "in-process); 0 disables the protected listener",
    )
    ap.add_argument(
        "--fake", action="store_true",
        help="in-memory apiserver + fake SCI (local development)",
    )
    ap.add_argument(
        "--leader-elect", action="store_true",
        help="Lease-based leader election (multi-replica deployments)",
    )
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    cloud = new_cloud(args.cloud)
    if args.fake:
        from substratus_tpu.kube.fake import FakeKube

        client: KubeClient = FakeKube()
        sci: SCIClient = FakeSCIClient()
    else:
        from substratus_tpu.kube.real import RealKube
        from substratus_tpu.sci.grpc_transport import GrpcSCIClient

        client = RealKube.in_cluster()
        sci = GrpcSCIClient(args.sci_address)

    # Health must serve BEFORE election blocks: a standby replica that
    # can't answer its liveness probe gets crash-looped and there is never
    # a warm standby.
    from substratus_tpu.observability.health import serve_health

    protect = bool(args.metrics_port) and not args.fake
    # When the protected listener owns /metrics, the open probe port must
    # not also serve it (that would bypass the RBAC check entirely).
    serve_health(
        port=args.probe_port, manager=None, expose_metrics=not protect
    )
    if protect:
        from substratus_tpu.observability.authz import MetricsAuthorizer

        serve_health(
            port=args.metrics_port, manager=None,
            authorizer=MetricsAuthorizer(client), tls=True,
        )

    if args.leader_elect and not args.fake:
        from substratus_tpu.controller.leader import LeaderElector

        elector = LeaderElector(client)
        elector.acquire_blocking()
        elector.keep_renewing()

    mgr = build_manager(client, cloud, sci)
    mgr.bootstrap()
    thread = mgr.start()
    thread.join()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
