"""Closed-loop fleet autoscaling: the pure decision core + the Server
reconciler that wires it to the gateway's fleet telemetry (ROADMAP item
1 — "the last piece between a fleet you size by hand and a fleet that
sizes itself"; docs/serving.md "Autoscaling").

Split by design:

  * ``Autoscaler.plan(FleetSignals, ScaleTargets, now) -> ScalePlan`` —
    pure data in/out, no HTTP, no k8s, no jax. Every robustness edge
    (hysteresis, cooldowns, sustained thresholds, frozen-on-bad-signals,
    slice snapping, victim choice) is unit-testable with hand-built
    signals (tests/test_autoscale.py).
  * ``ServerAutoscaler`` — the k8s wiring: polls the gateway's
    ``/debug/fleetz`` payload (the rendered FleetSignals contract,
    gateway/fleet.py), runs the core, and patches ``params.replicas`` /
    ``params.disaggregated`` tier sizes so the EXISTING
    ``_reconcile_server`` / ``_reconcile_disaggregated`` paths deploy
    the change — the autoscaler never builds a Deployment itself.
  * The in-process apply path for CPU chaos evidence lives in
    gateway/testing.py (``FleetSupervisor``): same decision core, same
    plan, applied to live in-process replicas with drain-based removal.

Robustness contract (the ISSUE's framing: a robustness system first):

  * decisions use EWMA-sustained signals held above/below a threshold
    for a configured duration — never one hot sample;
  * a hysteresis band separates the up and down thresholds, so a noisy
    signal random-walking between them yields ZERO decisions;
  * per-direction cooldowns bound decision frequency, and a scale-up
    also blocks the next scale-down (a replica just added must get a
    chance to absorb load before it can be judged idle);
  * step sizes are bounded (max_step_up / max_step_down);
  * stale, empty, or poisoned signals FREEZE the plan at the current
    (last-known-good) targets — a broken sensor must never shrink a
    loaded fleet. Outcomes land in
    ``substratus_autoscale_decisions_total{outcome}``.

Scale decisions must be deployable: when the fleet runs on TPU slices,
targets snap to the accelerator catalog's topology bins
(``snap_slice``, resources/accelerators.py) — the plan never emits a
chip count no topology holds (the ParvaGPU admission/placement split:
deciding *how much* is separate from deciding *a shape the scheduler
can place*).
"""
from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, replace
from typing import Dict, List, Mapping, Optional, Tuple

from substratus_tpu.gateway.fleet import FleetSignals, ReplicaSignals
from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.resources.accelerators import (
    derive_topology,
    tpu_info,
)

log = logging.getLogger("substratus.autoscale")

# Autoscaler metric catalog (docs/observability.md "Autoscaling").
METRICS.describe(
    "substratus_autoscale_decisions_total",
    "Autoscale decisions, by outcome (applied = targets changed, "
    "held = healthy signals but no change, frozen = stale/empty/"
    "poisoned signals pinned the plan at last-known-good).",
    type="counter",
)
METRICS.describe(
    "substratus_autoscale_target_replicas",
    "Current autoscaler replica target, by tier "
    "(replicas|prefill|decode).",
    type="gauge",
)

_OUTCOMES = ("applied", "held", "frozen")


@dataclass(frozen=True)
class SliceShape:
    """A deployable TPU slice: the snapped chip count always names a
    catalog topology (never a count no slice shape holds)."""

    generation: str
    topology: str
    chips: int
    num_hosts: int


def snap_slice(generation: str, chips: int) -> SliceShape:
    """Snap a raw chip ask to the smallest catalog topology holding it.
    Raises ValueError for chips <= 0 or beyond the generation's largest
    slice — an undeployable ask must fail loudly, not deploy weirdly."""
    if chips <= 0:
        raise ValueError(f"chips {chips} invalid (must be >= 1)")
    info = tpu_info(generation)
    topo = derive_topology(generation, chips)
    total = info.topologies[topo]
    num_hosts = (
        1 if total <= info.chips_per_host
        else total // info.chips_per_host
    )
    return SliceShape(
        generation=info.generation, topology=topo, chips=total,
        num_hosts=num_hosts,
    )


@dataclass(frozen=True)
class ScaleTargets:
    """The fleet's current declared size. Monolithic fleets use
    ``replicas``; disaggregated fleets use the two tier fields (and
    ``replicas`` is ignored). The plan returns the same shape."""

    replicas: int = 1
    prefill: int = 0
    decode: int = 0

    @property
    def disaggregated(self) -> bool:
        return self.prefill > 0 or self.decode > 0

    @property
    def total(self) -> int:
        return (
            self.prefill + self.decode if self.disaggregated
            else self.replicas
        )


@dataclass(frozen=True)
class ScalePlan:
    """One decision. ``outcome`` is the metric label: "applied" means
    the targets differ from the input (the caller should act),
    "held" means healthy signals and no change, "frozen" means the
    inputs were unusable and the targets are pinned at last-known-good.
    ``victims`` names the replicas a scale-down should drain (lowest
    sustained occupancy first; never the only member of a role).
    ``eta_s`` > 0 rides a cold-start scale-up (zero ready replicas):
    the gateway derives Retry-After from it instead of a bare 503."""

    outcome: str
    reason: str
    targets: ScaleTargets
    victims: Tuple[str, ...] = ()
    eta_s: float = 0.0
    slice: Optional[SliceShape] = None


@dataclass
class AutoscalePolicy:
    """Thresholds and timing for one autoscaled fleet. Defaults are
    conservative for production; tests and the CPU chaos harness shrink
    every window to keep wall clock in seconds."""

    min_replicas: int = 1
    max_replicas: int = 8
    scale_to_zero: bool = False

    # Scale-up pressure (any sustained condition triggers):
    up_queue_per_replica: float = 2.0  # EWMA queued reqs per replica
    up_occupancy: float = 0.85  # mean decode-slot occupancy
    up_shed_rate: float = 0.5  # fleet sheds/s (user-visible overload)
    kv_free_floor: float = 0.05  # tightest replica's free KV fraction

    # Scale-down evidence (ALL must hold, sustained):
    down_occupancy: float = 0.30
    down_queue_per_replica: float = 0.25

    # Sustained-signal windows + per-direction cooldowns.
    sustain_up_s: float = 5.0
    sustain_down_s: float = 15.0
    idle_zero_s: float = 60.0  # fully-idle time before scale-to-zero
    up_cooldown_s: float = 10.0
    down_cooldown_s: float = 30.0

    # Bounded steps: one decision never moves the fleet further than
    # this (a mis-tuned threshold costs one step per cooldown, not the
    # whole fleet).
    max_step_up: int = 2
    max_step_down: int = 1

    # Degradation: ALL replicas silent longer than this = a dead
    # aggregator or a partitioned fleet — freeze.
    stale_after_s: float = 20.0

    # Disaggregated rebalance: sustained transfer-queue depth per
    # decode replica above this grows the decode tier (the KV-handoff
    # backlog is the prefill:decode imbalance signal, serve/disagg.py).
    transfer_queue_per_decode: float = 2.0

    # Placement (optional): when set, every replica is one TPU slice of
    # this shape and plans carry the snapped SliceShape.
    tpu_generation: Optional[str] = None
    chips_per_replica: int = 0

    # Cold start: how long a scale-up from zero takes to first ready
    # replica (pod schedule + weights load). Rides ScalePlan.eta_s so
    # the gateway's shed can say "Retry-After: <eta>".
    cold_start_eta_s: float = 30.0


def policy_from_params(auto: Mapping) -> AutoscalePolicy:
    """params.autoscale (Server CR) -> policy. Unknown keys ignored;
    camelCase per the CR params convention (docs/container-contract.md)."""
    p = AutoscalePolicy()
    keymap = {
        "min": "min_replicas",
        "max": "max_replicas",
        "scaleToZero": "scale_to_zero",
        "upQueuePerReplica": "up_queue_per_replica",
        "upOccupancy": "up_occupancy",
        "upShedRate": "up_shed_rate",
        "kvFreeFloor": "kv_free_floor",
        "downOccupancy": "down_occupancy",
        "downQueuePerReplica": "down_queue_per_replica",
        "sustainUpSeconds": "sustain_up_s",
        "sustainDownSeconds": "sustain_down_s",
        "idleZeroSeconds": "idle_zero_s",
        "upCooldownSeconds": "up_cooldown_s",
        "downCooldownSeconds": "down_cooldown_s",
        "maxStepUp": "max_step_up",
        "maxStepDown": "max_step_down",
        "staleAfterSeconds": "stale_after_s",
        "transferQueuePerDecode": "transfer_queue_per_decode",
        "tpuGeneration": "tpu_generation",
        "chipsPerReplica": "chips_per_replica",
        "coldStartEtaSeconds": "cold_start_eta_s",
    }
    for key, attr in keymap.items():
        if key in auto:
            kind = type(getattr(p, attr))
            raw = auto[key]
            if kind is bool:
                setattr(p, attr, bool(raw))
            elif kind is int:
                setattr(p, attr, int(raw))
            elif kind is float:
                setattr(p, attr, float(raw))
            else:
                setattr(p, attr, str(raw) if raw is not None else None)
    if p.min_replicas < 0 or p.max_replicas < max(1, p.min_replicas):
        raise ValueError(
            f"autoscale bounds invalid: min={p.min_replicas} "
            f"max={p.max_replicas}"
        )
    return p


def signals_from_snapshot(payload: Mapping) -> FleetSignals:
    """Parse the /debug/fleetz JSON payload back into the typed
    FleetSignals contract. Raises ValueError on a structurally garbled
    payload — the caller treats that as a poisoned sensor (freeze),
    never as an empty fleet (which would invite a scale-down)."""
    if not isinstance(payload, Mapping):
        raise ValueError("fleetz payload is not a mapping")
    reps_raw = payload.get("replicas")
    fleet = payload.get("fleet")
    if not isinstance(reps_raw, Mapping) or not isinstance(fleet, Mapping):
        raise ValueError("fleetz payload missing replicas/fleet")
    rows: List[ReplicaSignals] = []
    for url, row in sorted(reps_raw.items()):
        if not isinstance(row, Mapping):
            raise ValueError(f"replica row {url!r} is not a mapping")
        ewma = row.get("ewma") or {}
        if not isinstance(ewma, Mapping):
            raise ValueError(f"replica row {url!r} ewma is not a mapping")
        rows.append(ReplicaSignals(
            url=str(url),
            role=str(row.get("role", "both") or "both"),
            samples=int(row.get("reports", 0)),
            age_s=float(row.get("age_s", float("inf"))),
            seq=int(row.get("seq", -1)),
            queue_depth=float(ewma.get("queue_depth", 0.0)),
            occupancy=float(ewma.get("occupancy", 0.0)),
            kv_free_frac=float(ewma.get("kv_free_frac", 1.0)),
            transfer_queue=float(ewma.get("transfer_queue", 0.0)),
            shed_rate=float(ewma.get("shed_rate", 0.0)),
        ))
    roles: Dict[str, int] = {}
    for r in rows:
        roles[r.role] = roles.get(r.role, 0) + 1
    return FleetSignals(
        ts=float(payload.get("now_mono", 0.0)),
        replicas=tuple(rows),
        queue_depth=float(fleet.get("queue_depth", 0.0)),
        occupancy=float(fleet.get("occupancy", 0.0)),
        kv_free_frac=float(fleet.get("kv_free_frac", 1.0)),
        transfer_queue=float(fleet.get("transfer_queue", 0.0)),
        shed_rate=float(fleet.get("shed_rate", 0.0)),
        roles=roles,
    )


def pick_victims(
    signals: FleetSignals, count: int, role: Optional[str] = None
) -> Tuple[str, ...]:
    """Choose replicas a scale-down should drain: lowest sustained
    occupancy (then queue) first — the cheapest streams to wait out.
    Never picks the only live member of a role: in a disaggregated
    fleet, draining the last prefill (or decode) replica would strand
    the other tier with committed work and no peer."""
    if count <= 0:
        return ()
    rows = [
        r for r in signals.replicas
        if role is None or r.role == role
    ]
    rows.sort(key=lambda r: (r.occupancy, r.queue_depth, r.url))
    live_roles: Dict[str, int] = {}
    for r in signals.replicas:
        live_roles[r.role] = live_roles.get(r.role, 0) + 1
    victims: List[str] = []
    for r in rows:
        if len(victims) >= count:
            break
        # "both" replicas are interchangeable; specialized roles must
        # keep one live copy.
        if r.role != "both" and live_roles.get(r.role, 0) <= 1:
            continue
        live_roles[r.role] = live_roles.get(r.role, 0) - 1
        victims.append(r.url)
    return tuple(victims)


def _finite(*values: float) -> bool:
    return all(math.isfinite(v) for v in values)


class Autoscaler:
    """The decision core. Holds only timing state (sustained-signal
    entry times, cooldown stamps, per-replica seq latches); every
    ``plan()`` input and output is pure data. One instance per
    autoscaled fleet (the wiring keys instances by CR)."""

    def __init__(self, policy: Optional[AutoscalePolicy] = None):
        self.policy = policy or AutoscalePolicy()
        # Sustained-signal tracking: monotonic time each condition
        # FIRST became (and stayed) true; None = currently false.
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._rebalance_since: Optional[float] = None
        # Per-direction cooldown stamps.
        self._last_up = float("-inf")
        self._last_down = float("-inf")
        # Poisoned-signal detection: last accepted seq per replica.
        # The fleet aggregator already rejects out-of-order deliveries
        # (with a restart-epoch rule), so a seq that REGRESSES by the
        # time it reaches us means the sensor chain itself is confused.
        self._seq_latch: Dict[str, int] = {}
        self._last_signal_ts = float("-inf")

    # -- health ------------------------------------------------------------

    def _health(
        self, signals: Optional[FleetSignals], targets: ScaleTargets,
        now: float,
    ) -> Optional[str]:
        """None = usable; otherwise the freeze reason. Degradation
        contract: a dead aggregator, an all-silent fleet, or poisoned
        rows must freeze the plan — never shrink a loaded fleet on a
        broken sensor's word."""
        if signals is None:
            return "no_signals"
        if not signals.replicas:
            # No telemetry rows while the fleet is supposed to have
            # replicas = every replica silent (or the aggregator lost
            # them). With targets at zero this is the HEALTHY idle
            # state, not a failure.
            return "empty" if targets.total > 0 else None
        if signals.ts < self._last_signal_ts:
            return "poisoned"
        ages = [r.age_s for r in signals.replicas]
        if targets.total > 0 and all(
            a > self.policy.stale_after_s for a in ages
        ):
            return "stale"
        for r in signals.replicas:
            if not _finite(
                r.queue_depth, r.occupancy, r.kv_free_frac,
                r.transfer_queue, r.shed_rate,
            ):
                return "poisoned"
            if (
                r.queue_depth < 0.0
                or not (0.0 <= r.occupancy <= 1.0 + 1e-6)
                or not (0.0 <= r.kv_free_frac <= 1.0 + 1e-6)
                or r.transfer_queue < 0.0
                or r.shed_rate < 0.0
            ):
                return "poisoned"
            last = self._seq_latch.get(r.url)
            if last is not None and 0 <= r.seq < last:
                return "poisoned"
        return None

    def _latch(self, signals: FleetSignals) -> None:
        self._last_signal_ts = max(self._last_signal_ts, signals.ts)
        latched = set()
        for r in signals.replicas:
            if r.seq >= 0:
                self._seq_latch[r.url] = r.seq
            latched.add(r.url)
        # Replicas that left the fleet free their latch (a scaled-down
        # url reused later starts a fresh epoch).
        for url in list(self._seq_latch):
            if url not in latched:
                del self._seq_latch[url]

    # -- the decision ------------------------------------------------------

    def plan(
        self,
        signals: Optional[FleetSignals],
        targets: ScaleTargets,
        now: Optional[float] = None,
        pending: float = 0.0,
    ) -> ScalePlan:
        """One decision pass. ``pending`` is demand the fleet telemetry
        cannot see because no replica exists to report it: the
        gateway's no-replica/cold-start shed count since the last pass.
        It is the ONLY signal that can scale up from zero."""
        now = time.monotonic() if now is None else now

        reason = self._health(signals, targets, now)
        if reason is not None:
            # Frozen: sustained-signal timers reset (the next healthy
            # sample starts a fresh window — half-stale evidence must
            # not pre-charge a decision).
            self._up_since = self._down_since = None
            self._idle_since = self._rebalance_since = None
            return self._finish(ScalePlan(
                outcome="frozen", reason=reason, targets=targets,
            ))
        if signals is not None:
            self._latch(signals)

        if targets.total == 0:
            return self._finish(self._plan_from_zero(
                targets, now, pending
            ))
        assert signals is not None  # health passed with total > 0
        if targets.disaggregated:
            return self._finish(
                self._plan_disagg(signals, targets, now)
            )
        return self._finish(self._plan_mono(signals, targets, now))

    def _finish(self, plan: ScalePlan) -> ScalePlan:
        METRICS.inc(
            "substratus_autoscale_decisions_total",
            {"outcome": plan.outcome},
        )
        t = plan.targets
        if t.disaggregated:
            METRICS.set(
                "substratus_autoscale_target_replicas", t.prefill,
                {"tier": "prefill"},
            )
            METRICS.set(
                "substratus_autoscale_target_replicas", t.decode,
                {"tier": "decode"},
            )
        else:
            METRICS.set(
                "substratus_autoscale_target_replicas", t.replicas,
                {"tier": "replicas"},
            )
        return plan

    def _snap(self) -> Optional[SliceShape]:
        pol = self.policy
        if pol.tpu_generation and pol.chips_per_replica > 0:
            return snap_slice(pol.tpu_generation, pol.chips_per_replica)
        return None

    def _plan_from_zero(
        self, targets: ScaleTargets, now: float, pending: float
    ) -> ScalePlan:
        """Scale-to-zero's other half: the fleet is (deliberately) at
        zero; only gateway-observed demand can wake it."""
        pol = self.policy
        if pending <= 0.0:
            return ScalePlan(
                outcome="held", reason="at_zero_no_demand",
                targets=targets,
            )
        if now - self._last_up < pol.up_cooldown_s:
            return ScalePlan(
                outcome="held", reason="up_cooldown", targets=targets,
            )
        self._last_up = now
        self._idle_since = None
        step = min(
            pol.max_step_up,
            max(pol.min_replicas, 1, math.ceil(
                pending / max(1.0, pol.up_queue_per_replica)
            )),
        )
        step = min(step, pol.max_replicas)
        new = (
            replace(targets, prefill=max(1, step - 1), decode=1)
            if targets.disaggregated else replace(targets, replicas=step)
        )
        return ScalePlan(
            outcome="applied", reason="cold_start_demand",
            targets=new, eta_s=pol.cold_start_eta_s, slice=self._snap(),
        )

    # -- monolithic fleet --------------------------------------------------

    def _up_pressure(
        self, signals: FleetSignals, n: int
    ) -> Optional[str]:
        pol = self.policy
        if signals.queue_depth / max(1, n) >= pol.up_queue_per_replica:
            return "queue_depth"
        if signals.occupancy >= pol.up_occupancy:
            return "occupancy"
        if signals.shed_rate >= pol.up_shed_rate:
            return "shed_rate"
        if signals.kv_free_frac <= pol.kv_free_floor:
            return "kv_pressure"
        return None

    def _down_evidence(self, signals: FleetSignals, n: int) -> bool:
        pol = self.policy
        return (
            signals.occupancy <= pol.down_occupancy
            and signals.queue_depth / max(1, n)
            <= pol.down_queue_per_replica
            and signals.shed_rate <= 0.0
        )

    def _fully_idle(self, signals: FleetSignals) -> bool:
        return (
            signals.queue_depth <= 0.0
            and signals.occupancy <= 0.01
            and signals.shed_rate <= 0.0
            and signals.transfer_queue <= 0.0
        )

    def _plan_mono(
        self, signals: FleetSignals, targets: ScaleTargets, now: float
    ) -> ScalePlan:
        pol = self.policy
        n = targets.replicas

        up_reason = self._up_pressure(signals, n)
        if up_reason is not None:
            if self._up_since is None:
                self._up_since = now
        else:
            self._up_since = None
        if self._down_evidence(signals, n):
            if self._down_since is None:
                self._down_since = now
        else:
            self._down_since = None
        if self._fully_idle(signals):
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None

        # Scale up: sustained pressure + cooldown + bounded step.
        if (
            up_reason is not None
            and self._up_since is not None
            and now - self._up_since >= pol.sustain_up_s
            and now - self._last_up >= pol.up_cooldown_s
            and n < pol.max_replicas
        ):
            want = n + 1
            if up_reason == "queue_depth":
                # Deep backlogs may take a bigger (still bounded) step.
                want = n + min(
                    pol.max_step_up,
                    max(1, math.ceil(
                        signals.queue_depth
                        / max(1e-9, pol.up_queue_per_replica * n)
                    ) - 1),
                )
            new_n = min(pol.max_replicas, max(want, n + 1))
            new_n = min(new_n, n + pol.max_step_up)
            self._last_up = now
            self._up_since = None
            return ScalePlan(
                outcome="applied", reason=f"up_{up_reason}",
                targets=replace(targets, replicas=new_n),
                slice=self._snap(),
            )

        # Scale to zero: fully idle long enough (opt-in), everything
        # drains.
        if (
            pol.scale_to_zero
            and self._idle_since is not None
            and now - self._idle_since >= pol.idle_zero_s
            and now - self._last_down >= pol.down_cooldown_s
            and now - self._last_up >= pol.down_cooldown_s
        ):
            self._last_down = now
            self._idle_since = None
            self._down_since = None
            return ScalePlan(
                outcome="applied", reason="scale_to_zero",
                targets=replace(targets, replicas=0),
                victims=pick_victims(signals, n),
            )

        # Scale down: sustained idleness evidence + both-direction
        # cooldown (a replica the last decision just added gets
        # down_cooldown_s to absorb load before it can be judged).
        floor = pol.min_replicas if not pol.scale_to_zero else max(
            pol.min_replicas, 1
        )
        if (
            self._down_since is not None
            and now - self._down_since >= pol.sustain_down_s
            and now - self._last_down >= pol.down_cooldown_s
            and now - self._last_up >= pol.down_cooldown_s
            and n > floor
        ):
            new_n = max(floor, n - pol.max_step_down)
            self._last_down = now
            self._down_since = None
            return ScalePlan(
                outcome="applied", reason="down_idle",
                targets=replace(targets, replicas=new_n),
                victims=pick_victims(signals, n - new_n),
                slice=self._snap(),
            )

        return ScalePlan(outcome="held", reason="in_band", targets=targets)

    # -- disaggregated fleet ----------------------------------------------

    def _tier_rows(
        self, signals: FleetSignals, role: str
    ) -> List[ReplicaSignals]:
        return [r for r in signals.replicas if r.role == role]

    def _plan_disagg(
        self, signals: FleetSignals, targets: ScaleTargets, now: float
    ) -> ScalePlan:
        """Two-tier sizing. The prefill tier scales on admission
        pressure (queue depth lives there — completions route to
        prefill, balancer.pick(role=)); the decode tier scales on the
        transfer-queue backlog (a handoff waiting to ship IS a decode
        slot shortage, serve/disagg.py). Tiers never scale below one
        replica: the peer tier's committed work needs a live copy of
        each role (scale-to-zero is a monolithic-fleet feature)."""
        pol = self.policy
        prefill = self._tier_rows(signals, "prefill")
        decode = self._tier_rows(signals, "decode")
        n_p, n_d = targets.prefill, targets.decode

        p_queue = sum(r.queue_depth for r in prefill)
        p_occ = (
            sum(r.occupancy for r in prefill) / len(prefill)
            if prefill else 0.0
        )
        d_occ = (
            sum(r.occupancy for r in decode) / len(decode)
            if decode else 0.0
        )
        tq = signals.transfer_queue

        up_p = p_queue / max(1, n_p) >= pol.up_queue_per_replica or (
            p_occ >= pol.up_occupancy
        )
        up_d = tq / max(1, n_d) >= pol.transfer_queue_per_decode or (
            d_occ >= pol.up_occupancy
        )
        if up_p or up_d:
            if self._up_since is None:
                self._up_since = now
        else:
            self._up_since = None
        down_ok = (
            p_occ <= pol.down_occupancy
            and d_occ <= pol.down_occupancy
            and p_queue / max(1, n_p) <= pol.down_queue_per_replica
            and tq <= 0.0
            and signals.shed_rate <= 0.0
        )
        if down_ok:
            if self._down_since is None:
                self._down_since = now
        else:
            self._down_since = None

        if (
            (up_p or up_d)
            and self._up_since is not None
            and now - self._up_since >= pol.sustain_up_s
            and now - self._last_up >= pol.up_cooldown_s
            and n_p + n_d < pol.max_replicas
        ):
            budget = min(
                pol.max_step_up, pol.max_replicas - (n_p + n_d)
            )
            add_d = 1 if up_d and budget > 0 else 0
            add_p = 1 if up_p and budget - add_d > 0 else 0
            if add_p + add_d > 0:
                self._last_up = now
                self._up_since = None
                return ScalePlan(
                    outcome="applied",
                    reason="up_transfer_queue" if up_d else "up_queue_depth",
                    targets=replace(
                        targets, prefill=n_p + add_p, decode=n_d + add_d
                    ),
                    slice=self._snap(),
                )

        if (
            self._down_since is not None
            and now - self._down_since >= pol.sustain_down_s
            and now - self._last_down >= pol.down_cooldown_s
            and now - self._last_up >= pol.down_cooldown_s
            and n_p + n_d > max(2, pol.min_replicas)
        ):
            # Shrink the idler tier (one step), never below one each.
            shrink_decode = d_occ <= p_occ and n_d > 1
            if not shrink_decode and n_p <= 1:
                shrink_decode = n_d > 1
            if shrink_decode and n_d > 1:
                new = replace(targets, decode=n_d - 1)
                victims = pick_victims(signals, 1, role="decode")
            elif n_p > 1:
                new = replace(targets, prefill=n_p - 1)
                victims = pick_victims(signals, 1, role="prefill")
            else:
                return ScalePlan(
                    outcome="held", reason="tier_floor", targets=targets
                )
            self._last_down = now
            self._down_since = None
            return ScalePlan(
                outcome="applied", reason="down_idle", targets=new,
                victims=victims, slice=self._snap(),
            )

        return ScalePlan(outcome="held", reason="in_band", targets=targets)


# ---------------------------------------------------------------------------
# k8s wiring


def targets_from_params(params: Mapping) -> ScaleTargets:
    """Server CR params -> current declared targets (the same fields
    _reconcile_server/_reconcile_disaggregated read)."""
    disagg = params.get("disaggregated")
    if disagg:
        counts = disagg if isinstance(disagg, Mapping) else {}
        return ScaleTargets(
            replicas=0,
            prefill=max(1, int(counts.get("prefill", 1))),
            decode=max(1, int(counts.get("decode", 1))),
        )
    return ScaleTargets(replicas=int(params.get("replicas", 1)))


def params_patch(plan: ScalePlan, params: Mapping) -> Dict:
    """The params mutation a plan implies — returned as a fresh dict so
    the caller patches a freshly-read CR (optimistic concurrency)."""
    out = dict(params)
    t = plan.targets
    if t.disaggregated:
        out["disaggregated"] = {"prefill": t.prefill, "decode": t.decode}
    else:
        out["replicas"] = t.replicas
    return out


class ServerAutoscaler:
    """Server reconciler closing the loop: fleet telemetry in, params
    patch out. Registered AFTER ServerReconciler (controller/
    manager_main.py) so a patched spec re-enqueues the deploy pass.

    ``fetch`` is injectable for tests; the default GETs the gateway's
    ``/debug/fleetz`` through the front Service (the controller runs
    in-cluster) and parses it with ``signals_from_snapshot``. Any fetch
    or parse failure is a dead/poisoned sensor: the core freezes and
    the CR keeps its current size."""

    def __init__(self, client, fetch=None, interval_s: float = 10.0):
        self.client = client
        self.fetch = fetch or self._fetch_fleetz
        self.interval_s = interval_s
        self._cores: Dict[Tuple[str, str], Autoscaler] = {}
        self._pending: Dict[Tuple[str, str], float] = {}

    @staticmethod
    def _fetch_fleetz(obj) -> Optional[Mapping]:
        import http.client
        import json as _json
        import urllib.request

        md = obj["metadata"]
        url = (
            f"http://{md['name']}-server.{md['namespace']}"
            ".svc.cluster.local:8080/debug/fleetz"
        )
        try:
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                return _json.loads(resp.read().decode())
        except (OSError, http.client.HTTPException, ValueError):
            # URLError/timeouts/refused are OSError; garbled JSON is
            # ValueError. Every flavor is the same dead-sensor outcome:
            # None -> the core freezes at last-known-good.
            return None

    def __call__(self, obj):
        from substratus_tpu.controller.runtime import Result
        from substratus_tpu.observability.events import EVENTS

        spec = obj.get("spec") or {}
        params = spec.get("params") or {}
        auto = params.get("autoscale")
        if not isinstance(auto, Mapping):
            return Result()
        # Flavors the reconciler cannot resize are skipped loudly once.
        if params.get("batchGenerate") or params.get("baseModel"):
            return Result()

        md = obj["metadata"]
        key = (md["namespace"], md["name"])
        core = self._cores.get(key)
        try:
            policy = policy_from_params(auto)
        except ValueError as e:
            EVENTS.emit(
                "AutoscaleInvalidPolicy", kind="Server",
                namespace=md["namespace"], name=md["name"],
                message=str(e), type="Warning",
            )
            return Result()
        if core is None:
            core = self._cores[key] = Autoscaler(policy)
        else:
            core.policy = policy  # CR edits apply next pass

        payload = self.fetch(obj)
        signals = None
        if payload is not None:
            try:
                signals = signals_from_snapshot(payload)
            except (ValueError, TypeError):
                signals = None  # poisoned payload = dead sensor

        targets = targets_from_params(params)
        plan = core.plan(
            signals, targets, pending=self._pending.pop(key, 0.0)
        )
        if plan.outcome == "frozen":
            EVENTS.emit(
                "AutoscaleFrozen", kind="Server",
                namespace=md["namespace"], name=md["name"],
                message=plan.reason, type="Warning",
            )
        elif plan.outcome == "applied":
            fresh = self.client.get("Server", md["namespace"], md["name"])
            fresh_params = (fresh.get("spec") or {}).get("params") or {}
            fresh["spec"]["params"] = params_patch(plan, fresh_params)
            self.client.update(fresh)
            EVENTS.emit(
                "AutoscaleApplied", kind="Server",
                namespace=md["namespace"], name=md["name"],
                message=(
                    f"{plan.reason}: replicas "
                    f"{targets.replicas}->{plan.targets.replicas}"
                    if not plan.targets.disaggregated else
                    f"{plan.reason}: prefill {targets.prefill}->"
                    f"{plan.targets.prefill} decode {targets.decode}->"
                    f"{plan.targets.decode}"
                ),
            )
            log.info(
                "autoscale %s/%s %s: %s -> %s (victims=%s)",
                md["namespace"], md["name"], plan.reason, targets,
                plan.targets, plan.victims,
            )
        return Result(requeue_after=self.interval_s)

    def note_pending(self, namespace: str, name: str, n: float) -> None:
        """Record gateway-observed demand for a scaled-to-zero Server
        (no replica exists to report it); consumed by the next pass."""
        key = (namespace, name)
        self._pending[key] = self._pending.get(key, 0.0) + n
