"""Zero-downtime rolling weight-swap across a Server's replica fleet.

A new checkpoint ref on a Server CR used to mean drain-and-restart:
tear each engine down, recompile every program, re-warm every cache.
`Engine.swap_params` (serve/engine.py) removes the reason — shapes
unchanged means the compiled prefill/decode/verify programs survive a
weight swap in place — so rollout becomes a *data-plane* operation:

  1. discover the replica fleet from the gateway's ``/debug/fleetz``
     (replicas are keyed by base URL — the same passive-telemetry
     aggregation the autoscaler reads);
  2. one replica at a time, fleet-health-gated: before touching a
     replica, every OTHER replica must answer ``/loadz`` 200, so a
     mid-rollout fleet always has healthy capacity taking traffic;
  3. ``POST /swapz`` with ``source="rollout"`` (the replica loads the
     checkpoint and installs it via swap_params — in-flight streams
     keep decoding across the boundary);
  4. verify by polling ``/loadz`` until the replica reports the target
     ``weights_version``, then move on.

Any failure aborts the rollout where it stands (already-swapped
replicas keep the new weights — the two versions are by construction
the same architecture, and a half-rolled fleet serving mixed versions
beats a rollback storm; the controller retries the remainder next
reconcile pass).

Two entry points share the coordinator: the ``ServerRollout``
reconciler below (watches ``spec.params.model`` changes, registered in
controller/manager_main.py) and the ``sub rollout`` CLI
(cli/commands.py) for operator-driven rollouts against an explicit
replica list.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from substratus_tpu.observability.metrics import METRICS

log = logging.getLogger(__name__)

METRICS.describe(
    "substratus_rollout_swaps_total",
    "Per-replica rolling weight-swaps by outcome "
    "(applied|swap_failed|verify_failed|health_gated).",
    type="counter",
)
METRICS.describe(
    "substratus_rollout_runs_total",
    "Rolling-swap runs by outcome (complete|aborted).",
    type="counter",
)


def _default_fetch(url: str, token: Optional[str] = None
                   ) -> Tuple[int, Optional[dict]]:
    """GET a JSON endpoint -> (status, body|None). Network failures are
    status 0: the caller treats them like any other non-200."""
    import http.client
    import urllib.error
    import urllib.request

    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, None
    except (OSError, http.client.HTTPException, ValueError):
        return 0, None


def _default_post(url: str, body: Mapping, token: Optional[str] = None
                  ) -> Tuple[int, Optional[dict]]:
    """POST JSON -> (status, body|None); same failure contract as
    _default_fetch. The timeout is generous: /swapz holds the
    connection through checkpoint load + the swap barrier."""
    import http.client
    import urllib.error
    import urllib.request

    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        url, data=json.dumps(dict(body)).encode(), headers=headers,
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300.0) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, None
    except (OSError, http.client.HTTPException, ValueError):
        return 0, None


class RolloutCoordinator:
    """One-replica-at-a-time rolling swap with a fleet-health gate.

    ``fetch``/``post`` are injectable for tests (and reused by the CLI
    with a bearer token bound in); ``sleep`` likewise so verify-polling
    is instant under test clocks."""

    def __init__(
        self,
        fetch: Callable[[str], Tuple[int, Optional[dict]]] = None,
        post: Callable[[str, Mapping], Tuple[int, Optional[dict]]] = None,
        poll_s: float = 0.5,
        verify_timeout_s: float = 60.0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.fetch = fetch or _default_fetch
        self.post = post or _default_post
        self.poll_s = poll_s
        self.verify_timeout_s = verify_timeout_s
        self.sleep = sleep

    def _healthy(self, url: str) -> bool:
        status, _ = self.fetch(f"{url.rstrip('/')}/loadz")
        return status == 200

    def run(
        self,
        replicas: List[str],
        checkpoint: str,
        version: Optional[int] = None,
    ) -> dict:
        """Roll `checkpoint` across `replicas`. Returns a result dict:
        {ok, version, swapped: [url], failed: url|None, reason}."""
        swapped: List[str] = []
        target = version

        def abort(url: str, outcome: str, reason: str) -> dict:
            METRICS.inc(
                "substratus_rollout_swaps_total", {"outcome": outcome}
            )
            METRICS.inc(
                "substratus_rollout_runs_total", {"outcome": "aborted"}
            )
            log.warning("rollout aborted at %s: %s", url, reason)
            return {
                "ok": False, "version": target, "swapped": swapped,
                "failed": url, "reason": reason,
            }

        for url in replicas:
            base = url.rstrip("/")
            # Fleet-health gate: the rest of the fleet must be taking
            # traffic before this replica is touched — a rollout never
            # narrows healthy capacity below fleet-minus-one.
            sick = [
                o for o in replicas if o != url and not self._healthy(o)
            ]
            if sick:
                return abort(
                    url, "health_gated",
                    f"unhealthy peers {sick}: pausing the rollout",
                )
            status, body = self.post(
                f"{base}/swapz",
                {
                    "checkpoint": checkpoint,
                    "version": target,
                    "source": "rollout",
                },
            )
            if status != 200 or not isinstance(body, dict):
                return abort(
                    url, "swap_failed", f"/swapz answered {status}"
                )
            applied = int(body.get("weights_version", 0))
            if target is None:
                # First replica names the generation; the rest converge
                # on it so the fleet lands on ONE version.
                target = applied
            # Verify: the replica must report the target generation on
            # /loadz before the rollout advances past it.
            deadline = time.monotonic() + self.verify_timeout_s
            while True:
                s, snap = self.fetch(f"{base}/loadz")
                if (
                    s == 200
                    and isinstance(snap, dict)
                    and int(snap.get("weights_version", 0)) == target
                ):
                    break
                if time.monotonic() > deadline:
                    return abort(
                        url, "verify_failed",
                        f"/loadz never reported weights_version={target}",
                    )
                self.sleep(self.poll_s)
            METRICS.inc(
                "substratus_rollout_swaps_total", {"outcome": "applied"}
            )
            swapped.append(url)
            log.info(
                "rolled %s to %s (weights_version=%s)",
                url, checkpoint, target,
            )
        METRICS.inc(
            "substratus_rollout_runs_total", {"outcome": "complete"}
        )
        return {
            "ok": True, "version": target, "swapped": swapped,
            "failed": None, "reason": None,
        }


class ServerRollout:
    """Server reconciler: a changed checkpoint ref rolls `swap` across
    the live fleet instead of waiting for pod churn. Registered AFTER
    ServerAutoscaler (controller/manager_main.py) — same CR, disjoint
    fields.

    The first observation of a Server records its ref as the baseline
    (those replicas booted with it; nothing to roll). A later edit to
    ``spec.params.model`` triggers: discover replica URLs from the
    gateway's ``/debug/fleetz``, run the coordinator, emit events. An
    aborted rollout keeps the OLD ref as last-seen so the next pass
    retries the remainder (swap_params is idempotent for replicas
    already on the target version — same weights, one more flush)."""

    def __init__(self, client, fetch=None, coordinator=None,
                 interval_s: float = 10.0):
        self.client = client
        self.fetch = fetch or self._fetch_fleetz
        self.coordinator = coordinator or RolloutCoordinator()
        self.interval_s = interval_s
        self._seen: Dict[Tuple[str, str], str] = {}

    @staticmethod
    def _fetch_fleetz(obj) -> Optional[Mapping]:
        md = obj["metadata"]
        status, body = _default_fetch(
            f"http://{md['name']}-server.{md['namespace']}"
            ".svc.cluster.local:8080/debug/fleetz"
        )
        return body if status == 200 else None

    def __call__(self, obj):
        from substratus_tpu.controller.runtime import Result
        from substratus_tpu.observability.events import EVENTS

        spec = obj.get("spec") or {}
        params = spec.get("params") or {}
        ref = params.get("model")
        # Batch jobs restart per run and weightless smoke servers have
        # no checkpoint ref: nothing to roll on either.
        if not ref or params.get("batchGenerate"):
            return Result()
        md = obj["metadata"]
        key = (md["namespace"], md["name"])
        last = self._seen.get(key)
        if last is None:
            self._seen[key] = str(ref)
            return Result(requeue_after=self.interval_s)
        if str(ref) == last:
            return Result(requeue_after=self.interval_s)

        payload = self.fetch(obj)
        replicas = sorted((payload or {}).get("replicas") or {})
        if not replicas:
            # No telemetry yet (gateway warming, fleet scaled to zero):
            # hold the old baseline and retry next pass.
            EVENTS.emit(
                "RolloutPending", kind="Server",
                namespace=md["namespace"], name=md["name"],
                message=f"no replicas visible on fleetz for {ref}",
                type="Warning",
            )
            return Result(requeue_after=self.interval_s)
        EVENTS.emit(
            "RolloutStarted", kind="Server",
            namespace=md["namespace"], name=md["name"],
            message=f"rolling {len(replicas)} replicas {last} -> {ref}",
        )
        res = self.coordinator.run(replicas, str(ref))
        if res["ok"]:
            self._seen[key] = str(ref)
            EVENTS.emit(
                "RolloutComplete", kind="Server",
                namespace=md["namespace"], name=md["name"],
                message=(
                    f"{len(res['swapped'])} replicas on "
                    f"weights_version={res['version']}"
                ),
            )
        else:
            EVENTS.emit(
                "RolloutAborted", kind="Server",
                namespace=md["namespace"], name=md["name"],
                message=f"{res['failed']}: {res['reason']}",
                type="Warning",
            )
        return Result(requeue_after=self.interval_s)
