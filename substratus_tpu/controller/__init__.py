from substratus_tpu.controller.runtime import Manager, Result

__all__ = ["Manager", "Result"]
