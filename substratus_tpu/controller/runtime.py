"""Controller runtime: watch-driven reconcile loop with dependency indexes.

The reference leans on controller-runtime (manager, workqueue, field indexes
— internal/controller/manager.go:14-72, cmd/controllermanager/main.go). This
is the same model rebuilt small:

  * every apiserver event enqueues the object's own reconciler (if any),
    its owner CR (ownerReferences walk — how Job/Pod status changes wake the
    CR that created them), and any CRs whose spec references the changed
    object (the `spec.model.name` / `spec.dataset.name` indexes that drive
    dependent wakeup, reference manager.go:23-72);
  * a deduplicating FIFO workqueue; reconcilers are idempotent and read
    fresh state every pass;
  * `run_until_idle()` drains the queue synchronously — the deterministic
    test mode (no Eventually-polling, unlike envtest) — while `start()` runs
    the same loop on a thread for real deployments.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from substratus_tpu.kube.client import Conflict, KubeClient, NotFound, Obj
from substratus_tpu.observability.events import EVENTS
from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.observability.tracing import tracer

log = logging.getLogger("substratus.controller")

CR_KINDS = ("Dataset", "Model", "Notebook", "Server")

# Reconcile instrumentation on the shared registry — the controller-runtime
# metric names the reference's ServiceMonitor dashboards already query,
# labeled by CR kind (docs/observability.md).
METRICS.describe(
    "substratus_reconcile_total",
    "Reconcile passes started, by CR kind.", type="counter",
)
METRICS.describe(
    "substratus_reconcile_errors_total",
    "Reconcile passes that raised (requeued with backoff), by CR kind.",
    type="counter",
)
METRICS.describe(
    "substratus_reconcile_conflicts_total",
    "Reconcile passes aborted on an optimistic-concurrency conflict.",
    type="counter",
)
METRICS.describe(
    "substratus_workqueue_adds_total",
    "Items enqueued onto the reconcile workqueue (post-dedup).",
    type="counter",
)
METRICS.describe(
    "substratus_workqueue_depth",
    "Reconcile workqueue depth.", type="gauge",
)
METRICS.histogram(
    "substratus_reconcile_seconds",
    "Wall time of one reconcile pass (all reconcilers for the object).",
)


@dataclass
class Result:
    requeue_after: Optional[float] = None  # seconds; None = wait for events


Reconciler = Callable[[Obj], Result]


class Manager:
    def __init__(self, client: KubeClient):
        self.client = client
        self.reconcilers: Dict[str, List[Reconciler]] = {}
        self._queue: deque = deque()
        self._queued: set = set()
        self._delayed: List[Tuple[float, tuple]] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        client.add_listener(self._on_event)
        # Controller event stream: reconcile transitions emitted through
        # the shared recorder ALSO land as core/v1 Event objects on this
        # client (`sub events` / kubectl get events). Event writes fan
        # out to listeners but never enqueue work: Event is not a
        # reconciled kind and carries no ownerReferences.
        EVENTS.attach_kube(client)

    def register(self, kind: str, reconciler: Reconciler) -> None:
        self.reconcilers.setdefault(kind, []).append(reconciler)

    # -- event routing -----------------------------------------------------

    def enqueue(self, kind: str, namespace: str, name: str) -> None:
        item = (kind, namespace, name)
        with self._lock:
            if item not in self._queued:
                self._queued.add(item)
                self._queue.append(item)
                METRICS.inc("substratus_workqueue_adds_total")
                METRICS.set("substratus_workqueue_depth", len(self._queue))
        self._wake.set()

    def _on_event(self, event: str, obj: Obj) -> None:
        kind = obj.get("kind")
        md = obj.get("metadata", {})
        ns, name = md.get("namespace", "default"), md.get("name")

        if kind in self.reconcilers:
            self.enqueue(kind, ns, name)

        # Owner wakeup: Job/Pod/Deployment/JobSet status changes requeue the
        # CR that owns them.
        for ref in md.get("ownerReferences", []):
            if ref.get("kind") in self.reconcilers:
                self.enqueue(ref["kind"], ns, ref["name"])

        # Gang-pod wakeup: a JobSet worker pod is two ownership hops from
        # its CR (Pod -> Job -> JobSet -> CR), so readiness transitions
        # would never requeue the CR through ownerReferences alone. The
        # JobSet controller labels every pod with its gang; route the
        # event to the JobSet's owners (the multi-host Server tracks its
        # leader pod's Ready condition this way).
        if kind == "Pod":
            gang = (md.get("labels") or {}).get(
                "jobset.sigs.k8s.io/jobset-name"
            )
            if gang:
                try:
                    js = self.client.get("JobSet", ns, gang)
                except NotFound:
                    js = None
                if js is not None:
                    for ref in js["metadata"].get("ownerReferences", []):
                        if ref.get("kind") in self.reconcilers:
                            self.enqueue(ref["kind"], ns, ref["name"])

        # Reference-index wakeup (reference manager.go:23-72): when a Model
        # or Dataset changes, requeue CRs whose spec points at it.
        if kind in ("Model", "Dataset"):
            field = "model" if kind == "Model" else "dataset"
            for dep_kind in ("Model", "Notebook", "Server"):
                if dep_kind not in self.reconcilers:
                    continue
                for dep in self.client.list(dep_kind, ns):
                    ref = (dep.get("spec") or {}).get(field) or {}
                    if ref.get("name") == name and (
                        ref.get("namespace") or ns
                    ) == ns:
                        dmd = dep["metadata"]
                        self.enqueue(dep_kind, dmd["namespace"], dmd["name"])

    # -- loop --------------------------------------------------------------

    def _pop(self) -> Optional[tuple]:
        with self._lock:
            now = time.monotonic()
            ready = [i for i, (t, _) in enumerate(self._delayed) if t <= now]
            for i in reversed(ready):
                _, item = self._delayed.pop(i)
                if item not in self._queued:
                    self._queued.add(item)
                    self._queue.append(item)
            if not self._queue:
                return None
            item = self._queue.popleft()
            self._queued.discard(item)
            return item

    def _process(self, item: tuple) -> None:
        kind, ns, name = item
        METRICS.inc("substratus_reconcile_total", {"kind": kind})
        t0 = time.perf_counter()
        with tracer.span(
            "controller.reconcile", kind=kind, namespace=ns, object=name
        ) as span:
            try:
                self._reconcile(item, span)
            finally:
                METRICS.observe(
                    "substratus_reconcile_seconds",
                    time.perf_counter() - t0,
                    {"kind": kind},
                )

    def _reconcile(self, item: tuple, span) -> None:
        kind, ns, name = item
        try:
            obj = self.client.get(kind, ns, name)
        except NotFound:
            span.set_attribute("outcome", "gone")
            return  # deleted; nothing to do (GC is ownerRef-driven)
        for rec in self.reconcilers.get(kind, []):
            try:
                result = rec(obj)
            except Conflict:
                # Optimistic-concurrency race: someone wrote between our read
                # and write. Requeue and re-read.
                METRICS.inc(
                    "substratus_reconcile_conflicts_total", {"kind": kind}
                )
                span.set_attribute("outcome", "conflict")
                EVENTS.emit(
                    "ReconcileConflict", kind=kind, namespace=ns, name=name,
                    message="optimistic-concurrency conflict; requeued",
                )
                self.enqueue(kind, ns, name)
                return
            except NotFound:
                span.set_attribute("outcome", "gone")
                return
            except Exception as e:  # sublint: allow[broad-except]: one bad reconcile must not kill the manager; counted, evented, and logged
                log.exception("reconcile %s %s/%s failed", kind, ns, name)
                METRICS.inc(
                    "substratus_reconcile_errors_total", {"kind": kind}
                )
                span.set_attribute("outcome", "error")
                # Exception TYPE only: the message could carry unbounded
                # cardinality and would defeat the recorder's dedup.
                EVENTS.emit(
                    "ReconcileError", kind=kind, namespace=ns, name=name,
                    message=type(e).__name__, type="Warning",
                )
                with self._lock:
                    self._delayed.append((time.monotonic() + 5.0, item))
                return
            if result and result.requeue_after is not None:
                span.set_attribute("outcome", "requeued")
                with self._lock:
                    self._delayed.append(
                        (time.monotonic() + result.requeue_after, item)
                    )
                return
            # Re-read: a later reconciler in the chain must see the writes of
            # an earlier one.
            try:
                obj = self.client.get(kind, ns, name)
            except NotFound:
                span.set_attribute("outcome", "gone")
                return

    def run_until_idle(self, max_iterations: int = 10_000) -> None:
        """Drain the queue synchronously (test/deterministic mode)."""
        for _ in range(max_iterations):
            item = self._pop()
            if item is None:
                return
            self._process(item)
        raise RuntimeError("reconcile queue did not quiesce")

    def start(self) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                item = self._pop()
                if item is None:
                    self._wake.wait(timeout=0.2)
                    self._wake.clear()
                    continue
                self._process(item)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    def bootstrap(self) -> None:
        """Enqueue every existing CR (controller restart catch-up)."""
        for kind in self.reconcilers:
            for obj in self.client.list(kind):
                md = obj["metadata"]
                self.enqueue(kind, md["namespace"], md["name"])
