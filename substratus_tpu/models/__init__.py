from substratus_tpu.models import llama
from substratus_tpu.models.llama import LlamaConfig

__all__ = ["llama", "LlamaConfig"]
