"""Llama model family (Llama 2/3, TinyLlama, and shape-compatible configs).

Flagship compute path of the framework. The reference operator ran Llama via
external CUDA images (examples/llama2-7b/*.yaml -> substratusai/model-*
images, SURVEY.md §2.2); here the model is in-repo, TPU-first:

  * params are plain pytrees with per-layer weights STACKED on a leading
    `layers` axis and the block applied via `lax.scan` — compile time is O(1)
    in depth and XLA sees one fused block;
  * every array carries a logical-axis annotation (parallel/sharding.py), so
    dp/fsdp/tp/sp strategies are rules-table changes, not model edits;
  * matmuls run in bfloat16 on the MXU with float32 softmax/norm accumulation;
  * weights may be int8-quantized per-channel (ops/quant.py) — decode is
    HBM-bandwidth-bound, so int8 weights nearly double decode throughput;
  * RoPE follows the HF rotate-half convention so HF checkpoints convert
    without permutation (load/hf.py).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from substratus_tpu.ops.attention import dot_product_attention
from substratus_tpu.ops.basics import (
    lora_delta,
    lora_delta_indexed,
    rms_norm,
    rope,
    swiglu,
)
from substratus_tpu.ops.quant import materialize, qeinsum, qeinsum_w8a8
from substratus_tpu.utils import jaxcompat

Params = Dict[str, Any]

# The engine may store this family's KV cache int8-quantized (init_cache).
SUPPORTS_INT8_KV = True
# train/lora.py adapters are implemented for this family's projections.
SUPPORTS_LORA = True
# forward() accepts slot-stacked adapter trees + a per-row adapter_ids
# gather — multi-tenant adapter serving (serve/adapters.py).
SUPPORTS_INDEXED_LORA = True


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    head_dim: Optional[int] = None
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 4096
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # Self-attention (no-cache path) implementation:
    #   "xla"   — einsum + masked softmax (always correct; CPU tests)
    #   "flash" — Pallas blockwise kernel (ops/flash_attention.py, TPU)
    #   "ring"  — sequence-parallel ring attention (ops/ring_attention.py);
    #             requires an ambient mesh (jax.sharding.use_mesh) with a
    #             "sequence" axis
    attn_impl: str = "xla"
    # Decode-with-cache attention implementation (ops/decode_attention.py):
    #   "xla"    — scale-after-dot einsums (default; also fastest measured)
    #   "pallas" — fused int8-dequant flash-decode Mosaic kernel
    decode_attn_impl: str = "xla"
    # Multi-token cached attention (chunked prefill / speculative verify):
    #   "xla"   — dequantize cache + reference attention (default)
    #   "flash" — blockwise Pallas kernel (ops/flash_attention.py::
    #             flash_cached_attention); opt-in via params.json until
    #             its Mosaic lowering is validated on a chip
    chunk_attn_impl: str = "xla"
    # W8A8: dynamically quantize activations per token so quantized matmuls
    # run in the MXU's native s8xs8 mode (ops/quant.py::qeinsum_w8a8).
    # Opt-in; weight-only int8 (qeinsum) is the default quantized path.
    quant_activations: bool = False
    # Mixture-of-experts (Mixtral family): n_experts == 0 means dense MLP.
    # Routed top-k with GShard-style capacity dispatch; expert weights shard
    # over the "expert" mesh axis (expert parallelism).
    n_experts: int = 0
    n_experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01

    @property
    def head_size(self) -> int:
        return self.head_dim if self.head_dim is not None else self.dim // self.n_heads

    def replace(self, **kw) -> "LlamaConfig":
        return dataclasses.replace(self, **kw)


# Shape-accurate configs for the model sizes the reference's examples exercise
# (examples/llama2-7b, llama2-13b-chat-gguf, llama2-70b) plus test sizes.
CONFIGS: Dict[str, LlamaConfig] = {
    "tiny": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=128, max_seq_len=128, norm_eps=1e-6,
    ),
    "debug-1b": LlamaConfig(
        vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        hidden_dim=5632, max_seq_len=2048,
    ),
    "llama2-7b": LlamaConfig(),
    "llama2-13b": LlamaConfig(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40, hidden_dim=13824),
    "llama2-70b": LlamaConfig(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8, hidden_dim=28672),
    "llama3-8b": LlamaConfig(
        vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        hidden_dim=14336, rope_theta=500000.0, max_seq_len=8192,
    ),
    "tinyllama-1.1b": LlamaConfig(
        vocab_size=32000, dim=2048, n_layers=22, n_heads=32, n_kv_heads=4,
        hidden_dim=5632, max_seq_len=2048,
    ),
    "tiny-moe": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=128, max_seq_len=128, norm_eps=1e-6, n_experts=4,
    ),
    "mixtral-8x7b": LlamaConfig(
        vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        hidden_dim=14336, rope_theta=1000000.0, max_seq_len=32768,
        n_experts=8, n_experts_per_token=2,
    ),
}


def param_logical_axes(cfg: LlamaConfig) -> Params:
    """Logical axis names for every param leaf (see parallel/sharding.py)."""
    layers = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "embed"),
    }
    if cfg.n_experts > 0:
        layers.update(
            {
                "router": ("layers", "embed", None),
                "w_gate": ("layers", "expert", "embed", "mlp"),
                "w_up": ("layers", "expert", "embed", "mlp"),
                "w_down": ("layers", "expert", "mlp", "embed"),
            }
        )
    else:
        layers.update(
            {
                "w_gate": ("layers", "embed", "mlp"),
                "w_up": ("layers", "embed", "mlp"),
                "w_down": ("layers", "mlp", "embed"),
            }
        )
    axes = {
        "tok_embed": ("vocab", "embed"),
        "layers": layers,
        "out_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def quant_contracting(cfg: LlamaConfig) -> Params:
    """Contracting dims per leaf for ops.quant.quantize_params; () = dense.

    Axes are for the STACKED layer leaves (leading layer dim from
    init_params), e.g. wq [L, d, h, k] contracts d=1. The resulting scales
    are per-output-channel — the standard quality choice, and what lets
    qeinsum commute the scale out of the dot after lax.scan slices the
    layer dim off (scale-after-dot keeps the int8 bytes on the MXU operand
    path; see ops/quant.py).
    """
    moe = cfg.n_experts > 0
    layers = {
        "attn_norm": (),
        "wq": (1,),
        "wk": (1,),
        "wv": (1,),
        "wo": (1, 2),
        "mlp_norm": (),
        # Expert weights carry a leading expert dim; contracting shifts by 1.
        "w_gate": (2,) if moe else (1,),
        "w_up": (2,) if moe else (1,),
        "w_down": (2,) if moe else (1,),
    }
    if moe:
        layers["router"] = ()
    q = {"tok_embed": (), "layers": layers, "out_norm": ()}
    if not cfg.tie_embeddings:
        q["lm_head"] = (0,)
    return q


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Random init (truncated-normal fan-in scaling), stacked layers."""
    hd = cfg.head_size
    k = iter(jax.random.split(key, 16))

    def dense(key, shape, fan_in):
        return (
            jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
            * (fan_in**-0.5)
        ).astype(cfg.dtype)

    L, D, H, KH, M = cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.hidden_dim
    E = cfg.n_experts
    if E > 0:
        mlp = {
            "router": dense(next(k), (L, D, E), D),
            "w_gate": dense(next(k), (L, E, D, M), D),
            "w_up": dense(next(k), (L, E, D, M), D),
            "w_down": dense(next(k), (L, E, M, D), M),
        }
    else:
        mlp = {
            "w_gate": dense(next(k), (L, D, M), D),
            "w_up": dense(next(k), (L, D, M), D),
            "w_down": dense(next(k), (L, M, D), M),
        }
    params: Params = {
        "tok_embed": dense(next(k), (cfg.vocab_size, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), cfg.dtype),
            "wq": dense(next(k), (L, D, H, hd), D),
            "wk": dense(next(k), (L, D, KH, hd), D),
            "wv": dense(next(k), (L, D, KH, hd), D),
            "wo": dense(next(k), (L, H, hd, D), H * hd),
            "mlp_norm": jnp.ones((L, D), cfg.dtype),
            **mlp,
        },
        "out_norm": jnp.ones((D,), cfg.dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(k), (D, cfg.vocab_size), D)
    return params


def init_cache(
    cfg: LlamaConfig, batch: int, max_len: Optional[int] = None, dtype=None
) -> Params:
    """Decode KV cache, layers-stacked: k/v [L, B, KH, S, head_dim].

    The per-head sequence-contiguous layout (KH before S) makes each kv
    head's history one contiguous HBM stream for the decode-attention
    read (ops/decode_attention.py) — the [B, S, KH, D] activation layout
    would interleave heads every D elements.

    dtype=jnp.int8 stores entries quantized per-vector (ops/quant.py
    quantize_kv) with f32 scales alongside ([L, B, KH, S]) — decode is
    bandwidth-bound on the cache read, so int8 roughly halves its HBM
    traffic.
    """
    S = max_len or cfg.max_seq_len
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, S, cfg.head_size)
    cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if dtype == jnp.int8:
        cache["k_scale"] = jnp.ones(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.ones(shape[:-1], jnp.float32)
    return cache


def cache_logical_axes(cfg: LlamaConfig, quantized: bool = False) -> Params:
    ax = ("layers", "cache_batch", "kv_heads", "cache_seq", "head_dim")
    axes = {"k": ax, "v": ax}
    if quantized:
        axes["k_scale"] = ax[:-1]
        axes["v_scale"] = ax[:-1]
    return axes


# The engine may use a paged (block) KV layout for this family (serve/
# paged_kv.py owns the allocator; ops/kvcache.py owns the device ops).
SUPPORTS_PAGED = True


def init_paged_cache(
    cfg: LlamaConfig, pages: int, page_size: int, dtype=None
) -> Params:
    """Paged decode cache: a global page pool k/v [L, P, bs, KH, head_dim]
    addressed through a per-sequence block table (ops/kvcache.py)."""
    from substratus_tpu.ops import kvcache

    dtype = dtype or cfg.dtype
    return kvcache.init_paged_cache(
        cfg.n_layers, pages, page_size, cfg.n_kv_heads, cfg.head_size,
        dtype, quantized=dtype == jnp.int8,
    )


def paged_cache_logical_axes(cfg: LlamaConfig, quantized: bool = False) -> Params:
    from substratus_tpu.ops import kvcache

    return kvcache.paged_cache_logical_axes(quantized)


def _self_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    positions: jnp.ndarray,
    cfg: LlamaConfig,
) -> jnp.ndarray:
    """No-cache causal attention, dispatched per cfg.attn_impl. The fused
    kernels assume standard positions (row r attends 0..r within the same
    sequence), which holds for training and full prefill."""
    if cfg.attn_impl == "flash":
        from substratus_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, True)
    if cfg.attn_impl in ("ring", "ulysses"):
        from jax.sharding import PartitionSpec as P

        if cfg.attn_impl == "ring":
            from substratus_tpu.ops.ring_attention import ring_attention as fn
        else:
            from substratus_tpu.ops.ulysses_attention import (
                ulysses_attention as fn,
            )

        spec = P(None, "sequence", None, None)
        sharded = jaxcompat.shard_map(
            lambda q, k, v: fn(q, k, v, axis_name="sequence"),
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names={"sequence"},
        )
        return sharded(q, k, v)
    return dot_product_attention(q, k, v, causal=True, q_positions=positions)


def _moe_ffn(
    h: jnp.ndarray,  # [B, S, D] (post-norm)
    lp: Params,
    cfg: LlamaConfig,
    train: bool,
    lora: Optional[Params] = None,  # per-layer adapters (may hold expert-
    # routed pairs a [E, in, r] / b [E, r, out], train/lora.py)
    lora_scale: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Routed top-k expert FFN (Mixtral-style).

    Two execution strategies, same routing:

    * train=True: GShard-style capacity dispatch — dense one-hot dispatch/
      combine einsums keep shapes static, the expert dim shards over the
      "expert" mesh axis (XLA inserts the expert-parallel all-to-alls), and
      tokens beyond an expert's capacity drop (combine weight 0) — the
      standard trade for static shapes at training batch sizes.
    * train=False: exact dropless top-k — every expert computed for every
      token, mixed by routing weights. E/k more FLOPs than dispatch, but
      decode is HBM-bandwidth-bound (all expert weights stream from HBM
      regardless of routing), and exactness makes prefill and cached decode
      consistent — capacity dropping would make them diverge.

    Returns (output [B,S,D], load-balancing aux scalar).
    """
    dt = cfg.dtype
    b, s, d = h.shape
    E, k = cfg.n_experts, cfg.n_experts_per_token
    lora = lora or {}

    qe = qeinsum_w8a8 if cfg.quant_activations else qeinsum

    def eproj(name, x, eq_w, eq_a, eq_b):
        """Per-expert projection with optional expert-routed LoRA delta."""
        out = qe(eq_w, x, lp[name], dt)
        if name in lora:
            down = jnp.einsum(eq_a, x, lora[name]["a"].astype(dt))
            out = out + jnp.einsum(
                eq_b, down, lora[name]["b"].astype(dt)
            ) * lora_scale
        return out

    logits = jnp.einsum(
        "bsd,de->bse", h.astype(jnp.float32),
        materialize(lp["router"], jnp.float32),
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [B,S,E]
    top_w, top_idx = jax.lax.top_k(probs, k)  # [B,S,k]
    top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)  # Mixtral renorm

    # Switch-style load-balancing aux: fraction of tokens routed to each
    # expert (top-1 assignment) x mean router prob, scaled by E.
    assigned = jax.nn.one_hot(top_idx[..., 0], E, dtype=jnp.float32)
    aux = jnp.sum(
        assigned.mean(axis=(0, 1)) * probs.mean(axis=(0, 1))
    ) * E

    if not train:
        # Exact dropless mix: per-token expert weights [B,S,E].
        w_full = jnp.sum(
            jax.nn.one_hot(top_idx, E, dtype=jnp.float32)
            * top_w[..., None],
            axis=2,
        )
        gate = eproj("w_gate", h, "bsd,edm->bsem", "bsd,edr->bser",
                     "bser,erm->bsem")
        up = eproj("w_up", h, "bsd,edm->bsem", "bsd,edr->bser",
                   "bser,erm->bsem")
        out = eproj("w_down", swiglu(gate, up), "bsem,emd->bsed",
                    "bsem,emr->bser", "bser,erd->bsed")
        y = jnp.einsum("bsed,bse->bsd", out, w_full.astype(dt))
        return y.astype(dt), aux

    t = s * k
    capacity = max(1, int(cfg.capacity_factor * s * k / E))
    # Flatten (token, choice) pairs; compute each pair's slot within its
    # expert's capacity buffer.
    onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [B,S,k,E]
    flat = onehot.reshape(b, t, E)
    pos = jnp.cumsum(flat, axis=1) - flat  # arrival order per expert
    keep = (pos < capacity).astype(jnp.float32) * flat  # [B,T,E]
    dispatch = keep[..., None] * jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32
    )  # [B,T,E,C]
    combine = dispatch * top_w.reshape(b, t)[..., None, None]

    h_rep = jnp.repeat(h, k, axis=1)  # [B,T,D] (token order matches flatten)
    expert_in = jnp.einsum(
        "btec,btd->ebcd", dispatch.astype(dt), h_rep
    )  # [E,B,C,D]
    gate = eproj("w_gate", expert_in, "ebcd,edm->ebcm", "ebcd,edr->ebcr",
                 "ebcr,erm->ebcm")
    up = eproj("w_up", expert_in, "ebcd,edm->ebcm", "ebcd,edr->ebcr",
               "ebcr,erm->ebcm")
    out = eproj("w_down", swiglu(gate, up), "ebcm,emd->ebcd",
                "ebcm,emr->ebcr", "ebcr,erd->ebcd")
    y = jnp.einsum("ebcd,btec->btd", out, combine.astype(dt))  # [B,T,D]
    y = y.reshape(b, s, k, d).sum(axis=2)
    return y.astype(dt), aux


def _block(
    x: jnp.ndarray,  # [B, S, D]
    lp: Params,  # single-layer params (leading L axis removed by scan)
    positions: jnp.ndarray,  # [B, S]
    cfg: LlamaConfig,
    layer_cache: Optional[Params],  # per-layer cache dict (k, v, [scales])
    kv_length: Optional[jnp.ndarray] = None,  # [B] valid cache prefix
    lora_layers: Optional[Params] = None,  # single-layer adapter tree
    lora_scale: float = 1.0,
    train: bool = False,
    block_table: Optional[jnp.ndarray] = None,  # [B, M]: paged cache layout
    adapter_ids: Optional[jnp.ndarray] = None,  # [B]: slot-stacked adapters
) -> Tuple[jnp.ndarray, Params, jnp.ndarray]:
    """One transformer block. Returns (x_out, kv_out, aux): kv_out is a dict
    of either the freshly computed seq entries {k, v} (no cache: training /
    prefill) or the updated full cache rows (decode — including k_scale/
    v_scale when the cache is int8-quantized); aux is the MoE
    load-balancing loss (0 for dense layers).

    With adapter_ids, the lora leaves carry a leading adapter-slot axis
    (serve/adapters.py stacks N tenants' adapters) and every row gathers
    its own pair — one dispatch serves a mixed-tenant batch."""
    dt = cfg.dtype
    lora = lora_layers or {}

    qe = qeinsum_w8a8 if cfg.quant_activations else qeinsum

    def proj(name: str, inp: jnp.ndarray, eq: str, lora_eq: str) -> jnp.ndarray:
        out = qe(eq, inp, lp[name], dt)
        if name in lora:
            if adapter_ids is not None:
                out = out + lora_delta_indexed(
                    inp, lora[name], lora_scale, lora_eq, adapter_ids
                )
            else:
                out = out + lora_delta(inp, lora[name], lora_scale, lora_eq)
        return out

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = proj("wq", h, "bsd,dhk->bshk", "bsr,rhk->bshk")
    kk = proj("wk", h, "bsd,dhk->bshk", "bsr,rhk->bshk")
    vv = proj("wv", h, "bsd,dhk->bshk", "bsr,rhk->bshk")
    q = rope(q, positions, cfg.rope_theta)
    kk = rope(kk, positions, cfg.rope_theta)

    if layer_cache is None:
        attn = _self_attention(q, kk, vv, positions, cfg)
        kv_out = {"k": kk, "v": vv}
    elif block_table is not None:
        from substratus_tpu.ops.kvcache import paged_update_and_read

        kv_out, k_cache, v_cache = paged_update_and_read(
            layer_cache, block_table, positions, kk, vv, dt
        )
        attn = dot_product_attention(
            q, k_cache, v_cache, causal=True, q_positions=positions,
            kv_length=kv_length,
        )
    else:
        from substratus_tpu.ops.decode_attention import update_cache_and_attend

        attn, kv_out = update_cache_and_attend(
            layer_cache, q, kk, vv, positions,
            kv_length=kv_length, impl=cfg.decode_attn_impl,
            chunk_impl=cfg.chunk_attn_impl,
        )

    b, s = x.shape[:2]
    attn_flat = attn.reshape(b, s, -1)
    o = qeinsum("bshk,hkd->bsd", attn, lp["wo"], dt)
    if "wo" in lora:
        if adapter_ids is not None:
            o = o + lora_delta_indexed(
                attn_flat, lora["wo"], lora_scale, "bsr,rd->bsd", adapter_ids
            )
        else:
            o = o + lora_delta(
                attn_flat, lora["wo"], lora_scale, "bsr,rd->bsd"
            )
    x = x + o
    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts > 0:
        y, aux = _moe_ffn(h, lp, cfg, train, lora, lora_scale)
        x = x + y
    else:
        gate = proj("w_gate", h, "bsd,dm->bsm", "bsr,rm->bsm")
        up = proj("w_up", h, "bsd,dm->bsm", "bsr,rm->bsm")
        x = x + proj("w_down", swiglu(gate, up), "bsm,md->bsd", "bsr,rd->bsd")
        aux = jnp.zeros((), jnp.float32)
    return x, kv_out, aux


def forward(
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32
    cfg: LlamaConfig,
    *,
    positions: Optional[jnp.ndarray] = None,  # [B, S] absolute positions
    cache: Optional[Params] = None,  # decode cache from init_cache (dense)
    # or init_paged_cache (pass block_table too)
    block_table: Optional[jnp.ndarray] = None,  # [B, M] page ids: selects
    # the paged cache layout (ops/kvcache.py)
    kv_length: Optional[jnp.ndarray] = None,  # [B] valid cache prefix; use
    # when slots <= position may hold stale data (e.g. resumed caches)
    lora: Optional[Params] = None,  # adapter tree from train.lora.init_lora
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] int32 — lora leaves
    # carry a leading adapter-slot axis and each row gathers its own pair
    # (multi-tenant serving; serve/adapters.py::AdapterStore.device_tree)
    remat: bool = False,  # rematerialize each block (training memory saver)
    train: bool = False,  # MoE: capacity dispatch (train) vs exact (infer)
) -> Tuple[jnp.ndarray, Params]:
    """Returns (logits [B, S, vocab], kv).

    Without cache: training/prefill; kv = fresh entries [L, B, S, KH, hd]
    (a cache fragment the serving engine can insert into a decode cache).
    With cache: decode/continued generation; tokens are written at
    `positions` (per-row) and attention runs over the full cache; kv = the
    updated cache.
    """
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

    x = materialize(params["tok_embed"], cfg.dtype)[tokens]

    lora_scale = lora["scale"] if lora is not None else 1.0

    def body(carry, layer_in):
        x_out, kv, aux = _block(
            carry,
            layer_in["lp"],
            positions,
            cfg,
            layer_in.get("cache"),
            kv_length,
            layer_in.get("lora"),
            lora_scale,
            train,
            block_table,
            adapter_ids,
        )
        return x_out, {"kv": kv, "aux": aux}

    xs: Dict[str, Any] = {"lp": params["layers"]}
    if cache is not None:
        xs["cache"] = cache
    if lora is not None:
        xs["lora"] = lora["layers"]
    if remat:
        body = jax.checkpoint(body)
    x, ys = lax.scan(body, x, xs)

    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum(
            "bsd,vd->bsv", x, materialize(params["tok_embed"], cfg.dtype)
        )
    else:
        logits = (qeinsum_w8a8 if cfg.quant_activations else qeinsum)(
            "bsd,dv->bsv", x, params["lm_head"], cfg.dtype
        )
    kv = ys["kv"]  # stacked over layers; same structure as the cache
    if cfg.n_experts > 0 and cache is None:
        # Per-layer router load-balancing losses (training/prefill only —
        # the decode cache must keep a stable structure for buffer
        # donation); the trainer adds router_aux_weight * mean.
        kv["moe_aux"] = ys["aux"]
    return logits.astype(jnp.float32), kv


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def decode_step(
    params: Params,
    cache: Params,
    tokens: jnp.ndarray,  # [B] current token per row
    positions: jnp.ndarray,  # [B] position to write/attend at
    cfg: LlamaConfig,
) -> Tuple[jnp.ndarray, Params]:
    """One greedy-decode-ready step: logits for the next token + updated
    cache. Cache buffer is donated -> updated in place on device."""
    logits, new_cache = forward(
        params, tokens[:, None], cfg, positions=positions[:, None], cache=cache
    )
    return logits[:, 0, :], new_cache
