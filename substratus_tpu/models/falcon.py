"""Falcon model family (falcon-7b[-instruct], falcon-40b).

BASELINE.json's config list includes falcon-7b-instruct serving and the
falcon-40b finetune (the reference's largest example,
examples/falcon-40b/finetuned-model.yaml). Architectural differences from
Llama, implemented TPU-first in the same stacked-scan style:

  * parallel block: x + attn(ln(x)) + mlp(ln(x)) — one residual add, and on
    7b-style models attention and MLP share a single LayerNorm
    (new_decoder_architecture=False); 40b-style models use separate ln_attn
    / ln_mlp (new_decoder_architecture=True);
  * multi-query (7b: 1 kv head) / grouped-query (40b: 8) attention with
    rotary embeddings;
  * GELU MLP, biasless projections (config.bias=False in released models),
    tied LM head.

Same module interface as models/llama.py / models/opt.py (see
serve/engine.py and models/registry.py).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from substratus_tpu.ops.attention import dot_product_attention
from substratus_tpu.ops.basics import layer_norm, rope, lora_delta

Params = Dict[str, Any]

# train/lora.py adapters attach to the attention projections (wq/wk/wv/wo).
SUPPORTS_LORA = True


@dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    dim: int = 4544
    n_layers: int = 32
    n_heads: int = 71
    n_kv_heads: int = 1
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 2048
    separate_ln: bool = False  # True = 40b-style ln_attn/ln_mlp
    dtype: Any = jnp.bfloat16

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def hidden_dim(self) -> int:
        return 4 * self.dim

    def replace(self, **kw) -> "FalconConfig":
        return dataclasses.replace(self, **kw)


CONFIGS: Dict[str, FalconConfig] = {
    "tiny-falcon": FalconConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=1,
        max_seq_len=128,
    ),
    "tiny-falcon-40b-style": FalconConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        max_seq_len=128, separate_ln=True,
    ),
    "falcon-7b": FalconConfig(),
    "falcon-40b": FalconConfig(
        dim=8192, n_layers=60, n_heads=128, n_kv_heads=8, separate_ln=True
    ),
}


def param_logical_axes(cfg: FalconConfig) -> Params:
    layers = {
        "ln1_scale": ("layers", "embed"),
        "ln1_bias": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "fc1": ("layers", "embed", "mlp"),
        "fc2": ("layers", "mlp", "embed"),
    }
    if cfg.separate_ln:
        layers["ln2_scale"] = ("layers", "embed")
        layers["ln2_bias"] = ("layers", "embed")
    return {
        "tok_embed": ("vocab", "embed"),
        "layers": layers,
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
    }


def init_params(cfg: FalconConfig, key: jax.Array) -> Params:
    hd = cfg.head_size
    L, D, H, KH, M = (
        cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.hidden_dim
    )
    k = iter(jax.random.split(key, 10))

    def dense(key, shape, fan_in):
        return (
            jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
            * (fan_in**-0.5)
        ).astype(cfg.dtype)

    layers = {
        "ln1_scale": jnp.ones((L, D), cfg.dtype),
        "ln1_bias": jnp.zeros((L, D), cfg.dtype),
        "wq": dense(next(k), (L, D, H, hd), D),
        "wk": dense(next(k), (L, D, KH, hd), D),
        "wv": dense(next(k), (L, D, KH, hd), D),
        "wo": dense(next(k), (L, H, hd, D), H * hd),
        "fc1": dense(next(k), (L, D, M), D),
        "fc2": dense(next(k), (L, M, D), M),
    }
    if cfg.separate_ln:
        layers["ln2_scale"] = jnp.ones((L, D), cfg.dtype)
        layers["ln2_bias"] = jnp.zeros((L, D), cfg.dtype)
    return {
        "tok_embed": dense(next(k), (cfg.vocab_size, D), D),
        "layers": layers,
        "final_ln_scale": jnp.ones((D,), cfg.dtype),
        "final_ln_bias": jnp.zeros((D,), cfg.dtype),
    }


def init_cache(
    cfg: FalconConfig, batch: int, max_len: Optional[int] = None, dtype=None
) -> Params:
    """Decode KV cache [L, B, KH, S, head_dim] — per-head sequence-
    contiguous, same convention as llama.init_cache (KH=1 for MQA)."""
    S = max_len or cfg.max_seq_len
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, S, cfg.head_size)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: FalconConfig, quantized: bool = False) -> Params:
    ax = ("layers", "cache_batch", "kv_heads", "cache_seq", "head_dim")
    return {"k": ax, "v": ax}


def _block(x, lp, positions, cfg, layer_cache, kv_length=None,
           lora_layers=None, lora_scale=1.0):
    lora = lora_layers or {}
    h_attn = layer_norm(x, lp["ln1_scale"], lp["ln1_bias"], cfg.norm_eps)
    h_mlp = (
        layer_norm(x, lp["ln2_scale"], lp["ln2_bias"], cfg.norm_eps)
        if cfg.separate_ln
        else h_attn
    )

    def proj(name, eq, lora_eq):
        out = jnp.einsum(eq, h_attn, lp[name])
        if name in lora:
            out = out + lora_delta(h_attn, lora[name], lora_scale, lora_eq)
        return out

    q = proj("wq", "bsd,dhk->bshk", "bsr,rhk->bshk")
    kk = proj("wk", "bsd,dhk->bshk", "bsr,rhk->bshk")
    vv = proj("wv", "bsd,dhk->bshk", "bsr,rhk->bshk")
    q = rope(q, positions, cfg.rope_theta)
    kk = rope(kk, positions, cfg.rope_theta)

    if layer_cache is None:
        attn = dot_product_attention(q, kk, vv, causal=True, q_positions=positions)
        kv_out = {"k": kk, "v": vv}
    else:
        from substratus_tpu.ops.decode_attention import update_cache_and_attend

        attn, kv_out = update_cache_and_attend(
            layer_cache, q, kk, vv, positions, kv_length=kv_length,
        )

    attn_out = jnp.einsum("bshk,hkd->bsd", attn, lp["wo"])
    if "wo" in lora:
        b, s = x.shape[:2]
        attn_out = attn_out + lora_delta(
            attn.reshape(b, s, -1), lora["wo"], lora_scale, "bsr,rd->bsd"
        )
    mlp_out = jnp.einsum(
        "bsm,md->bsd",
        jax.nn.gelu(jnp.einsum("bsd,dm->bsm", h_mlp, lp["fc1"]), approximate=False),
        lp["fc2"],
    )
    # Parallel block: one residual add for both sublayers.
    return x + attn_out + mlp_out, kv_out


def forward(
    params: Params,
    tokens: jnp.ndarray,
    cfg: FalconConfig,
    *,
    positions: Optional[jnp.ndarray] = None,
    cache: Optional[Params] = None,
    kv_length: Optional[jnp.ndarray] = None,  # [B] valid cache prefix
    lora: Optional[Params] = None,  # {"layers": adapters, "scale": s}
    remat: bool = False,
    train: bool = False,
) -> Tuple[jnp.ndarray, Params]:
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

    x = params["tok_embed"][tokens]

    lora_scale = lora["scale"] if lora is not None else 1.0

    def body(carry, layer_in):
        x_out, kv = _block(
            carry, layer_in["lp"], positions, cfg, layer_in.get("cache"),
            kv_length, layer_in.get("lora"), lora_scale,
        )
        return x_out, kv

    xs: Dict[str, Any] = {"lp": params["layers"]}
    if cache is not None:
        xs["cache"] = cache
    if lora is not None:
        xs["lora"] = lora["layers"]
    if remat:
        body = jax.checkpoint(body)
    x, kv = lax.scan(body, x, xs)

    x = layer_norm(
        x, params["final_ln_scale"], params["final_ln_bias"], cfg.norm_eps
    )
    logits = jnp.einsum("bsd,vd->bsv", x, params["tok_embed"])  # tied head
    return logits.astype(jnp.float32), kv


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def decode_step(params, cache, tokens, positions, cfg):
    logits, new_cache = forward(
        params, tokens[:, None], cfg, positions=positions[:, None], cache=cache
    )
    return logits[:, 0, :], new_cache
