"""OPT model family (facebook/opt-125m .. opt-66b).

The reference's CPU smoke model is facebook/opt-125m (test/system.sh,
examples/facebook-opt-125m); this makes it a first-class citizen rather
than a stand-in. Same TPU-first structure as models/llama.py — stacked
layers scanned with lax.scan, logical-axis annotations, KV-cache decode —
with the OPT architectural differences: learned positional embeddings
(offset by 2, an OPT quirk), LayerNorm with bias, biased projections, ReLU
MLP, tied LM head.

Implements the same module interface the serving engine consumes:
CONFIGS / init_params / param_logical_axes / init_cache / forward /
decode_step (see serve/engine.py).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from substratus_tpu.ops.attention import dot_product_attention
from substratus_tpu.ops.basics import layer_norm, lora_delta

Params = Dict[str, Any]

POS_OFFSET = 2  # OPT reserves the first two position-embedding rows.

# train/lora.py adapters attach to the attention projections (wq/wk/wv/wo).
SUPPORTS_LORA = True


@dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    max_seq_len: int = 2048
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    # The engine treats kv heads uniformly; OPT is MHA.
    @property
    def n_kv_heads(self) -> int:
        return self.n_heads

    def replace(self, **kw) -> "OPTConfig":
        return dataclasses.replace(self, **kw)


CONFIGS: Dict[str, OPTConfig] = {
    "tiny-opt": OPTConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, hidden_dim=128,
        max_seq_len=128,
    ),
    "opt-125m": OPTConfig(),
    "opt-1.3b": OPTConfig(dim=2048, n_layers=24, n_heads=32, hidden_dim=8192),
    "opt-6.7b": OPTConfig(dim=4096, n_layers=32, n_heads=32, hidden_dim=16384),
}


def param_logical_axes(cfg: OPTConfig) -> Params:
    return {
        "tok_embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "layers": {
            "ln1_scale": ("layers", "embed"),
            "ln1_bias": ("layers", "embed"),
            "wq": ("layers", "embed", "heads", "head_dim"),
            "bq": ("layers", "heads", "head_dim"),
            "wk": ("layers", "embed", "heads", "head_dim"),
            "bk": ("layers", "heads", "head_dim"),
            "wv": ("layers", "embed", "heads", "head_dim"),
            "bv": ("layers", "heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "bo": ("layers", "embed"),
            "ln2_scale": ("layers", "embed"),
            "ln2_bias": ("layers", "embed"),
            "fc1": ("layers", "embed", "mlp"),
            "fc1_b": ("layers", "mlp"),
            "fc2": ("layers", "mlp", "embed"),
            "fc2_b": ("layers", "embed"),
        },
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
    }


def init_params(cfg: OPTConfig, key: jax.Array) -> Params:
    hd = cfg.head_size
    L, D, H, M = cfg.n_layers, cfg.dim, cfg.n_heads, cfg.hidden_dim
    k = iter(jax.random.split(key, 12))

    def dense(key, shape, fan_in):
        return (
            jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
            * (fan_in**-0.5)
        ).astype(cfg.dtype)

    return {
        "tok_embed": dense(next(k), (cfg.vocab_size, D), D),
        "pos_embed": dense(next(k), (cfg.max_seq_len + POS_OFFSET, D), D),
        "layers": {
            "ln1_scale": jnp.ones((L, D), cfg.dtype),
            "ln1_bias": jnp.zeros((L, D), cfg.dtype),
            "wq": dense(next(k), (L, D, H, hd), D),
            "bq": jnp.zeros((L, H, hd), cfg.dtype),
            "wk": dense(next(k), (L, D, H, hd), D),
            "bk": jnp.zeros((L, H, hd), cfg.dtype),
            "wv": dense(next(k), (L, D, H, hd), D),
            "bv": jnp.zeros((L, H, hd), cfg.dtype),
            "wo": dense(next(k), (L, H, hd, D), D),
            "bo": jnp.zeros((L, D), cfg.dtype),
            "ln2_scale": jnp.ones((L, D), cfg.dtype),
            "ln2_bias": jnp.zeros((L, D), cfg.dtype),
            "fc1": dense(next(k), (L, D, M), D),
            "fc1_b": jnp.zeros((L, M), cfg.dtype),
            "fc2": dense(next(k), (L, M, D), M),
            "fc2_b": jnp.zeros((L, D), cfg.dtype),
        },
        "final_ln_scale": jnp.ones((D,), cfg.dtype),
        "final_ln_bias": jnp.zeros((D,), cfg.dtype),
    }


def init_cache(
    cfg: OPTConfig, batch: int, max_len: Optional[int] = None, dtype=None
) -> Params:
    """Decode KV cache [L, B, KH, S, head_dim] — per-head sequence-
    contiguous, same convention as llama.init_cache."""
    S = max_len or cfg.max_seq_len
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, batch, cfg.n_heads, S, cfg.head_size)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: OPTConfig, quantized: bool = False) -> Params:
    ax = ("layers", "cache_batch", "kv_heads", "cache_seq", "head_dim")
    return {"k": ax, "v": ax}


def _block(x, lp, positions, cfg, layer_cache, kv_length=None,
           lora_layers=None, lora_scale=1.0):
    lora = lora_layers or {}
    h = layer_norm(x, lp["ln1_scale"], lp["ln1_bias"], cfg.norm_eps)

    def proj(name, bias, eq, lora_eq):
        out = jnp.einsum(eq, h, lp[name]) + lp[bias]
        if name in lora:
            out = out + lora_delta(h, lora[name], lora_scale, lora_eq)
        return out

    q = proj("wq", "bq", "bsd,dhk->bshk", "bsr,rhk->bshk")
    kk = proj("wk", "bk", "bsd,dhk->bshk", "bsr,rhk->bshk")
    vv = proj("wv", "bv", "bsd,dhk->bshk", "bsr,rhk->bshk")

    if layer_cache is None:
        attn = dot_product_attention(q, kk, vv, causal=True, q_positions=positions)
        kv_out = (kk, vv)
    else:
        from substratus_tpu.ops.decode_attention import update_cache_and_attend

        k_cache, v_cache = layer_cache  # [B, KH, S_cache, D]
        attn, kv = update_cache_and_attend(
            {"k": k_cache, "v": v_cache}, q, kk, vv, positions,
            kv_length=kv_length,
        )
        kv_out = (kv["k"], kv["v"])

    o = jnp.einsum("bshk,hkd->bsd", attn, lp["wo"]) + lp["bo"]
    if "wo" in lora:
        b, s = x.shape[:2]
        o = o + lora_delta(
            attn.reshape(b, s, -1), lora["wo"], lora_scale, "bsr,rd->bsd"
        )
    x = x + o
    h = layer_norm(x, lp["ln2_scale"], lp["ln2_bias"], cfg.norm_eps)
    h = jax.nn.relu(jnp.einsum("bsd,dm->bsm", h, lp["fc1"]) + lp["fc1_b"])
    x = x + jnp.einsum("bsm,md->bsd", h, lp["fc2"]) + lp["fc2_b"]
    return x, kv_out


def forward(
    params: Params,
    tokens: jnp.ndarray,
    cfg: OPTConfig,
    *,
    positions: Optional[jnp.ndarray] = None,
    cache: Optional[Params] = None,
    kv_length: Optional[jnp.ndarray] = None,  # [B] valid cache prefix
    lora: Optional[Params] = None,  # {"layers": adapters, "scale": s}
    remat: bool = False,
    train: bool = False,
) -> Tuple[jnp.ndarray, Params]:
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

    x = params["tok_embed"][tokens] + params["pos_embed"][positions + POS_OFFSET]

    lora_scale = lora["scale"] if lora is not None else 1.0

    def body(carry, layer_in):
        lp = layer_in["lp"]
        x_out, kv = _block(
            carry, lp, positions, cfg, layer_in.get("cache"), kv_length,
            layer_in.get("lora"), lora_scale,
        )
        return x_out, kv

    xs: Dict[str, Any] = {"lp": params["layers"]}
    if cache is not None:
        xs["cache"] = (cache["k"], cache["v"])
    if lora is not None:
        xs["lora"] = lora["layers"]
    if remat:
        body = jax.checkpoint(body)
    x, (ks, vs) = lax.scan(body, x, xs)

    x = layer_norm(
        x, params["final_ln_scale"], params["final_ln_bias"], cfg.norm_eps
    )
    logits = jnp.einsum("bsd,vd->bsv", x, params["tok_embed"])  # tied head
    return logits.astype(jnp.float32), {"k": ks, "v": vs}


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def decode_step(params, cache, tokens, positions, cfg):
    logits, new_cache = forward(
        params, tokens[:, None], cfg, positions=positions[:, None], cache=cache
    )
    return logits[:, 0, :], new_cache
