"""Model-family registry: the single dispatch point for multi-architecture
support.

Every family is a module implementing the engine/trainer protocol
(CONFIGS / init_params / param_logical_axes / init_cache /
cache_logical_axes / forward / decode_step). Adding a family means one
entry here; serve/load/checkpoint code looks up, never type-switches.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

from substratus_tpu.models import falcon, llama, opt

FAMILIES = {
    "llama": llama,  # Llama 2/3, Mistral, Mixtral (MoE), TinyLlama
    "opt": opt,  # facebook/opt-*
    "falcon": falcon,  # falcon-7b[-instruct], falcon-40b
}

# transformers `model_type` -> family name (HF checkpoint dispatch).
HF_MODEL_TYPES = {
    "llama": "llama",
    "mistral": "llama",
    "mixtral": "llama",
    "opt": "opt",
    "falcon": "falcon",
}

_CONFIG_CLASS_TO_FAMILY = {
    llama.LlamaConfig: "llama",
    opt.OPTConfig: "opt",
    falcon.FalconConfig: "falcon",
}


def family_of(cfg: Any) -> str:
    for cls, name in _CONFIG_CLASS_TO_FAMILY.items():
        if isinstance(cfg, cls):
            return name
    raise TypeError(f"unknown model config type {type(cfg)!r}")


def module_of(cfg: Any):
    return FAMILIES[family_of(cfg)]


def config_class(name: str):
    return {v: k for k, v in _CONFIG_CLASS_TO_FAMILY.items()}[name]


def module_for(name: str):
    if name not in FAMILIES:
        raise KeyError(f"unknown model family {name!r} (known: {sorted(FAMILIES)})")
    return FAMILIES[name]


def find_named_config(name: str) -> Tuple[Any, Any]:
    """Named smoke/test config -> (family_module, config)."""
    for fam in FAMILIES.values():
        if name in fam.CONFIGS:
            return fam, fam.CONFIGS[name]
    known = sorted(
        cfg for fam in FAMILIES.values() for cfg in fam.CONFIGS
    )
    raise KeyError(f"unknown model config {name!r} (known: {known})")
