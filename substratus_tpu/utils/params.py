"""params.json hygiene for the container contract.

Twice this codebase accepted a documented params key and silently ignored it
(grad_accum_steps, max_prefill_len). Entrypoints now declare the keys they
consume and warn loudly about anything else — a typo'd knob should be a
visible warning, never a silent no-op.
"""
from __future__ import annotations

import sys
from typing import Dict, Iterable


def warn_unknown_keys(
    params: Dict, known: Iterable[str], where: str
) -> None:
    unknown = sorted(set(params) - set(known))
    if unknown:
        print(
            f"warning: {where} ignores unrecognized params.json keys "
            f"{unknown} (typo? known keys: {sorted(set(known))})",
            file=sys.stderr,
            flush=True,
        )
