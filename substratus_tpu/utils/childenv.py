"""Shared child-process construction for the hardware harness paths.

ROADMAP item 5 background: ``bench.py``'s decode probe hung at backend
init for five straight rounds while ``__graft_entry__``'s MULTICHIP
dryrun ran green in the SAME container — which kills the wedged-tunnel
theory and localizes the bug to the delta between the two harnesses:
how each builds its child's environment (``JAX_PLATFORMS`` handling,
``PYTHONPATH`` / sitecustomize plugin exposure, the XLA host-device
flag) and how each watches the child (timeout classification). This
module IS that delta, deleted: both paths construct children through
``child_env``/``run_child``, and tests/test_harness_env.py pins their
equivalence so the next hardware session debugs ONE harness path, not
two that drifted.

Import-light on purpose: no jax, no substratus imports — safe to load
under a wedged device tunnel (the exact situation it exists for).
"""
from __future__ import annotations

import os
import re
import subprocess
import time
from dataclasses import dataclass
from typing import Mapping, Optional, Sequence


def merge_host_device_flag(env: dict, n_devices: int) -> None:
    """Set ``--xla_force_host_platform_device_count=n`` in
    ``env['XLA_FLAGS']``, REWRITING any existing count (a pre-set wrong
    count must not win), preserving every other flag."""
    flags = env.get("XLA_FLAGS", "")
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", flags
    ).strip()
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()


def child_env(
    platform: Optional[str] = None,
    host_devices: Optional[int] = None,
    clean_pythonpath: bool = False,
    base: Optional[Mapping[str, str]] = None,
) -> dict:
    """The one env-construction rule for harness children.

    ``platform=None`` inherits the caller's ``JAX_PLATFORMS`` untouched
    (the bench probe's chip path: the child must see the same backend
    the capture targets); a string pins it (the dryrun pins ``"cpu"``).
    ``host_devices`` merges the XLA virtual-device flag.
    ``clean_pythonpath=True`` clears ``PYTHONPATH`` so a
    sitecustomize-injected PJRT plugin never loads in the child (the
    dryrun's sanitization rule)."""
    env = dict(os.environ if base is None else base)
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    if host_devices is not None:
        merge_host_device_flag(env, host_devices)
    if clean_pythonpath:
        env["PYTHONPATH"] = ""
    return env


@dataclass
class ChildResult:
    """One watched child run. ``hung=True`` means the hard timeout
    fired and the child was killed — the wedged-tunnel signature both
    harnesses must classify, never propagate."""

    rc: Optional[int]
    stdout: str
    stderr: str
    elapsed_s: float
    hung: bool = False

    @property
    def ok(self) -> bool:
        return not self.hung and self.rc == 0


def run_child(
    argv: Sequence[str],
    timeout_s: float,
    env: Optional[Mapping[str, str]] = None,
    cwd: Optional[str] = None,
) -> ChildResult:
    """THE watchdog: run a child with captured output and a hard
    wall-clock limit. A timeout returns ``hung=True`` instead of
    raising (``subprocess.run`` kills the process group on expiry), so
    callers branch on one classification instead of re-implementing
    TimeoutExpired handling three subtly different ways."""
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            list(argv), capture_output=True, text=True,
            timeout=timeout_s, env=dict(env) if env is not None else None,
            cwd=cwd,
        )
    except subprocess.TimeoutExpired as e:
        return ChildResult(
            rc=None,
            stdout=(e.stdout or b"").decode(errors="replace")
            if isinstance(e.stdout, bytes) else (e.stdout or ""),
            stderr=(e.stderr or b"").decode(errors="replace")
            if isinstance(e.stderr, bytes) else (e.stderr or ""),
            elapsed_s=time.monotonic() - t0,
            hung=True,
        )
    return ChildResult(
        rc=proc.returncode, stdout=proc.stdout, stderr=proc.stderr,
        elapsed_s=time.monotonic() - t0,
    )
