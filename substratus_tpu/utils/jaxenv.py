"""JAX backend selection hardening for container entrypoints.

Some environments inject out-of-tree PJRT plugins via sitecustomize that
intercept backend initialization even when `JAX_PLATFORMS=cpu` is set; if
the plugin's device tunnel is unreachable, every jax call hangs. Entrypoints
call `honor_requested_platform()` first: when the operator/user explicitly
asked for cpu (or tpu), any other registered plugin backend is dropped so
the request is actually honored — a hung accelerator tunnel must fail over
loudly, not hang a serving pod's readiness forever.
"""
from __future__ import annotations

import os

_KNOWN = {"cpu", "tpu", "gpu", "cuda", "rocm"}


def honor_requested_platform() -> None:
    requested = os.environ.get("JAX_PLATFORMS", "")
    if not requested:
        return
    wanted = {p.strip() for p in requested.split(",") if p.strip()}
    if not wanted or not wanted.issubset(_KNOWN):
        return  # a plugin platform was requested explicitly; leave it alone
    import jax
    from jax._src import xla_bridge as xb

    for name in list(xb._backend_factories):
        if name not in wanted and name not in _KNOWN:
            xb._backend_factories.pop(name, None)
    jax.config.update("jax_platforms", ",".join(sorted(wanted)))
