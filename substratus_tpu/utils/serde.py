"""Dataclass <-> Kubernetes-style JSON (camelCase, omit-empty) conversion.

The reference gets this from Go struct tags + controller-gen
(api/v1/*_types.go); here a single generic converter keeps the API types
declarative: snake_case dataclass fields serialize as camelCase, None/empty
values are omitted (k8s omitempty semantics), nested dataclasses, lists and
dicts recurse.
"""
from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, Type, TypeVar, get_args, get_origin

T = TypeVar("T")


def camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def to_dict(obj: Any) -> Any:
    """Dataclass tree -> plain JSON-able dict (camelCase, omit empty)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: Dict[str, Any] = {}
        for f in dataclasses.fields(obj):
            v = to_dict(getattr(obj, f.name))
            if v is None or v == {} or v == []:
                continue
            out[camel(f.name)] = v
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items() if v is not None}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def _resolve(tp: Any) -> Any:
    """Unwrap Optional[X] -> X."""
    if get_origin(tp) is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def from_dict(cls: Type[T], data: Optional[Dict[str, Any]]) -> Optional[T]:
    """Inverse of to_dict. Unknown keys are ignored (k8s forward compat)."""
    if data is None:
        return None
    if not dataclasses.is_dataclass(cls):
        return data  # type: ignore[return-value]
    hints = typing.get_type_hints(cls)
    kwargs: Dict[str, Any] = {}
    by_camel = {camel(f.name): f for f in dataclasses.fields(cls)}
    for key, value in data.items():
        f = by_camel.get(key)
        if f is None:
            continue
        tp = _resolve(hints[f.name])
        origin = get_origin(tp)
        if dataclasses.is_dataclass(tp) and isinstance(value, dict):
            kwargs[f.name] = from_dict(tp, value)
        elif origin in (list, typing.List) and value is not None:
            (item_tp,) = get_args(tp) or (Any,)
            item_tp = _resolve(item_tp)
            if dataclasses.is_dataclass(item_tp):
                kwargs[f.name] = [from_dict(item_tp, v) for v in value]
            else:
                kwargs[f.name] = list(value)
        else:
            kwargs[f.name] = value
    return cls(**kwargs)  # type: ignore[call-arg]
