"""Version-compat accessors for jax APIs that moved between releases.

The repo targets current jax (`jax.shard_map`, `jax.set_mesh`), but CI
images and operator laptops lag; these shims translate to the older
spellings instead of AttributeError-ing whole subsystems. Each shim
prefers the new API when present, so on current jax they are free.
"""
from __future__ import annotations

import jax


def ambient_mesh(mesh):
    """Ambient-mesh context manager: `jax.set_mesh` where it exists,
    else the Mesh object's own context manager (the pre-set_mesh
    spelling of the same thing). Code that opens a shard_map inside a
    jitted step needs the mesh ambient either way."""
    set_mesh = getattr(jax, "set_mesh", None)
    return set_mesh(mesh) if set_mesh is not None else mesh


def pcast(x, axes, to="varying"):
    """`jax.lax.pcast` (varying-axes typing, new jax) or identity: legacy
    shard_map has no varying-type system — every value inside the manual
    region is already device-varying, so the cast is a no-op there."""
    fn = getattr(jax.lax, "pcast", None)
    return fn(x, axes, to=to) if fn is not None else x


def shard_map(f, *, in_specs, out_specs, axis_names=None, mesh=None):
    """`jax.shard_map` (new: keyword-only, ambient mesh, `axis_names`
    picking the manual axes) with a fallback onto the legacy
    `jax.experimental.shard_map.shard_map` (positional mesh, every axis
    manual unless listed in `auto`)."""
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = dict(in_specs=in_specs, out_specs=out_specs)
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if mesh is not None:
            kwargs["mesh"] = mesh
        return native(f, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy

    if mesh is None:
        # The callers enter the mesh via trainer.ambient_mesh (the Mesh
        # context manager on legacy jax), which is exactly where legacy
        # thread resources record it.
        from jax._src import mesh as mesh_lib

        mesh = mesh_lib.thread_resources.env.physical_mesh
        if mesh.empty:
            raise ValueError(
                "shard_map needs an ambient mesh (with ambient_mesh(m):) "
                "or an explicit mesh= on this jax version"
            )
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return legacy(
        f, mesh, in_specs=in_specs, out_specs=out_specs,
        auto=auto, check_rep=False,
    )
