"""Shared CR spec/status types (reference: api/v1/common_types.go:8-111).

TPU-first departure: `Resources` gains `tpu: {type, chips, topology}` — the
north-star API change — alongside cpu/memory/disk and a gpu field kept for
capability parity. TPU types/topologies are validated against the catalog in
resources/accelerators.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class BuildGit:
    """Build the container image from a git repo (ref: common_types.go
    Build.Git — tag OR branch, pulled at build time only)."""

    url: str = ""
    branch: Optional[str] = None
    tag: Optional[str] = None
    path: Optional[str] = None  # subdir containing Dockerfile


@dataclass
class BuildUpload:
    """Build from a client-uploaded tarball: the client sets md5 + requestID,
    the controller answers with a signed URL in status.buildUpload."""

    md5_checksum: str = ""
    request_id: str = ""


@dataclass
class Build:
    git: Optional[BuildGit] = None
    upload: Optional[BuildUpload] = None


@dataclass
class UploadStatus:
    """Signed-URL handshake state (ref: common_types.go UploadStatus)."""

    signed_url: Optional[str] = None
    request_id: Optional[str] = None
    expiration: Optional[str] = None
    stored_md5_checksum: Optional[str] = None


@dataclass
class ObjectRef:
    name: str = ""
    namespace: Optional[str] = None


@dataclass
class GPUResources:
    """Kept for reference capability parity (a100/t4/l4 enum in
    common_types.go:96-111); clusters targeted by this framework are
    TPU-only but the API does not forbid GPU pools."""

    type: str = ""
    count: int = 0


@dataclass
class TPUResources:
    """The TPU ask. `type` is a generation (v4, v5e, v5p, v6e), `chips` the
    total chip count, `topology` an optional explicit slice topology like
    "4x4" / "2x2x2"; when omitted it is derived from chips (see
    resources/accelerators.py)."""

    type: str = "v5e"
    chips: int = 1
    topology: Optional[str] = None


@dataclass
class Resources:
    cpu: Optional[int] = None
    disk: Optional[int] = None  # Gi
    memory: Optional[int] = None  # Gi
    gpu: Optional[GPUResources] = None
    tpu: Optional[TPUResources] = None


@dataclass
class ArtifactsStatus:
    url: Optional[str] = None


@dataclass
class Params:
    """CR params are an arbitrary JSON object surfaced to the container as
    /content/params.json + PARAM_* env (docs/design.md:271-281)."""

    values: Dict[str, object] = field(default_factory=dict)
