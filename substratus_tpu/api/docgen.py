"""API reference generation from the dataclass types (reference: docs/api
is the generated field reference for the CRDs; here the same artifact
derives from the dataclasses that already generate the CRD schemas —
one source of truth for apiserver validation, client serde, and docs).

    python -m substratus_tpu.api.docgen > docs/api.md    (make api-docs)
"""
from __future__ import annotations

import dataclasses
import typing
from typing import Any, get_args, get_origin

from substratus_tpu.api import types as T
from substratus_tpu.utils.serde import camel


def _type_name(tp: Any) -> str:
    origin = get_origin(tp)
    if origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        return _type_name(args[0])
    if origin in (list, typing.List):
        (item,) = get_args(tp) or (str,)
        return f"[]{_type_name(item)}"
    if origin in (dict, typing.Dict):
        kt, vt = get_args(tp) or (str, str)
        return f"map[{_type_name(kt)}]{_type_name(vt)}"
    if dataclasses.is_dataclass(tp):
        return tp.__name__
    return getattr(tp, "__name__", str(tp))


def _doc_first_line(tp: Any) -> str:
    doc = (tp.__doc__ or "").strip().splitlines()
    if not doc or doc[0].startswith(f"{tp.__name__}("):
        return ""  # dataclass auto-docstring, not documentation
    # first PARAGRAPH (up to the blank line) — wrapped sentences must not
    # ship truncated mid-clause
    para = []
    for line in doc:
        if not line.strip():
            break
        para.append(line.strip())
    return " ".join(para)


def _walk(tp: Any, seen: dict) -> None:
    """Collect every dataclass reachable from tp, in reference order."""
    origin = get_origin(tp)
    if origin is typing.Union:
        for a in get_args(tp):
            if a is not type(None):
                _walk(a, seen)
        return
    if origin in (list, typing.List, dict, typing.Dict):
        for a in get_args(tp):
            _walk(a, seen)
        return
    if dataclasses.is_dataclass(tp) and tp.__name__ not in seen:
        seen[tp.__name__] = tp
        hints = typing.get_type_hints(tp)
        for f in dataclasses.fields(tp):
            _walk(hints[f.name], seen)


def _render_table(tp: Any) -> str:
    hints = typing.get_type_hints(tp)
    rows = ["| Field | Type | Default |", "|---|---|---|"]
    for f in dataclasses.fields(tp):
        if f.default is not dataclasses.MISSING:
            default = repr(f.default)
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
            default = repr(f.default_factory())
        else:
            default = ""
        rows.append(
            f"| `{camel(f.name)}` | `{_type_name(hints[f.name])}` |"
            f" `{default}` |"
        )
    return "\n".join(rows)


def render() -> str:
    out = [
        "# API reference",
        "",
        "Generated from the dataclass API types (`make api-docs` — do not",
        "edit by hand). The same types generate the CRD schemas",
        "(`make manifests`), so this document, the apiserver's validation,",
        "and the client serde cannot drift apart.",
        "",
        f"All kinds are `apiVersion: {T.API_VERSION}`, namespaced, with a",
        "status subresource and standard `metadata`.",
        "",
    ]
    shared: dict = {}
    for kind in T.KINDS:
        # the kind class IS the source of truth for its spec type — a
        # fifth kind added to T.KINDS shows up here with no second map
        spec = type(T.KINDS[kind]().spec)
        out += [f"## {kind}", ""]
        doc = _doc_first_line(spec)
        if doc:
            out += [doc, ""]
        out += [f"### {kind} spec", "", _render_table(spec), ""]
        # nested types collect ONCE into a shared section — rendering
        # Build/Resources per kind would quadruple the doc and collide
        # the markdown anchors
        hints = typing.get_type_hints(spec)
        for f in dataclasses.fields(spec):
            _walk(hints[f.name], shared)
    out += ["## Common types", "",
            "Referenced from the spec tables above.", ""]
    for name, tp in shared.items():
        out += [f"### {name}", ""]
        d = _doc_first_line(tp)
        if d:
            out += [d, ""]
        out += [_render_table(tp), ""]
    out += ["## Common status", ""]
    status_types: dict = {}
    _walk(T.CommonStatus, status_types)
    for name, tp in status_types.items():
        out += [f"### {name}", ""]
        d = _doc_first_line(tp)
        if d:
            out += [d, ""]
        out += [_render_table(tp), ""]
    return "\n".join(out) + "\n"


if __name__ == "__main__":
    print(render(), end="")
