from substratus_tpu.api.common import (
    ArtifactsStatus,
    Build,
    BuildGit,
    BuildUpload,
    ObjectRef,
    Resources,
    TPUResources,
    UploadStatus,
    GPUResources,
)
from substratus_tpu.api.conditions import (
    CONDITION_BUILT,
    CONDITION_COMPLETE,
    CONDITION_SERVING,
    CONDITION_UPLOADED,
    Condition,
)
from substratus_tpu.api.types import (
    GROUP,
    VERSION,
    Dataset,
    Model,
    Notebook,
    Server,
    KINDS,
    new_object,
)

__all__ = [
    "ArtifactsStatus", "Build", "BuildGit", "BuildUpload", "ObjectRef",
    "Resources", "TPUResources", "GPUResources", "UploadStatus",
    "Condition", "CONDITION_BUILT", "CONDITION_COMPLETE", "CONDITION_SERVING",
    "CONDITION_UPLOADED",
    "GROUP", "VERSION", "Dataset", "Model", "Notebook", "Server", "KINDS",
    "new_object",
]
