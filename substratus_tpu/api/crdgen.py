"""CRD manifest generation from the dataclass API types.

The reference generates config/crd/bases via controller-gen struct tags;
here the same artifact is derived from the dataclasses themselves:

    python -m substratus_tpu.api.crdgen > config/crd/substratus-crds.yaml
"""
from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, get_args, get_origin

import yaml

from substratus_tpu.api import types as T
from substratus_tpu.utils.serde import camel

_SCALARS = {
    str: {"type": "string"},
    int: {"type": "integer"},
    float: {"type": "number"},
    bool: {"type": "boolean"},
}


def _schema(tp: Any) -> Dict[str, Any]:
    origin = get_origin(tp)
    if origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        return _schema(args[0])
    if tp in _SCALARS:
        return dict(_SCALARS[tp])
    if origin in (list, typing.List):
        (item,) = get_args(tp) or (str,)
        return {"type": "array", "items": _schema(item)}
    if origin in (dict, typing.Dict):
        return {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    if dataclasses.is_dataclass(tp):
        props = {}
        hints = typing.get_type_hints(tp)
        for f in dataclasses.fields(tp):
            props[camel(f.name)] = _schema(hints[f.name])
        return {"type": "object", "properties": props}
    return {"x-kubernetes-preserve-unknown-fields": True, "type": "object"}


def crd_for(kind: str) -> Dict[str, Any]:
    # the kind class carries its spec type — single source, no side map
    spec_cls = type(T.KINDS[kind]().spec)
    plural = T.PLURALS[kind]
    status_schema = _schema(T.CommonStatus)
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{T.GROUP}"},
        "spec": {
            "group": T.GROUP,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": kind.lower(),
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": T.VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": [
                        {
                            "name": "Ready",
                            "type": "boolean",
                            "jsonPath": ".status.ready",
                        }
                    ],
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "spec": _schema(spec_cls),
                                "status": status_schema,
                            },
                        }
                    },
                }
            ],
        },
    }


def render_all() -> str:
    docs = [crd_for(kind) for kind in T.KINDS]
    return yaml.safe_dump_all(docs, sort_keys=False)


if __name__ == "__main__":
    print(render_all())
