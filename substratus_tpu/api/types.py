"""The four CRDs: Dataset, Model, Notebook, Server.

Reference: api/v1/{dataset,model,notebook,server}_types.go. Same capability
surface — command/image/build/resources/params specs, ready+conditions+
artifacts status, cross-CR refs (Model->base Model/Dataset, Notebook->Model/
Dataset, Server->Model) — expressed as Python dataclasses that serialize to
the exact CR JSON shape (utils/serde.py).
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Type

from substratus_tpu.api.common import (
    ArtifactsStatus,
    Build,
    ObjectRef,
    Resources,
    UploadStatus,
)
from substratus_tpu.api.conditions import Condition
from substratus_tpu.utils.serde import from_dict, to_dict

GROUP = "substratus.ai"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"


@dataclass
class Metadata:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    generation: int = 1
    resource_version: str = "0"
    uid: str = ""
    owner_references: List[Dict[str, Any]] = field(default_factory=list)
    creation_timestamp: Optional[str] = None
    deletion_timestamp: Optional[str] = None


@dataclass
class CommonStatus:
    ready: bool = False
    conditions: List[Condition] = field(default_factory=list)
    artifacts: Optional[ArtifactsStatus] = None
    build_upload: Optional[UploadStatus] = None


@dataclass
class DatasetSpec:
    """Data-loading job spec (ref: dataset_types.go:10-28)."""

    command: List[str] = field(default_factory=list)
    image: Optional[str] = None
    build: Optional[Build] = None
    resources: Optional[Resources] = None
    env: Dict[str, str] = field(default_factory=dict)
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelSpec:
    """Model import/train spec (ref: model_types.go:10-36): `model` is the
    base-model ref (finetune), `dataset` the training-data ref."""

    command: List[str] = field(default_factory=list)
    image: Optional[str] = None
    build: Optional[Build] = None
    resources: Optional[Resources] = None
    env: Dict[str, str] = field(default_factory=dict)
    params: Dict[str, Any] = field(default_factory=dict)
    model: Optional[ObjectRef] = None
    dataset: Optional[ObjectRef] = None


@dataclass
class NotebookSpec:
    """Jupyter dev environment (ref: notebook_types.go:10-38)."""

    command: List[str] = field(default_factory=list)
    image: Optional[str] = None
    build: Optional[Build] = None
    resources: Optional[Resources] = None
    env: Dict[str, str] = field(default_factory=dict)
    params: Dict[str, Any] = field(default_factory=dict)
    model: Optional[ObjectRef] = None
    dataset: Optional[ObjectRef] = None
    suspend: bool = False


@dataclass
class ServerSpec:
    """Inference server (ref: server_types.go:10-31): `model` is
    required. `dataset` is only read by the batch-generation flavor
    (`params.batchGenerate`, docs/batch-generation.md): the referenced
    Dataset artifact mounts RO at /content/data and holds the prompt
    manifest."""

    command: List[str] = field(default_factory=list)
    image: Optional[str] = None
    build: Optional[Build] = None
    resources: Optional[Resources] = None
    env: Dict[str, str] = field(default_factory=dict)
    params: Dict[str, Any] = field(default_factory=dict)
    model: Optional[ObjectRef] = None
    dataset: Optional[ObjectRef] = None


def _object_class(kind: str, spec_cls: Type) -> Type:
    @dataclass
    class Obj:
        metadata: Metadata = field(default_factory=Metadata)
        spec: spec_cls = field(default_factory=spec_cls)  # type: ignore[valid-type]
        status: CommonStatus = field(default_factory=CommonStatus)

        KIND = kind

        @property
        def name(self) -> str:
            return self.metadata.name

        @property
        def namespace(self) -> str:
            return self.metadata.namespace

        def to_dict(self) -> Dict[str, Any]:
            d = {
                "apiVersion": API_VERSION,
                "kind": kind,
                "metadata": to_dict(self.metadata),
                "spec": to_dict(self.spec),
            }
            status = to_dict(self.status)
            # ready:false still matters; serde omits falsy, so force it.
            status["ready"] = self.status.ready
            d["status"] = status
            return d

        @classmethod
        def from_dict(cls, data: Dict[str, Any]) -> "Obj":
            obj = cls()
            obj.metadata = from_dict(Metadata, data.get("metadata") or {}) or Metadata()
            obj.spec = from_dict(spec_cls, data.get("spec") or {}) or spec_cls()
            obj.status = (
                from_dict(CommonStatus, data.get("status") or {}) or CommonStatus()
            )
            return obj

        def deepcopy(self) -> "Obj":
            return copy.deepcopy(self)

    Obj.__name__ = kind
    Obj.__qualname__ = kind
    return Obj


Dataset = _object_class("Dataset", DatasetSpec)
Model = _object_class("Model", ModelSpec)
Notebook = _object_class("Notebook", NotebookSpec)
Server = _object_class("Server", ServerSpec)

KINDS: Dict[str, Type] = {
    "Dataset": Dataset,
    "Model": Model,
    "Notebook": Notebook,
    "Server": Server,
}

# plural <-> kind mapping for REST paths / CLI
PLURALS = {
    "Dataset": "datasets",
    "Model": "models",
    "Notebook": "notebooks",
    "Server": "servers",
}
KIND_OF_PLURAL = {v: k for k, v in PLURALS.items()}


def new_object(kind: str, name: str, namespace: str = "default"):
    obj = KINDS[kind]()
    obj.metadata.name = name
    obj.metadata.namespace = namespace
    return obj


def object_from_dict(data: Dict[str, Any]):
    kind = data.get("kind")
    if kind not in KINDS:
        raise ValueError(f"unknown kind {kind!r}")
    return KINDS[kind].from_dict(data)
